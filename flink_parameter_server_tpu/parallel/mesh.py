"""Device-mesh construction for the PS framework.

The reference's two parallelism knobs (SURVEY.md §2 "Parallelism
strategies") map onto named mesh axes:

  * ``workerParallelism``  → the ``dp`` axis: data batches are sharded
    across it, worker-local state is partitioned along it.
  * ``psParallelism``      → the ``ps`` axis: the parameter table is
    row-sharded across it.

A Flink job picks the two independently; here they share one physical mesh
(``dp × ps``) so pull/push collectives ride ICI.  Multi-host scale-out: the
same named axes span hosts via ``jax.distributed`` — shardings are laid out
so the ``ps`` axis stays within a slice (ICI) and only the data-ingestion
edge crosses DCN.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


DP_AXIS = "dp"
PS_AXIS = "ps"


def make_mesh(
    worker_parallelism: Optional[int] = None,
    ps_parallelism: Optional[int] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Tuple[str, str] = (DP_AXIS, PS_AXIS),
) -> Mesh:
    """Build a ``dp × ps`` mesh over the available devices.

    Defaults: use every device; if only one of the two parallelism degrees
    is given the other absorbs the remaining devices; if neither is given
    all devices go to ``dp`` (pure data parallelism, params replicated).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if worker_parallelism is None and ps_parallelism is None:
        worker_parallelism, ps_parallelism = n, 1
    elif worker_parallelism is None:
        assert n % ps_parallelism == 0, (n, ps_parallelism)
        worker_parallelism = n // ps_parallelism
    elif ps_parallelism is None:
        assert n % worker_parallelism == 0, (n, worker_parallelism)
        ps_parallelism = n // worker_parallelism
    assert worker_parallelism * ps_parallelism == n, (
        f"worker_parallelism({worker_parallelism}) * ps_parallelism"
        f"({ps_parallelism}) != device count ({n})"
    )
    arr = np.array(devices).reshape(worker_parallelism, ps_parallelism)
    return Mesh(arr, axis_names)


def single_device_mesh(axis_names: Tuple[str, str] = (DP_AXIS, PS_AXIS)) -> Mesh:
    return Mesh(np.array(jax.devices()[:1]).reshape(1, 1), axis_names)


__all__ = ["DP_AXIS", "PS_AXIS", "make_mesh", "single_device_mesh"]
