"""Pipeline parallelism — GPipe-style stage pipelining over a ``pp`` axis.

The reference has no pipeline parallelism (SURVEY.md §2: "PP — NO"); this
module exists because distributed scale is a first-class requirement of
the rebuild: models whose layer stack exceeds one chip's HBM shard layers
across a ``pp`` mesh axis, and microbatches stream through the stages over
the ICI ring.

Design (SPMD schedule inside one ``shard_map``):

  * stage ``s`` holds its block of layers (params stacked per stage,
    sharded ``P('pp', ...)``),
  * time ticks ``t = 0 .. S+M-2`` (S stages, M microbatches): at tick t,
    stage s computes microbatch ``t-s`` if it is in [0, M), then
    ``ppermute``s its activation to stage ``s+1``,
  * stage 0 injects microbatch t at tick t; the last stage accumulates
    outputs; a final masked ``psum`` over ``pp`` replicates them.

Every stage computes at every tick (idle ticks are masked, not skipped) —
the classic bubble cost ``(S-1)/(S+M-1)``; raise M to amortise.  The
schedule is fully differentiable (``ppermute`` transposes to the reverse
permutation), so ``jax.grad`` through a pipelined forward just works.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

Array = jax.Array


def pipeline_apply(
    stage_params: Any,
    x: Array,
    block_fn: Callable[[Any, Array], Array],
    *,
    mesh: Mesh,
    pp_axis: str = "pp",
    dp_axis: str | None = "dp",
    num_microbatches: int,
    x_tail_spec: tuple | None = None,
) -> Array:
    """Run ``x`` through ``S = mesh.shape[pp_axis]`` pipelined stages.

    ``stage_params``: pytree whose leaves lead with the stage axis
    (shape ``(S, ...)``), sharded ``P(pp_axis, ...)``.
    ``x``: (B, ...), batch dim sharded over ``dp_axis`` (if the mesh has
    it) and replicated over ``pp`` — each dp row pipelines only its own
    batch shard.  ``block_fn(stage_local_params, x_mb) -> y_mb``: one
    stage's compute on one microbatch (same shape in/out).
    ``num_microbatches``: must divide the per-dp-shard batch.
    Returns (B, ...) sharded like ``x``.

    The tick schedule runs under ``lax.scan`` so ``block_fn`` is traced
    exactly once regardless of M (raise M freely to shrink the
    (S-1)/(S+M-1) bubble without blowing up compile time) and reverse
    -mode autodiff composes; the uniform loop body issues one (wasted)
    final-tick ppermute in exchange.
    """
    S = mesh.shape[pp_axis]
    M = num_microbatches
    if dp_axis is not None and dp_axis not in mesh.axis_names:
        dp_axis = None
    dp = mesh.shape[dp_axis] if dp_axis else 1
    B = x.shape[0]
    assert B % (M * dp) == 0, (B, M, dp)

    param_specs = jax.tree.map(lambda _: P(pp_axis), stage_params)
    # x_tail_spec shards the non-batch dims (e.g. (sp_axis, None) to keep
    # the sequence dim sp-sharded through the pipeline for ring attention)
    if x_tail_spec is None:
        x_tail_spec = (None,) * (x.ndim - 1)
    assert len(x_tail_spec) == x.ndim - 1, (x_tail_spec, x.ndim)
    x_spec = P(*((dp_axis,) + tuple(x_tail_spec)))

    def body(local_params, x_full):
        # local_params leaves: (1, ...) — this stage's block
        local_params = jax.tree.map(lambda l: l[0], local_params)
        s = jax.lax.axis_index(pp_axis)
        mb = x_full.shape[0] // M
        inputs = x_full.reshape((M, mb) + x_full.shape[1:])
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(t, state):
            carry, outputs = state
            # stage 0 injects microbatch t (clamped index is masked off
            # for t >= M by `active` below)
            inj = jax.lax.dynamic_index_in_dim(
                inputs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
            )
            x_in = jnp.where(s == 0, inj, carry)
            idx = t - s
            active = jnp.logical_and(idx >= 0, idx < M)
            y = block_fn(local_params, x_in)
            y = jnp.where(active, y, x_in)
            write = jnp.logical_and(active, s == S - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(
                    write,
                    y,
                    jax.lax.dynamic_index_in_dim(
                        outputs, jnp.clip(idx, 0, M - 1), 0, keepdims=False
                    ),
                ),
                jnp.clip(idx, 0, M - 1),
                axis=0,
            )
            carry = jax.lax.ppermute(y, pp_axis, perm)
            return carry, outputs

        carry = jnp.zeros_like(inputs[0])
        outputs = jnp.zeros_like(inputs)
        (carry, outputs), _ = jax.lax.scan(
            lambda state, t: (tick(t, state), None),
            (carry, outputs),
            jnp.arange(S + M - 1),
        )

        # outputs live on the last stage only; replicate via psum
        outputs = jax.lax.psum(outputs, pp_axis)
        return outputs.reshape(x_full.shape)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x)


def stack_stage_params(
    layer_params_list,
    num_stages: int,
    *,
    mesh: Mesh | None = None,
    pp_axis: str = "pp",
):
    """Group a list of per-layer param pytrees into ``num_stages`` stacked
    stage pytrees: leaves gain leading dims (num_stages, layers_per_stage).

    With ``mesh``, each *concrete* (eager/init-time) leaf is built
    shard-by-shard via ``jax.make_array_from_callback`` onto
    ``P(pp_axis, ...)`` — a device never materialises more than its own
    stage's layers, so the layer stack can exceed one chip's memory.
    Under a jit trace the host path can't run; leaves are stacked and
    sharding-constrained instead, and GSPMD decides the transient — for
    stacks that can't fit replicated, stack eagerly before jit.

    ``block_fn`` then scans its stage's (layers_per_stage, ...) leaves.
    """
    n = len(layer_params_list)
    assert n % num_stages == 0, (n, num_stages)
    per = n // num_stages

    def stack(*leaves):
        stacked = jnp.stack(leaves)  # (n, ...)
        return stacked.reshape((num_stages, per) + stacked.shape[1:])

    if mesh is None:
        return jax.tree.map(stack, *layer_params_list)

    traced = any(
        isinstance(l, jax.core.Tracer)
        for l in jax.tree.leaves(layer_params_list)
    )
    if traced:
        # under jit the host shard-by-shard path can't run; stack and let
        # GSPMD place the result via a sharding constraint
        def stack_constrained(*leaves):
            out = stack(*leaves)
            return jax.lax.with_sharding_constraint(
                out,
                jax.NamedSharding(mesh, P(pp_axis, *([None] * (out.ndim - 1)))),
            )

        return jax.tree.map(stack_constrained, *layer_params_list)

    import numpy as np

    def stack_sharded(*leaves):
        # host views of the per-layer leaves; each device's callback
        # assembles only the rows (stages) its shard owns
        host = [np.asarray(l) for l in leaves]
        shape = (num_stages, per) + host[0].shape
        sharding = jax.NamedSharding(
            mesh, P(pp_axis, *([None] * (len(shape) - 1)))
        )
        blocks = {}  # memoize per index: replica devices (dp) share blocks

        def cb(index):
            key = tuple(
                (sl.start, sl.stop, sl.step) if isinstance(sl, slice) else sl
                for sl in index
            )
            if key not in blocks:
                lo, hi, _ = index[0].indices(num_stages)
                block = np.stack(
                    [host[s * per + j]
                     for s in range(lo, hi) for j in range(per)]
                ).reshape((hi - lo, per) + host[0].shape)
                blocks[key] = block[(slice(None),) + tuple(index[1:])]
            return blocks[key]

        return jax.make_array_from_callback(shape, sharding, cb)

    return jax.tree.map(stack_sharded, *layer_params_list)


__all__ = ["pipeline_apply", "stack_stage_params"]
