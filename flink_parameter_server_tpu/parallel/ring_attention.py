"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference has nothing sequence-related (SURVEY.md §2: SP/CP "ABSENT"),
but long-context support is a first-class requirement of this framework:
the Transformer config (BASELINE.json #5) must scale past a single chip's
memory for long sequences.

Design (blockwise/ring attention): the sequence dimension is sharded over
``sp``; each device holds one Q/K/V block.  S−1 ``ppermute`` steps rotate
the K/V blocks around the ICI ring while every device accumulates its
queries' attention with the *online softmax* (running max/denominator), so
the full (T × T) score matrix never materialises and per-device memory is
O(T/S · T/S) per step.  Compute for step j overlaps with the DMA of step
j+1 under XLA's async collective scheduling.

Causality is enforced per block pair: the j-th rotation gives device ``i``
the K/V of global block ``(i − j) mod S``; blocks strictly in the future
are fully masked, the diagonal block gets the triangular mask, past blocks
are unmasked.  Step 0 is the self block, so every query row always has at
least one valid key (no -inf softmax rows).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

Array = jax.Array


def _block_attention_update(q, k, v, scores_mask, m, l, o, scale):
    """One online-softmax accumulation step.

    q: (B, H, T, D), k/v: (B, H, T, D); scores_mask (T, T) bool (True =
    attend); m, l: (B, H, T) fp32; o: (B, H, T, D) fp32.

    Scores and all running accumulators are float32 regardless of the
    input dtype (the flash/ring-attention convention): bf16 running
    max/denominator compound ~1e-2 error per rescale chain over many ring
    steps.  Inputs may stay bf16 — the MXU reads bf16 operands and this
    einsum accumulates fp32 via ``preferred_element_type``.
    """
    scores = (
        jnp.einsum("bhtd,bhsd->bhts", q, k, preferred_element_type=jnp.float32)
        * scale
    )  # (B,H,T,S) fp32
    scores = jnp.where(scores_mask[None, None], scores, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    # renormalise previous accumulators; exp(-inf - finite) == 0 is safe
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    # fully-masked rows produce p == 0 everywhere, contributing nothing
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhts,bhsd->bhtd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def ring_attention_inner(
    q_blk: Array,
    k_blk: Array,
    v_blk: Array,
    *,
    sp_axis: str,
    num_blocks: int,
    causal: bool = True,
) -> Array:
    """The ring schedule on LOCAL (B, T_local, H_local, D) blocks.

    Call this inside an *enclosing* ``shard_map`` whose mesh carries
    ``sp_axis`` (shard_maps don't nest) — e.g. from a pipeline stage body
    (:mod:`.pipeline`).  ``num_blocks`` must be the static ``sp`` size.
    """
    # (B_local, T_local, H, D) → (B, H, T, D)
    qh = jnp.moveaxis(q_blk, 2, 1)
    kh = jnp.moveaxis(k_blk, 2, 1)
    vh = jnp.moveaxis(v_blk, 2, 1)
    B, H, T, D = qh.shape
    scale = 1.0 / (D**0.5)
    my = jax.lax.axis_index(sp_axis)

    m = jnp.full((B, H, T), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, T), jnp.float32)
    o = jnp.zeros((B, H, T, D), jnp.float32)

    tri = jnp.tril(jnp.ones((T, T), bool))
    full = jnp.ones((T, T), bool)
    none = jnp.zeros((T, T), bool)

    def step(j, carry):
        m, l, o, kh, vh = carry
        src = (my - j) % num_blocks
        if causal:
            mask = jnp.where(src == my, tri, jnp.where(src < my, full, none))
        else:
            mask = full
        m, l, o = _block_attention_update(qh, kh, vh, mask, m, l, o, scale)
        if j < num_blocks - 1:  # final rotation's result is never read
            perm = [(i, (i + 1) % num_blocks) for i in range(num_blocks)]
            kh = jax.lax.ppermute(kh, sp_axis, perm)
            vh = jax.lax.ppermute(vh, sp_axis, perm)
        return m, l, o, kh, vh

    # unrolled python loop: num_blocks is static and small; lets XLA
    # pipeline each step's compute with the next ppermute
    carry = (m, l, o, kh, vh)
    for j in range(num_blocks):
        carry = step(j, carry)
    m, l, o, _, _ = carry

    out = (o / jnp.maximum(l[..., None], 1e-30)).astype(q_blk.dtype)
    return jnp.moveaxis(out, 1, 2)  # back to (B, T, H, D)


def ring_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    mesh: Mesh,
    sp_axis: str = "sp",
    dp_axis: Optional[str] = "dp",
    tp_axis: Optional[str] = None,
    causal: bool = True,
) -> Array:
    """Causal multi-head attention with the sequence sharded over ``sp``.

    q, k, v: (B, T_global, H, D) with T_global sharded over ``sp``, B over
    ``dp`` (if present) and heads over ``tp`` (if given — each device then
    runs the ring for its local heads only, composing SP×TP).  Returns
    same-shaped output, same sharding.
    """
    num_blocks = mesh.shape[sp_axis]

    lead = (dp_axis,) if dp_axis else (None,)
    spec = P(*lead, sp_axis, tp_axis, None)

    def body(q_blk, k_blk, v_blk):
        return ring_attention_inner(
            q_blk, k_blk, v_blk,
            sp_axis=sp_axis, num_blocks=num_blocks, causal=causal,
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def reference_attention(q: Array, k: Array, v: Array, *, causal: bool = True) -> Array:
    """Unsharded causal attention — the parity oracle for ring_attention."""
    qh = jnp.moveaxis(q, 2, 1)
    kh = jnp.moveaxis(k, 2, 1)
    vh = jnp.moveaxis(v, 2, 1)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bhtd,bhsd->bhts", qh, kh) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bhsd->bhtd", probs, vh)
    return jnp.moveaxis(out, 1, 2)


__all__ = ["ring_attention", "reference_attention"]
