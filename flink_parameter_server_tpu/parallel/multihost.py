"""Multi-host (multi-process) scale-out.

Reference parity: the reference scales out by adding Flink TaskManagers —
worker/server subtasks spread across JVMs, Netty carries the messages
(SURVEY.md §2 "Distributed communication backend").  The TPU equivalent is
JAX multi-process: one Python process per host, ``jax.distributed``
coordination, and *the same named-axis programs* — `Mesh` simply spans all
hosts' devices and XLA routes collectives over ICI within a slice and DCN
between slices.  Nothing else in this framework changes: every
`shard_map`/`pjit` path already addresses devices by mesh axis, not by
host.

Axis-layout rule (the scaling-book recipe): put the *ps* (parameter) axis
and any *tp/sp* axes INSIDE a slice so pull/push/ring collectives ride
ICI; put *dp* across slices so only gradient/delta aggregation crosses
DCN.  ``make_multihost_mesh`` encodes that default.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


_initialized = False

# Env vars whose presence signals a coordinated multi-process launch.
_COORD_ENV_HINTS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "CLOUD_TPU_TASK_ID",
    "TPU_WORKER_ID",
)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialise JAX multi-process mode (idempotent); returns True if
    distributed init ran.

    MUST be called before any other JAX API touches a backend
    (``jax.devices()``, the first jit, …) — ``jax.distributed.initialize``
    rejects already-initialised processes.  With explicit arguments, init
    always runs (errors propagate).  With no arguments, init runs only
    when the environment signals a coordinated launch (coordinator env
    vars / TPU-pod metadata vars); a plain single-process run is a no-op,
    and crucially this check touches only ``os.environ``, never a JAX
    backend."""
    global _initialized
    if _initialized:
        return True
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    if not explicit and not any(os.environ.get(k) for k in _COORD_ENV_HINTS):
        return False  # single process, nothing to coordinate
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def make_multihost_mesh(
    *,
    dp: Optional[int] = None,
    ps: int = 1,
    axis_names: Tuple[str, str] = ("dp", "ps"),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Global mesh over every process's devices with the DCN/ICI-aware
    layout: the trailing (``ps``) axis is laid out within hosts (ICI),
    the leading (``dp``) axis across hosts (DCN-crossing is amortised
    delta aggregation, not per-pull traffic).
    """
    explicit_devices = devices is not None
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None:
        assert n % ps == 0, (n, ps)
        dp = n // ps
    assert dp * ps == n, f"dp({dp}) * ps({ps}) != global device count ({n})"
    if not explicit_devices:
        # jax.devices() ordering groups by process, so row-major
        # (dp, ps) keeps a ps row within one host iff ps divides the
        # per-host device count.
        per_host = jax.local_device_count()
        assert per_host % ps == 0 or per_host == n, (
            f"ps axis ({ps}) must divide the per-host device count "
            f"({per_host}) so parameter-shard rows stay inside one slice "
            f"and pulls ride ICI, not DCN"
        )
    arr = np.array(devices).reshape(dp, ps)
    return Mesh(arr, axis_names)


def process_local_batch_slice(global_batch: int) -> slice:
    """Which rows of a global batch this process should load — the data
    pipeline runs per host; each host feeds only its devices' shard
    (the ingestion edge stays host-local, like the reference's per-TM
    source splits)."""
    p = jax.process_index()
    n = jax.process_count()
    per = global_batch // n
    assert per * n == global_batch, (global_batch, n)
    return slice(p * per, (p + 1) * per)


__all__ = ["initialize", "make_multihost_mesh", "process_local_batch_slice"]
