"""Explicit shard_map pull/push — the ICI collective message plane.

Reference parity: this module *is* the rebuild's "distributed communication
backend" (SURVEY.md §2): it replaces Flink's Netty point-to-point keyed
routing (``partitionCustom(hash(paramId) % psParallelism)`` worker→server,
``workerPartitionIndex`` routing server→worker, iteration feedback edge)
with XLA collectives over ICI inside one jitted step.

Routing scheme (block layout): shard ``s`` of the ``ps`` axis owns rows
``[s·R, (s+1)·R)`` of the padded table (R = rows per shard).  For a pull:

  * every ``ps`` shard receives the (replicated-over-ps) id batch,
  * answers the ids it owns, zeros elsewhere,
  * one ``psum`` over ``ps`` assembles the full answer — a single
    all-reduce replaces the reference's two network hops + queueing per
    pull (SURVEY.md §3.1 "Boundary crossings").

For a push each shard keeps only its own rows' deltas and scatter-adds them
locally — zero cross-shard traffic (the partitioning does the routing).

Skew note: hot ids (Criteo, word2vec) all land on one shard under block
layout just as under the reference's mod-hash; :mod:`..ops.hashing` provides
an affine id-permutation to spread them.
"""
from __future__ import annotations

import warnings
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.compat import shard_map

Array = jax.Array


def _rows_per_shard(padded_capacity: int, num_shards: int) -> int:
    assert padded_capacity % num_shards == 0
    return padded_capacity // num_shards


def shard_pull(
    table: Array,
    ids: Array,
    *,
    mesh: Mesh,
    ps_axis: str = "ps",
    dp_axis: Optional[str] = "dp",
) -> Array:
    """Sharded gather via one psum over the ``ps`` axis.

    ``table``: (padded_capacity, *value_shape) sharded P(ps_axis, ...).
    ``ids``:   (..., n) int32, sharded along ``dp`` on its leading dim (if a
    dp axis exists) and replicated over ``ps``.
    Returns values with ``ids``' shape + value_shape, sharded like ``ids``.
    """
    num_shards = mesh.shape[ps_axis]
    value_rank = table.ndim - 1
    vspec = (None,) * value_rank

    table_spec = P(ps_axis, *vspec)
    ids_spec = P(dp_axis, *((None,) * (ids.ndim - 1))) if dp_axis else P(
        *((None,) * ids.ndim)
    )
    out_spec = P(*(ids_spec + vspec)) if dp_axis else P(*((None,) * ids.ndim + vspec))

    def body(local_table: Array, local_ids: Array) -> Array:
        rows = local_table.shape[0]
        shard = jax.lax.axis_index(ps_axis)
        lo = shard * rows
        rel = local_ids - lo
        hit = (rel >= 0) & (rel < rows)
        rel = jnp.clip(rel, 0, rows - 1)
        vals = jnp.take(local_table, rel.reshape(-1), axis=0)
        vals = vals.reshape(local_ids.shape + local_table.shape[1:])
        vals = jnp.where(
            hit.reshape(hit.shape + (1,) * value_rank), vals, jnp.zeros_like(vals)
        )
        return jax.lax.psum(vals, ps_axis)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(table_spec, ids_spec),
        out_specs=out_spec,
    )(table, ids)


def shard_push_add(
    table: Array,
    ids: Array,
    deltas: Array,
    mask: Optional[Array] = None,
    *,
    mesh: Mesh,
    ps_axis: str = "ps",
    dp_axis: Optional[str] = "dp",
    impl: str = "xla",
    ids_sorted: bool = False,
) -> Array:
    """Sharded scatter-add: each ``ps`` shard folds in only the rows it
    owns.  When a ``dp`` axis exists, each worker's deltas are first
    all-gathered over ``dp`` (the worker→server "shuffle", now one ICI
    collective) and then locally scatter-added.

    ``impl="pallas"``: each shard's local scatter runs the sorted-run
    duplicate-compressing kernel (:mod:`..ops.pallas_scatter`) — one HBM
    read-modify-write per unique local row under Zipf-hot ids.
    ``impl="xla_sorted"``: the same dedup in pure XLA
    (:mod:`..ops.sorted_scatter`) — no Mosaic shape constraints.

    ``ids_sorted=True`` (xla_sorted only): the caller promises GLOBALLY
    ascending flat ids (batch presort).  The dp split is then contiguous
    chunks of a sorted array and the tiled all_gather reassembles them
    in dp order, so each shard sees ascending ids — the per-shard
    argsort + delta permute are skipped entirely (the op handles each
    shard's out-of-range lanes order-preservingly; see
    :func:`..ops.sorted_scatter.sorted_dedup_scatter_add`).
    """
    value_rank = table.ndim - 1
    if impl == "pallas":
        # Real Mosaic's measured shape rules (benchmarks/mosaic_probe.py):
        # compiled kernels need 128-aligned row widths and 8-aligned
        # per-shard capacities.  Fall back observably, never silently.
        from ..ops.pallas_scatter import supports_shape

        rows_per_shard = table.shape[0] // mesh.shape[ps_axis]
        row_width = 1
        for s in table.shape[1:]:
            row_width *= s
        if jax.default_backend() == "tpu" and not supports_shape(
            rows_per_shard, row_width
        ):
            warnings.warn(
                f"shard_push_add impl='pallas' falling back to XLA "
                f"scatter: per-shard table ({rows_per_shard}, {row_width}) "
                f"violates Mosaic alignment (need rows % 8 == 0, "
                f"width % 128 == 0)",
                RuntimeWarning,
                stacklevel=2,
            )
            impl = "xla"
    vspec = (None,) * value_rank
    table_spec = P(ps_axis, *vspec)
    lead = P(dp_axis) if dp_axis else P(None)
    ids_spec = P(*(lead + (None,) * (ids.ndim - 1)))
    deltas_spec = P(*(lead + (None,) * (deltas.ndim - 1)))
    mask_spec = P(*(lead + (None,) * (ids.ndim - 1)))

    def body(local_table, local_ids, local_deltas, local_mask):
        rows = local_table.shape[0]
        shard = jax.lax.axis_index(ps_axis)
        if dp_axis is not None:
            # Bring every worker's (ids, deltas) to every ps shard.
            local_ids = jax.lax.all_gather(local_ids, dp_axis, tiled=True)
            local_deltas = jax.lax.all_gather(local_deltas, dp_axis, tiled=True)
            local_mask = jax.lax.all_gather(local_mask, dp_axis, tiled=True)
        lo = shard * rows
        rel = local_ids.reshape(-1) - lo
        hit = (rel >= 0) & (rel < rows)
        hit = hit & local_mask.reshape(-1)
        if impl == "pallas":
            # the public wrapper owns the lane prep (mask→zero-delta,
            # sort, sentinel handling) — don't duplicate it here
            from ..ops.pallas_scatter import scatter_add as pallas_scatter_add

            return pallas_scatter_add(
                local_table,
                rel,
                local_deltas.reshape((-1,) + local_table.shape[1:]),
                hit,
            )
        if impl == "xla_sorted":
            from ..ops.sorted_scatter import sorted_dedup_scatter_add

            # under ids_sorted the op itself keeps invalid lanes
            # order-preserving (zero-delta + monotone clip) — the
            # ascending rel = [negatives][this shard's run][>= rows]
            # needs no caller-side prep
            return sorted_dedup_scatter_add(
                local_table,
                rel,
                local_deltas.reshape((-1,) + local_table.shape[1:]),
                hit,
                oob=rows,
                ids_sorted=ids_sorted,
            )
        rel = jnp.clip(rel, 0, rows - 1)
        d = local_deltas.reshape((-1,) + local_table.shape[1:])
        d = jnp.where(
            hit.reshape((-1,) + (1,) * value_rank), d, jnp.zeros_like(d)
        ).astype(local_table.dtype)
        return local_table.at[rel].add(d)

    if mask is None:
        mask = jnp.ones(ids.shape, dtype=bool)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(table_spec, ids_spec, deltas_spec, mask_spec),
        out_specs=table_spec,
        # After the all_gather over dp, every dp row computes identical
        # local tables; the checker can't infer that replication statically.
        check_vma=False,
    )(table, ids, deltas, mask)


__all__ = ["shard_pull", "shard_push_add"]
