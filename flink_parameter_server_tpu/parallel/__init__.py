"""flink_parameter_server_tpu.parallel"""
