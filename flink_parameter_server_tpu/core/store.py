"""ShardedParamStore — the TPU-native server-side keyed parameter store.

Reference parity: replaces the reference server's per-subtask
``HashMap[Int, P]`` with ``getOrElseUpdate(id, init(id))`` semantics
(``SimplePSLogic`` — SURVEY.md §2 #3) and its ``hash(paramId) % psParallelism``
routing (SURVEY.md §2 "Model parallelism").

TPU-first design
----------------
The store is a dense ``(capacity, *value_shape)`` ``jax.Array`` living in HBM,
row-sharded over a named mesh axis (``"ps"``).  The reference's message-level
protocol maps onto array ops *inside* a jitted step:

  * ``pull(ids)``  → sharded gather (``jnp.take``); XLA lowers the
    cross-shard reads to ICI collectives (or we do it explicitly with
    ``shard_map`` — see :mod:`..parallel.collectives`).
  * ``push(ids, deltas)`` → sharded scatter-add (``table.at[ids].add``).

"Lazy init on first pull" in the reference uses a *deterministic per-id*
initializer (``RangedRandomFactorInitializerDescriptor``), so eager
whole-table initialisation at create time is observationally equivalent and
far more TPU-friendly (one fused init kernel instead of per-row branches).

Duplicate ids within one microbatch: the reference applies each push
sequentially; with the default commutative ``add`` update, combining
duplicates with a segment-sum is exactly equivalent.  For *non-commutative*
custom ``update`` functions, intra-batch duplicate deltas are summed first
and ``update`` is then applied once per touched id — the documented
semantic delta vs. the reference (bounded staleness ≤ one microbatch;
SURVEY.md §7 "Guiding translation").
"""
from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array
InitFn = Callable[[Array], Array]  # ids (n,) int32 -> values (n, *value_shape)
UpdateFn = Callable[[Array, Array], Array]  # (current, combined_delta) -> new

# Trace-time count of pushes where a non-default scatter_impl ("pallas",
# "xla_sorted") had to fall back to the XLA scatter (batch not divisible
# by dp, Mosaic shape violation).  The choice is static per compiled
# step, so one warning per offending trace suffices — a user who
# configured a specific impl must never *silently* not get it (a bench
# row would then mislabel which arm actually ran).
_PALLAS_FALLBACKS = 0


def pallas_fallback_count() -> int:
    return _PALLAS_FALLBACKS


def _note_scatter_fallback(impl: str, reason: str) -> None:
    global _PALLAS_FALLBACKS
    _PALLAS_FALLBACKS += 1
    warnings.warn(
        f"scatter_impl={impl!r} store falling back to XLA scatter: "
        f"{reason}",
        RuntimeWarning,
        stacklevel=3,
    )


def _note_pallas_fallback(reason: str) -> None:
    _note_scatter_fallback("pallas", reason)


def _dp_axis_and_divisible(mesh, n: int):
    """(dp_axis or None, batch-divisibility ok) — the shared gate for
    dispatching a push through shard_push_add's all_gather plane."""
    from ..parallel.mesh import DP_AXIS

    dp_axis = (
        DP_AXIS
        if DP_AXIS in mesh.axis_names and mesh.shape[DP_AXIS] > 1
        else None
    )
    return dp_axis, (dp_axis is None or n % mesh.shape[dp_axis] == 0)


def _resolve_layout(
    layout: str, update: Union[str, UpdateFn], value_shape: Tuple[int, ...]
) -> str:
    """Resolve the table layout, validating packed-layout constraints.

    ``"auto"`` picks packed for narrow-row add-stores (the shapes where
    lane packing pays — MF/FM/PA) and dense otherwise."""
    if layout not in ("dense", "packed", "auto"):
        raise ValueError(
            f"layout must be 'dense', 'packed' or 'auto', got {layout!r}"
        )
    width = 1
    for s in value_shape:
        width *= int(s)
    if layout == "auto":
        return "packed" if (update == "add" and width < 128) else "dense"
    if layout == "packed" and update != "add":
        # the generic update path applies `update` per logical row on a
        # dense combined table — packing it would need an unpack per push
        raise ValueError(
            "layout='packed' requires update='add' (custom update "
            "functions take the dense per-row path)"
        )
    return layout


@dataclasses.dataclass(frozen=True)
class StoreSpec:
    """Static configuration of a parameter store (not a pytree leaf)."""

    capacity: int
    value_shape: Tuple[int, ...] = ()
    dtype: Any = jnp.float32
    # "add" uses the fast scatter-add path; any other callable takes the
    # generic dense-update path (see module docstring; intra-batch
    # duplicate deltas are always summed before `update` is applied).
    update: Union[str, UpdateFn] = "add"
    # "xla" = native XLA scatter; "pallas" = the sorted-run duplicate
    # -compressing TPU kernel (ops/pallas_scatter.py) — wins under Zipf-hot
    # id distributions; only valid with update="add" and vector values.
    # "xla_sorted" = duplicate compression in pure XLA (sort + segment-sum
    # + unique_indices scatter, ops/sorted_scatter.py) — no Mosaic shape
    # constraints, runs on any backend; only valid with update="add".
    scatter_impl: str = "xla"
    mesh: Optional[Mesh] = None
    ps_axis: str = "ps"
    # "dense": one logical row per physical row (the trivial layout).
    # "packed": k = 128 // row_width logical rows per 128-lane physical
    #   row (ops/packed.py) — the TPU-native layout for narrow values
    #   (MF dim 64, FM dim 17): full vector lanes on every pull/push and
    #   pallas-kernel eligibility at any width.  Requires update="add".
    layout: str = "dense"

    def __post_init__(self) -> None:
        # A user who configured a specific impl must never silently not
        # get it: a typo like "sorted" or "xla-sorted" would otherwise
        # fall through every `== "pallas"` / `== "xla_sorted"` dispatch
        # and run the plain XLA scatter without a word.
        valid = ("xla", "pallas", "xla_sorted")
        if self.scatter_impl not in valid:
            raise ValueError(
                f"scatter_impl={self.scatter_impl!r} is not one of {valid}"
            )
        if self.layout not in ("dense", "packed"):
            raise ValueError(
                f"layout={self.layout!r} is not one of ('dense', 'packed')"
            )

    @property
    def num_shards(self) -> int:
        if self.mesh is None:
            return 1
        return self.mesh.shape[self.ps_axis]

    @property
    def row_width(self) -> int:
        w = 1
        for s in self.value_shape:
            w *= int(s)
        return w

    @property
    def pack(self) -> int:
        """Logical rows per physical row (1 for the dense layout)."""
        if self.layout != "packed":
            return 1
        from ..ops.packed import pack_k

        return pack_k(self.row_width)

    @property
    def rows_per_shard(self) -> int:
        """Per-shard PHYSICAL row count, window-aligned for the pallas
        kernel.

        Real Mosaic reads/writes the table in aligned 8-row windows
        (ops/pallas_scatter.WINDOW); aligning every shard's block here
        means the kernel path never needs a pad-copy of the table."""
        n = self.num_shards
        logical = (self.capacity + self.pack - 1) // self.pack
        per = (logical + n - 1) // n
        return ((per + 7) // 8) * 8

    @property
    def padded_capacity(self) -> int:
        """LOGICAL capacity including padding rows (init'd, addressable)."""
        return self.rows_per_shard * self.num_shards * self.pack

    def table_shape(self) -> Tuple[int, ...]:
        """Shape of the physical table array."""
        if self.layout == "packed":
            from ..ops.packed import phys_width

            return (
                self.rows_per_shard * self.num_shards,
                phys_width(self.row_width),
            )
        return (self.padded_capacity,) + self.value_shape

    def sharding(self) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        if self.layout == "packed":
            return NamedSharding(self.mesh, P(self.ps_axis, None))
        return NamedSharding(
            self.mesh, P(self.ps_axis, *([None] * len(self.value_shape)))
        )


def zeros_init(spec: StoreSpec) -> InitFn:
    def init(ids: Array) -> Array:
        return jnp.zeros(ids.shape + spec.value_shape, spec.dtype)

    return init


def create_table(spec: StoreSpec, init_fn: Optional[InitFn] = None) -> Array:
    """Materialise the full table, eagerly initialised via ``init_fn``.

    ``init_fn`` must be deterministic per id (vectorised over an id array) —
    the analogue of the reference's ranged-random factor initializer
    descriptors, which exist precisely so that init is reproducible per key.
    """
    init_fn = init_fn or zeros_init(spec)
    ids = jnp.arange(spec.padded_capacity, dtype=jnp.int32)
    out_sharding = spec.sharding()

    def build(ids):
        values = init_fn(ids)
        if spec.layout == "packed":
            from ..ops.packed import pack_table

            values = pack_table(
                values.reshape(-1, spec.row_width),
                spec.rows_per_shard * spec.num_shards,
            )
        return values

    if out_sharding is not None:
        build = jax.jit(build, out_shardings=out_sharding)
    else:
        build = jax.jit(build)
    return build(ids)


def pull(spec: StoreSpec, table: Array, ids: Array) -> Array:
    """Batched pull: ``values[i] = table[ids[i]]`` (sharded gather).

    Out-of-range ids are clipped (callers use a validity mask alongside).
    Packed layout: one physical-row gather + one lane slice (both
    vectorized XLA gathers — see ops/packed.py)."""
    ids = jnp.clip(ids.astype(jnp.int32), 0, spec.padded_capacity - 1)
    if spec.layout == "packed":
        from ..ops.packed import packed_pull

        vals = packed_pull(table, ids.reshape(-1), spec.row_width)
        return vals.reshape(ids.shape + spec.value_shape)
    return jnp.take(table, ids, axis=0)


def _phys_scatter_args(
    spec: StoreSpec, table: Array, flat_ids: Array, flat_deltas: Array
):
    """(ids, deltas) at PHYSICAL granularity for XLA/sharded scatters.

    Dense: passthrough.  Packed: lane-shift each delta row to its
    sub-row offset and divide ids down to physical rows (the sentinel
    ``padded_capacity`` divides to the out-of-range physical row, so
    ``mode="drop"`` semantics are preserved)."""
    if spec.layout != "packed":
        return flat_ids, flat_deltas
    from ..ops.packed import lane_shift_deltas, packed_phys_ids

    shifted = lane_shift_deltas(
        flat_deltas.reshape(-1, spec.row_width).astype(table.dtype),
        flat_ids,
        spec.row_width,
    )
    return packed_phys_ids(flat_ids, spec.row_width), shifted


def push(
    spec: StoreSpec,
    table: Array,
    ids: Array,
    deltas: Array,
    mask: Optional[Array] = None,
    *,
    ids_sorted: bool = False,
) -> Array:
    """Batched push: fold ``deltas`` into rows ``ids`` (sharded scatter).

    ``mask`` (same leading shape as ``ids``) zeroes out padding lanes — the
    jit-friendly replacement for the reference's variable-length message
    batches (SURVEY.md §7 "Dynamic shapes").  Out-of-range ids are dropped
    (``mode="drop"``), matching :func:`..parallel.collectives.shard_push_add`.

    ``ids_sorted=True`` is the caller's promise that ``ids`` is ascending
    with any NEGATIVE lanes at the end (make_train_step's ``presort``
    sorts by the routed key, which guarantees exactly this): the
    plain-"xla" scatter then tells XLA ``indices_are_sorted`` (any shard
    count — that branch never reorders lanes) and "xla_sorted" skips its
    argsort at ANY shard count (the dp split of a sorted array is
    contiguous chunks, reassembled in order by the tiled all_gather —
    see :func:`..parallel.collectives.shard_push_add`).  The pallas
    shard_map push ignores it (the kernel sorts in-kernel).
    """
    vr = len(spec.value_shape)
    lead = tuple(deltas.shape[: deltas.ndim - vr])
    if (vr and tuple(deltas.shape[deltas.ndim - vr:]) != spec.value_shape) or (
        lead != tuple(ids.shape)
    ):
        raise ValueError(
            f"push deltas shape {tuple(deltas.shape)} does not match ids "
            f"shape {tuple(ids.shape)} + store value shape "
            f"{spec.value_shape}"
        )
    if mask is not None and tuple(mask.shape) != tuple(ids.shape):
        # a length-1 mask would silently broadcast across every lane
        raise ValueError(
            f"push mask shape {tuple(mask.shape)} does not match ids shape "
            f"{tuple(ids.shape)}"
        )
    ids = ids.astype(jnp.int32)
    flat_ids = ids.reshape(-1)
    # Negative ids would wrap (numpy semantics) before mode="drop" applies;
    # route them to an always-out-of-bounds sentinel so they drop too.
    flat_ids = jnp.where(flat_ids < 0, spec.padded_capacity, flat_ids)
    flat_deltas = deltas.reshape((-1,) + spec.value_shape)
    if mask is not None:
        flat_mask = mask.reshape(-1)
        # Masked-out lanes keep their id but carry a zero delta: for the
        # fast add path zero deltas are a no-op; for the generic path the
        # count is also masked.
        flat_deltas = jnp.where(
            flat_mask.reshape((-1,) + (1,) * len(spec.value_shape)),
            flat_deltas,
            jnp.zeros_like(flat_deltas),
        )

    if spec.update == "add":
        if spec.scatter_impl == "pallas":
            from ..ops import pallas_scatter as _pallas

            # Real Mosaic constrains the compiled kernel's shapes
            # (dim % 128, capacity % 8 — measured, see
            # benchmarks/mosaic_probe.py).  Interpreter mode (non-TPU)
            # has no dim constraint; capacity is window-aligned by
            # rows_per_shard either way.  The packed layout is always
            # eligible (physical width 128 by construction).
            kernel_width = (
                int(np.prod(table.shape[1:]))
                if spec.layout == "packed"
                else spec.row_width
            )
            shapes_ok = jax.default_backend() != "tpu" or _pallas.supports_shape(
                spec.rows_per_shard, kernel_width
            )
            if not shapes_ok:
                _note_pallas_fallback(
                    f"table row width {kernel_width} not a multiple of 128 "
                    f"(Mosaic lane alignment; use layout='packed')"
                )
            elif spec.num_shards == 1:
                if (
                    spec.layout == "packed"
                    and 1 < spec.pack <= _pallas.MAX_INKERNEL_SUB_K
                ):
                    # logical ids + logical-width deltas: the kernel
                    # lane-shifts in-register, so the HBM delta buffer
                    # never pays the 128-lane expansion
                    return _pallas.scatter_add(
                        table,
                        flat_ids,
                        flat_deltas.reshape(-1, spec.row_width),
                        None,
                        sub_k=spec.pack,
                        sub_width=spec.row_width,
                    )
                if spec.layout == "packed":
                    # pack == 1 (row width 65..127 or a non-multiple of
                    # 128 above it: lane-padded, not packed) and very
                    # narrow rows (e.g. scalars, pack=128, where sub_k
                    # unrolled in-kernel rolls would dominate): pre-shift
                    # XLA-side and scatter at physical granularity
                    s_ids, s_deltas = _phys_scatter_args(
                        spec, table, flat_ids, flat_deltas
                    )
                    return _pallas.scatter_add(table, s_ids, s_deltas, None)
                return _pallas.scatter_add(
                    table, flat_ids, flat_deltas,
                    None if mask is None else flat_mask,
                )
            else:
                # Sharded: run the kernel per ps shard under shard_map
                # (the explicit collective plane).  Requires the flat
                # batch length to divide the dp size for the all_gather
                # specs; otherwise fall back to XLA scatter.
                from ..parallel.collectives import shard_push_add

                s_ids, s_deltas = _phys_scatter_args(
                    spec, table, flat_ids, flat_deltas
                )
                n = s_ids.shape[0]
                dp_axis, divisible = _dp_axis_and_divisible(spec.mesh, n)
                if divisible:
                    # mask=None: masked lanes' deltas were zeroed above,
                    # so a no-op under add — skip the extra mask all_gather
                    return shard_push_add(
                        table,
                        s_ids,
                        s_deltas,
                        None,
                        mesh=spec.mesh,
                        ps_axis=spec.ps_axis,
                        dp_axis=dp_axis,
                        impl="pallas",
                    )
                _note_pallas_fallback(
                    f"flat batch {n} not divisible by "
                    f"dp={spec.mesh.shape[dp_axis]}"
                )
        s_ids, s_deltas = _phys_scatter_args(
            spec, table, flat_ids, flat_deltas
        )
        if spec.scatter_impl == "xla_sorted":
            # duplicate compression in pure XLA (ops/sorted_scatter.py):
            # for the packed layout this runs at PHYSICAL granularity, so
            # Zipf-hot neighbours sharing a physical row combine too
            if spec.num_shards == 1:
                from ..ops.sorted_scatter import sorted_dedup_scatter_add

                # ids_sorted survives _phys_scatter_args: the packed
                # physical id (logical // pack) is monotone and the
                # negative-lane sentinel (padded_capacity, routed above)
                # maps to exactly the physical row count = oob
                return sorted_dedup_scatter_add(
                    table, s_ids, s_deltas, None,
                    oob=table.shape[0], ids_sorted=ids_sorted,
                )
            from ..parallel.collectives import shard_push_add

            n = s_ids.shape[0]
            dp_axis, divisible = _dp_axis_and_divisible(spec.mesh, n)
            if divisible:
                # the dp split of a globally sorted id array is
                # contiguous chunks, reassembled in order by the tiled
                # all_gather — the promise survives sharding
                return shard_push_add(
                    table, s_ids, s_deltas, None,
                    mesh=spec.mesh, ps_axis=spec.ps_axis, dp_axis=dp_axis,
                    impl="xla_sorted", ids_sorted=ids_sorted,
                )
            # plain XLA scatter is still correct — but never silent
            _note_scatter_fallback(
                "xla_sorted",
                f"flat batch {n} not divisible by "
                f"dp={spec.mesh.shape[dp_axis]}",
            )
        # (valid even sharded: this branch never reorders lanes — GSPMD
        # sees the logical, still-ascending id array)
        return table.at[s_ids].add(
            s_deltas.astype(table.dtype), mode="drop",
            indices_are_sorted=ids_sorted,
        )

    # Generic path: combine duplicates densely, then apply `update` once per
    # touched row.  O(capacity) per step — documented slow path; the add
    # fast path is the perf path.
    combined = jnp.zeros_like(table).at[flat_ids].add(
        flat_deltas.astype(table.dtype), mode="drop"
    )
    ones = jnp.ones(flat_ids.shape, jnp.int32)
    if mask is not None:
        ones = jnp.where(flat_mask, ones, 0)
    counts = (
        jnp.zeros((spec.padded_capacity,), jnp.int32)
        .at[flat_ids]
        .add(ones, mode="drop")
    )
    update_fn: UpdateFn = spec.update  # type: ignore[assignment]
    updated = update_fn(table, combined)
    touched = (counts > 0).reshape((-1,) + (1,) * len(spec.value_shape))
    return jnp.where(touched, updated, table)


@jax.tree_util.register_pytree_node_class
class ShardedParamStore:
    """Functional bundle of (spec, table).  All mutators return new stores.

    The TPU-side equivalent of one *logical* parameter server spanning
    ``spec.num_shards`` shards (the reference's ``psParallelism``).
    """

    def __init__(self, spec: StoreSpec, table: Array):
        self.spec = spec
        self.table = table

    # -- construction -----------------------------------------------------
    @classmethod
    def create(
        cls,
        capacity: int,
        value_shape: Tuple[int, ...] = (),
        *,
        dtype: Any = jnp.float32,
        init_fn: Optional[InitFn] = None,
        update: Union[str, UpdateFn] = "add",
        scatter_impl: str = "xla",
        mesh: Optional[Mesh] = None,
        ps_axis: str = "ps",
        layout: str = "dense",
    ) -> "ShardedParamStore":
        spec = StoreSpec(
            capacity=capacity,
            value_shape=tuple(value_shape),
            dtype=dtype,
            update=update,
            scatter_impl=scatter_impl,
            mesh=mesh,
            ps_axis=ps_axis,
            layout=_resolve_layout(layout, update, tuple(value_shape)),
        )
        return cls(spec, create_table(spec, init_fn))

    @classmethod
    def from_values(
        cls,
        values: Array,
        *,
        update: Union[str, UpdateFn] = "add",
        scatter_impl: str = "xla",
        mesh: Optional[Mesh] = None,
        ps_axis: str = "ps",
        layout: str = "dense",
    ) -> "ShardedParamStore":
        """Seed the store from an existing ``(capacity, *value_shape)``
        array — the reference's ``transformWithModelLoad`` analogue
        (SURVEY.md §5 "Checkpoint / resume")."""
        spec = StoreSpec(
            capacity=values.shape[0],
            value_shape=tuple(values.shape[1:]),
            dtype=values.dtype,
            update=update,
            scatter_impl=scatter_impl,
            mesh=mesh,
            ps_axis=ps_axis,
            layout=_resolve_layout(layout, update, tuple(values.shape[1:])),
        )
        return cls(spec, cls._place(spec, values))

    @classmethod
    def from_spec_values(
        cls, spec: StoreSpec, values: Array
    ) -> "ShardedParamStore":
        """Seed a store carrying the *full* target ``spec`` (update rule,
        ``scatter_impl``, mesh layout) from an unpadded ``(capacity, ...)``
        value array — the checkpoint-restore path, which must not drop
        spec fields the way a shape-inferred rebuild would."""
        return cls(spec, cls._place(spec, values.astype(spec.dtype)))

    @staticmethod
    def _place(spec: StoreSpec, values: Array) -> Array:
        pad = spec.padded_capacity - values.shape[0]
        if pad:
            values = jnp.concatenate(
                [values, jnp.zeros((pad,) + spec.value_shape, spec.dtype)]
            )
        if spec.layout == "packed":
            from ..ops.packed import pack_table

            values = pack_table(
                values.reshape(-1, spec.row_width),
                spec.rows_per_shard * spec.num_shards,
            )
        sharding = spec.sharding()
        if sharding is not None:
            values = jax.device_put(values, sharding)
        return values

    # -- protocol ---------------------------------------------------------
    def pull(self, ids: Array) -> Array:
        return pull(self.spec, self.table, ids)

    def push(
        self, ids: Array, deltas: Array, mask: Optional[Array] = None
    ) -> "ShardedParamStore":
        return ShardedParamStore(
            self.spec, push(self.spec, self.table, ids, deltas, mask)
        )

    def values(self) -> Array:
        """Final model dump (unpadded, LOGICAL layout) — the reference's
        close()-time parameter flush (SURVEY.md §3.5)."""
        if self.spec.layout == "packed":
            from ..ops.packed import unpack_table

            vals = unpack_table(
                self.table, self.spec.capacity, self.spec.row_width
            )
            return vals.reshape((self.spec.capacity,) + self.spec.value_shape)
        return self.table[: self.spec.capacity]

    # -- pytree plumbing ---------------------------------------------------
    def tree_flatten(self):
        return (self.table,), self.spec

    @classmethod
    def tree_unflatten(cls, spec, leaves):
        return cls(spec, leaves[0])


__all__ = [
    "StoreSpec",
    "ShardedParamStore",
    "create_table",
    "pull",
    "push",
    "zeros_init",
    "pallas_fallback_count",
]
