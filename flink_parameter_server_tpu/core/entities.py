"""Wire-level message entities of the parameter-server protocol.

Reference parity: mirrors the message case classes of
``hu.sztaki.ilab.ps.entities`` in FlinkML/flink-parameter-server
(Pull, Push, PullAnswer, WorkerToPS, PSToWorker — SURVEY.md §2 #5).

In the reference these are per-record stream payloads ferried between the
worker and server CoFlatMap operators over Flink's Netty channels.  In the
TPU rebuild the *hot path never materialises them*: a microbatch of pulls is
a sharded gather and a microbatch of pushes a sharded scatter-add inside one
jitted step.  The dataclasses below exist for

  * the host-side event backend (``backend="local"``), which reproduces the
    reference's per-record callback semantics exactly, and
  * tracing/debug dumps, where reconstructing the logical message stream
    from a batched step is useful.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Generic, TypeVar, Union

P = TypeVar("P")  # parameter value type


@dataclass(frozen=True)
class Pull:
    """Worker asks the server for the current value of ``param_id``."""

    param_id: int


@dataclass(frozen=True)
class Push(Generic[P]):
    """Worker sends a delta for ``param_id`` to be folded into the store."""

    param_id: int
    delta: Any


@dataclass(frozen=True)
class PullAnswer(Generic[P]):
    """Server's reply to a :class:`Pull`."""

    param_id: int
    value: Any


@dataclass(frozen=True)
class WorkerToPS(Generic[P]):
    """Envelope on the worker→server stream.

    ``worker_partition_index`` is embedded so the server can address the
    answer back to the right worker subtask — the reference carries it in
    every message for the same reason (SURVEY.md §2 "Distributed
    communication backend").
    """

    worker_partition_index: int
    message: Union[Pull, Push]


@dataclass(frozen=True)
class PSToWorker(Generic[P]):
    """Envelope on the server→worker (feedback) stream."""

    worker_partition_index: int
    answer: PullAnswer


__all__ = [
    "Pull",
    "Push",
    "PullAnswer",
    "WorkerToPS",
    "PSToWorker",
]
