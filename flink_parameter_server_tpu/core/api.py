"""Public parameter-server API surface.

Reference parity (SURVEY.md §2 #2–#4): this module is the TPU-native
re-founding of the reference's L3 traits

  * ``WorkerLogic[T, P, WOut]``            → :class:`WorkerLogic`
  * ``ParameterServerLogic[P, PSOut]``     → :class:`ParameterServerLogic`
  * ``ParameterServerClient[P, WOut]``     → :class:`ParameterServerClient`
  * ``ParameterServer[P, PSOut]``          → :class:`ParameterServer`
  * ``WorkerLogic.addPullLimiter``         → :func:`add_pull_limiter`

Two programming models are offered:

1. **Event API** (this module): per-record callbacks identical in shape to
   the reference — ``on_recv(data, ps)`` / ``on_pull_recv(id, value, ps)``.
   Runs on the host via the ``local`` backend, preserving the reference's
   asynchronous interleaving semantics.  Arbitrary Python allowed.

2. **Batched API** (:mod:`..core.batched`): a pure function over a
   microbatch of events — this is what compiles under ``jax.jit`` and runs
   on TPU.  ``pull`` becomes a sharded gather, ``push`` a sharded
   scatter-add over ICI collectives.

The ``transform`` entrypoint (:mod:`..core.transform`) accepts either.
"""
from __future__ import annotations

import abc
import collections
from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")  # training-data record type
P = TypeVar("P")  # parameter value type
WOut = TypeVar("WOut")  # worker output type
PSOut = TypeVar("PSOut")  # server output type


class ParameterServerClient(abc.ABC, Generic[P, WOut]):
    """What worker logic calls: ``pull`` / ``push`` / ``output``.

    Mirrors the reference's ``ParameterServerClient`` (SURVEY.md §2 #4).
    """

    @abc.abstractmethod
    def pull(self, param_id: int) -> None:
        """Request the current value of ``param_id``; the answer arrives
        asynchronously via ``WorkerLogic.on_pull_recv``."""

    @abc.abstractmethod
    def push(self, param_id: int, delta: P) -> None:
        """Send a delta to be folded into the stored value."""

    @abc.abstractmethod
    def output(self, w_out: WOut) -> None:
        """Emit a record on the worker-output stream."""


class WorkerLogic(abc.ABC, Generic[T, P, WOut]):
    """User hook driving training, invoked per input record and per pull
    answer.  Mirrors the reference's ``WorkerLogic`` trait
    (SURVEY.md §2 #2: ``onRecv`` / ``onPullRecv`` / ``close``)."""

    @abc.abstractmethod
    def on_recv(self, data: T, ps: ParameterServerClient[P, WOut]) -> None:
        """Called once per training record delivered to this worker."""

    @abc.abstractmethod
    def on_pull_recv(
        self, param_id: int, param_value: P, ps: ParameterServerClient[P, WOut]
    ) -> None:
        """Called once per pull answer addressed to this worker."""

    def close(self) -> None:  # noqa: B027 — optional hook
        """Called when the input is exhausted and the loop has drained."""


class ParameterServer(abc.ABC, Generic[P, PSOut]):
    """Server-side callback interface handed to ``ParameterServerLogic``.

    Mirrors the reference's ``ParameterServer`` iface
    (``answerPull(id, value, workerIdx)`` / ``output(psOut)``)."""

    @abc.abstractmethod
    def answer_pull(self, param_id: int, value: P, worker_idx: int) -> None:
        ...

    @abc.abstractmethod
    def output(self, ps_out: PSOut) -> None:
        ...


class ParameterServerLogic(abc.ABC, Generic[P, PSOut]):
    """Server hook per pull/push.  Mirrors the reference's
    ``ParameterServerLogic`` (SURVEY.md §2 #3)."""

    @abc.abstractmethod
    def on_pull_recv(
        self, param_id: int, worker_idx: int, ps: ParameterServer[P, PSOut]
    ) -> None:
        ...

    @abc.abstractmethod
    def on_push_recv(
        self, param_id: int, delta: P, ps: ParameterServer[P, PSOut]
    ) -> None:
        ...

    def close(self, ps: ParameterServer[P, PSOut]) -> None:  # noqa: B027
        """Input exhausted: typically dumps the final model to the PS-output
        stream (the reference's "flush model on close", SURVEY.md §3.5)."""


class SimplePSLogic(ParameterServerLogic[P, PSOut]):
    """Default server logic: in-memory keyed store with user ``init`` and
    ``update`` functions — the reference's ``SimplePSLogic`` backed by a
    ``HashMap[Int, P]`` with ``getOrElseUpdate`` semantics.

    On close, dumps every ``(id, value)`` pair to the server-output stream.
    """

    def __init__(
        self,
        init: Callable[[int], P],
        update: Callable[[P, P], P],
    ) -> None:
        self.init = init
        self.update = update
        self.store: dict[int, P] = {}

    def on_pull_recv(self, param_id, worker_idx, ps):
        if param_id not in self.store:
            self.store[param_id] = self.init(param_id)
        ps.answer_pull(param_id, self.store[param_id], worker_idx)

    def on_push_recv(self, param_id, delta, ps):
        if param_id not in self.store:
            self.store[param_id] = self.init(param_id)
        self.store[param_id] = self.update(self.store[param_id], delta)

    def close(self, ps):
        for param_id, value in self.store.items():
            ps.output((param_id, value))


class _PullLimitedClient(ParameterServerClient[P, WOut]):
    """Client wrapper enforcing a bound on in-flight pulls per worker."""

    def __init__(self, inner: ParameterServerClient[P, WOut], limiter: "_PullLimiter"):
        self._inner = inner
        self._limiter = limiter

    def pull(self, param_id: int) -> None:
        self._limiter.request(param_id, self._inner)

    def push(self, param_id: int, delta) -> None:
        self._inner.push(param_id, delta)

    def output(self, w_out) -> None:
        self._inner.output(w_out)


class _PullLimiter:
    def __init__(self, limit: int):
        self.limit = limit
        self.in_flight = 0
        self.queue: collections.deque = collections.deque()

    def request(self, param_id: int, client: ParameterServerClient) -> None:
        if self.in_flight < self.limit:
            self.in_flight += 1
            client.pull(param_id)
        else:
            self.queue.append(param_id)

    def on_answer(self, client: ParameterServerClient) -> None:
        self.in_flight -= 1
        while self.queue and self.in_flight < self.limit:
            self.in_flight += 1
            client.pull(self.queue.popleft())

    def inflight(self) -> int:
        """Pulls issued but not yet answered — the pipelining depth the
        limiter is currently using (<= ``limit``; queued requests are
        NOT in flight).  Exposed so the telemetry plane can watch a
        worker's pull pipeline live instead of inferring it."""
        return self.in_flight

    def queued(self) -> int:
        """Pulls waiting for a window slot (the backpressure signal)."""
        return len(self.queue)


class _PullLimitedWorker(WorkerLogic[T, P, WOut]):
    def __init__(self, inner: WorkerLogic[T, P, WOut], limit: int):
        self._inner = inner
        self._limiter = _PullLimiter(limit)

    @property
    def limiter(self) -> _PullLimiter:
        """The wrapped limiter (its ``inflight()``/``queued()`` are the
        observability surface ``add_pull_limiter`` registers as gauges)."""
        return self._limiter

    def on_recv(self, data, ps):
        self._inner.on_recv(data, _PullLimitedClient(ps, self._limiter))

    def on_pull_recv(self, param_id, param_value, ps):
        self._limiter.on_answer(ps)
        self._inner.on_pull_recv(param_id, param_value, _PullLimitedClient(ps, self._limiter))

    def close(self):
        self._inner.close()


def add_pull_limiter(
    worker_logic: WorkerLogic[T, P, WOut],
    limit: int,
    *,
    registry=None,
    worker: Optional[str] = None,
) -> WorkerLogic[T, P, WOut]:
    """Bound the number of in-flight pulls per worker — the reference's
    ``WorkerLogic.addPullLimiter`` (SURVEY.md §2 #2).  Excess pulls queue on
    the worker and are issued as answers come back.

    The limiter's window usage is observable live: ``inflight_pulls``
    and ``queued_pulls`` probe gauges (``component=train``, plus a
    ``worker=`` label when given) register on ``registry`` — default the
    process-wide one — so a pipeline stuck at its window (inflight
    pinned at ``limit``, queue growing) shows on ``/metrics`` instead of
    being invisible inside the event loop.  ``registry=False`` opts out
    (pure-unit tests)."""
    wrapped = _PullLimitedWorker(worker_logic, limit)
    if registry is not False:
        # lazy import: core/ must not import telemetry/ at module load
        # (telemetry is a leaf plane, core is the trunk)
        from ..telemetry.registry import get_registry

        reg = registry if registry is not None else get_registry()
        labels = {"worker": worker} if worker is not None else {}
        reg.gauge(
            "inflight_pulls", component="train",
            fn=wrapped.limiter.inflight, **labels,
        )
        reg.gauge(
            "queued_pulls", component="train",
            fn=wrapped.limiter.queued, **labels,
        )
    return wrapped


__all__ = [
    "ParameterServerClient",
    "WorkerLogic",
    "ParameterServer",
    "ParameterServerLogic",
    "SimplePSLogic",
    "add_pull_limiter",
]
