"""Combination (batching) senders for the event backend.

Reference parity (SURVEY.md §2 #6): the reference's pluggable
client/server senders — "simple" 1:1 variants plus *combination* variants
that buffer messages and flush on a count and/or timer trigger — exist to
amortise Flink's per-message serialization/network cost.

In the compiled TPU backend the microbatch itself is the combination
buffer (count trigger ≡ batch size; see ops/dedup.py), so this module only
serves the host event backend: it reproduces the observable semantics of
message batching (bursty delivery, reordering across the flush boundary)
for migration tests.  The "timer" is the event loop's logical clock (one
tick per delivered event) — deterministic, unlike the reference's
wall-clock timers (SURVEY.md §4's ordering caveat becomes testable).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class SenderPolicy:
    """Flush policy for a buffering sender.

    count: flush when this many messages are buffered (1 = simple sender,
    i.e. the reference's non-combination variant).
    interval: also flush every `interval` logical ticks of the event loop
    (None = count-only).
    """

    count: int = 1
    interval: Optional[int] = None

    def __post_init__(self):
        assert self.count >= 1
        assert self.interval is None or self.interval >= 1


SIMPLE = SenderPolicy(count=1)


class BufferingSender:
    """Accumulates outgoing messages; ``poll``/``force`` return what to
    deliver now.  Used for both directions (client→PS and PS→worker)."""

    def __init__(self, policy: SenderPolicy):
        self.policy = policy
        self.buffer: List = []
        self.last_flush_tick = 0

    def offer(self, message, tick: int) -> List:
        self.buffer.append(message)
        if len(self.buffer) >= self.policy.count:
            return self.flush(tick)
        return []

    def poll(self, tick: int) -> List:
        """Timer check: flush if the interval elapsed."""
        if (
            self.policy.interval is not None
            and self.buffer
            and tick - self.last_flush_tick >= self.policy.interval
        ):
            return self.flush(tick)
        return []

    def flush(self, tick: int) -> List:
        out, self.buffer = self.buffer, []
        self.last_flush_tick = tick
        return out


__all__ = ["SenderPolicy", "BufferingSender", "SIMPLE"]
