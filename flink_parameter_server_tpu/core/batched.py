"""Batched (jit-compiled) worker API — the TPU programming model.

Reference parity: this is the compiled counterpart of the reference's
``WorkerLogic`` trait (SURVEY.md §2 #2).  Where the reference invokes
``onRecv`` per record and ``onPullRecv`` per answer on a JVM thread, the TPU
rebuild processes a *microbatch of events per jitted step*:

    ids            = logic.keys(batch)                # which params to pull
    pulled         = store.pull(ids)                  # sharded gather
    state', req, o = logic.step(state, batch, pulled) # the "training math"
    store'         = store.push(req.ids, req.deltas)  # sharded scatter-add

The worker's mutable local state (e.g. MF user vectors) is an explicit
pytree threaded through ``step`` — data-parallel across the ``dp`` mesh axis
the way the reference's worker state is partitioned across
``workerParallelism`` subtasks.
"""
from __future__ import annotations

import abc
import dataclasses
from typing import Any, Generic, Optional, Tuple, TypeVar

import jax

Array = jax.Array
State = TypeVar("State")
Batch = TypeVar("Batch")
Out = TypeVar("Out")


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PushRequest:
    """A microbatch of pushes: fold ``deltas[i]`` into param ``ids[i]``.

    ``mask`` marks valid lanes (padding-friendly static shapes)."""

    ids: Array
    deltas: Array
    mask: Optional[Array] = None


class BatchedWorkerLogic(abc.ABC, Generic[State, Batch, Out]):
    """Pure-functional worker logic compiled into the jitted step."""

    @abc.abstractmethod
    def init_state(self, rng: Array) -> State:
        """Create the worker-local state pytree (sharded along ``dp``)."""

    @abc.abstractmethod
    def keys(self, batch: Batch) -> Array:
        """Param ids this microbatch needs pulled (static shape; pad +
        mask for variable counts)."""

    @abc.abstractmethod
    def step(
        self, state: State, batch: Batch, pulled: Array
    ) -> Tuple[State, PushRequest, Out]:
        """One compiled training step over the microbatch."""

    def finish(self, state: State) -> Any:  # noqa: B027
        """Optional close-time worker output (e.g. dump local user
        vectors) — counterpart of ``WorkerLogic.close``."""
        return None

    def per_record_leaves(self, batch: Batch) -> Any:
        """Optional presort contract: a pytree of bools with ``batch``'s
        structure, True for leaves indexed per record (leading dim =
        record index).  When overridden, ``presort=True`` permutes
        exactly the True leaves and VALIDATES their leading dims at
        trace time — replacing the shape-based default (permute every
        leaf whose leading dim equals the key count), whose documented
        trap is a non-per-record leaf that coincidentally matches the
        batch size (e.g. a (batch, d) per-step constant table).
        Return ``None`` (the default) to keep the heuristic."""
        return None


__all__ = ["PushRequest", "BatchedWorkerLogic"]
