"""``transform`` — the framework entry point.

Reference parity: re-founds ``FlinkParameterServer.transform`` and its
overload family (SURVEY.md §2 #1, §3.1): wire a training stream + worker
logic + server logic together, return the multiplexed worker/server output
streams.  The reference's Flink iteration (feedback edge, per-message Netty
hops, ``iterationWaitTime`` silence-timeout shutdown) is replaced by:

  * ``backend="tpu"`` (the point of this framework): a microbatch of events
    per jitted step; pull = sharded gather, push = sharded scatter-add, all
    collectives over ICI.  Termination is explicit: the input iterator ends,
    the final parameter dump is emitted — no silence-timeout hack
    (SURVEY.md §7 "Termination/close semantics").

  * ``backend="local"``: a host-side event loop running the *exact*
    reference callback API (``on_recv`` / ``on_pull_recv`` / ``answer_pull``)
    with FIFO message queues between worker and server partitions — the
    semantics-fidelity harness (races included when ``input_window`` > 1)
    and the migration path for arbitrary Python logics.
"""
from __future__ import annotations

import collections
import dataclasses
import itertools
import zlib
from typing import Any, Callable, Generic, Iterable, List, Optional, Tuple, TypeVar, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .api import (
    ParameterServer,
    ParameterServerClient,
    ParameterServerLogic,
    SimplePSLogic,
    WorkerLogic,
)
from .batched import BatchedWorkerLogic
from .entities import Pull, PullAnswer, Push, PSToWorker, WorkerToPS
from .store import ShardedParamStore
from ..parallel.mesh import DP_AXIS

T = TypeVar("T")
P_ = TypeVar("P_")
WOut = TypeVar("WOut")
PSOut = TypeVar("PSOut")


def jnp_copy(x):
    """Device-resident copy preserving sharding (for donation safety)."""
    return jnp.copy(x) if isinstance(x, jax.Array) else x


def stable_route_hash(key) -> int:
    """Routing hash for ``hash(paramId) % psParallelism`` that is stable
    across processes (Python's ``hash`` is PYTHONHASHSEED-randomised for
    strings, which would break cross-process determinism of the event
    backend).  Ints keep identity semantics, matching the reference's
    ``paramId.hashCode`` for Scala Ints."""
    if isinstance(key, bool):
        return int(key)
    if isinstance(key, (int, np.integer)):
        return int(key)
    if isinstance(key, str):
        return zlib.crc32(key.encode("utf-8"))
    if isinstance(key, bytes):
        return zlib.crc32(key)
    return zlib.crc32(repr(key).encode("utf-8"))


@dataclasses.dataclass
class TransformResult(Generic[WOut, PSOut]):
    """The two multiplexed output streams of a PS job — the reference
    returns them as one ``DataStream[Either[WOut, PSOut]]``; we keep them
    separate and offer :meth:`either` for parity."""

    worker_outputs: List[Any]
    server_outputs: List[Any]
    store: Optional[ShardedParamStore] = None
    worker_state: Any = None

    def either(self) -> List[Tuple[str, Any]]:
        return [("left", w) for w in self.worker_outputs] + [
            ("right", s) for s in self.server_outputs
        ]


# ---------------------------------------------------------------------------
# Local (event) backend — reference-exact callback semantics on the host.
# ---------------------------------------------------------------------------


class _LocalClient(ParameterServerClient):
    def __init__(self, runtime: "_LocalRuntime", worker_idx: int):
        self._rt = runtime
        self._widx = worker_idx

    def pull(self, param_id: int) -> None:
        self._rt.send_w2ps(self._widx, WorkerToPS(self._widx, Pull(param_id)))

    def push(self, param_id: int, delta) -> None:
        self._rt.send_w2ps(
            self._widx, WorkerToPS(self._widx, Push(param_id, delta))
        )

    def output(self, w_out) -> None:
        self._rt.worker_outputs.append(w_out)


class _LocalPSIface(ParameterServer):
    def __init__(self, runtime: "_LocalRuntime", server_idx: int):
        self._rt = runtime
        self._sidx = server_idx

    def answer_pull(self, param_id: int, value, worker_idx: int) -> None:
        self._rt.send_ps2w(
            self._sidx, PSToWorker(worker_idx, PullAnswer(param_id, value))
        )

    def output(self, ps_out) -> None:
        self._rt.server_outputs.append(ps_out)


class _LocalRuntime:
    """Single FIFO event loop emulating the Flink iteration.

    Input records are admitted up to ``input_window`` ahead of message
    processing, so pulls/pushes from different workers interleave — the
    async-hazard surface of the reference (SURVEY.md §3.2) reproduced
    deterministically.
    """

    def __init__(
        self,
        worker_logics: List[WorkerLogic],
        ps_logics: List[ParameterServerLogic],
        partitioner: Optional[Callable[[Any, int], int]],
        input_window: int,
        client_sender: Optional["SenderPolicy"] = None,
        ps_sender: Optional["SenderPolicy"] = None,
    ):
        from .senders import SIMPLE, BufferingSender

        self.workers = worker_logics
        self.servers = ps_logics
        self.partitioner = partitioner
        self.input_window = max(1, input_window)
        self.events: collections.deque = collections.deque()
        self.worker_outputs: List[Any] = []
        self.server_outputs: List[Any] = []
        self.ps_ifaces = [
            _LocalPSIface(self, s) for s in range(len(self.servers))
        ]
        self.clients = [_LocalClient(self, i) for i in range(len(self.workers))]
        self.tick = 0
        self.client_senders = [
            BufferingSender(client_sender or SIMPLE) for _ in self.workers
        ]
        self.ps_senders = [
            BufferingSender(ps_sender or SIMPLE) for _ in self.servers
        ]
        # only interval-triggered senders ever flush from poll(); the
        # default SIMPLE config leaves this empty (zero per-event cost)
        self._interval_senders = [
            ("w2ps", s)
            for s in self.client_senders
            if s.policy.interval is not None
        ] + [
            ("ps2w", s)
            for s in self.ps_senders
            if s.policy.interval is not None
        ]

    # -- sender plumbing (the combination-sender layer, SURVEY.md §2 #6) --
    def send_w2ps(self, worker_idx: int, msg: WorkerToPS) -> None:
        for m in self.client_senders[worker_idx].offer(msg, self.tick):
            self.events.append(("w2ps", m))

    def send_ps2w(self, server_idx: int, msg: PSToWorker) -> None:
        for m in self.ps_senders[server_idx].offer(msg, self.tick):
            self.events.append(("ps2w", m))

    def _poll_senders(self) -> None:
        for tag, s in self._interval_senders:
            for m in s.poll(self.tick):
                self.events.append((tag, m))

    def _force_flush_senders(self) -> bool:
        flushed = False
        for s in self.client_senders:
            for m in s.flush(self.tick):
                self.events.append(("w2ps", m))
                flushed = True
        for s in self.ps_senders:
            for m in s.flush(self.tick):
                self.events.append(("ps2w", m))
                flushed = True
        return flushed

    def _route_server(self, param_id: int) -> int:
        # The reference's partitionCustom(hash(paramId) % psParallelism),
        # with a PYTHONHASHSEED-independent hash for determinism.
        return stable_route_hash(param_id) % len(self.servers)

    def run(self, data: Iterable) -> None:
        it = iter(data)
        rr = itertools.cycle(range(len(self.workers)))
        exhausted = False
        in_window = 0
        while True:
            # Admit inputs up to the window.
            while not exhausted and in_window < self.input_window:
                try:
                    record = next(it)
                except StopIteration:
                    exhausted = True
                    break
                widx = (
                    self.partitioner(record, len(self.workers))
                    if self.partitioner
                    else next(rr)
                )
                self.events.append(("input", widx, record))
                in_window += 1
            if not self.events:
                if exhausted:
                    # input done and queue drained: force any buffered
                    # combination-sender messages out before concluding
                    # (the reference's timeout-flush, made explicit)
                    if self._force_flush_senders():
                        continue
                    break
                continue
            ev = self.events.popleft()
            self.tick += 1
            if ev[0] == "input":
                _, widx, record = ev
                in_window -= 1
                self.workers[widx].on_recv(record, self.clients[widx])
            elif ev[0] == "w2ps":
                msg: WorkerToPS = ev[1]
                sidx = self._route_server(msg.message.param_id)
                if isinstance(msg.message, Pull):
                    self.servers[sidx].on_pull_recv(
                        msg.message.param_id,
                        msg.worker_partition_index,
                        self.ps_ifaces[sidx],
                    )
                else:
                    self.servers[sidx].on_push_recv(
                        msg.message.param_id,
                        msg.message.delta,
                        self.ps_ifaces[sidx],
                    )
            else:  # ps2w
                msg2: PSToWorker = ev[1]
                self.workers[msg2.worker_partition_index].on_pull_recv(
                    msg2.answer.param_id,
                    msg2.answer.value,
                    self.clients[msg2.worker_partition_index],
                )
            self._poll_senders()
        # Drain: input exhausted and all in-flight messages delivered →
        # fire close hooks (the reference's iterationWaitTime-timeout moment,
        # made explicit).
        for w in self.workers:
            w.close()
        for sidx, s in enumerate(self.servers):
            s.close(self.ps_ifaces[sidx])


def _instances(factory_or_instance, n: int, what: str) -> List[Any]:
    if callable(factory_or_instance) and not isinstance(
        factory_or_instance, (WorkerLogic, ParameterServerLogic)
    ):
        return [factory_or_instance() for _ in range(n)]
    if n != 1:
        raise ValueError(
            f"{what} parallelism {n} > 1 requires a zero-arg factory, got an "
            f"instance (stateful logics cannot be shared across partitions)"
        )
    return [factory_or_instance]


# ---------------------------------------------------------------------------
# TPU (batched) backend — the compiled hot path.
# ---------------------------------------------------------------------------


def make_train_step(
    logic: BatchedWorkerLogic,
    spec,
    *,
    presort: bool = False,
) -> Callable:
    """Build the fused pull→compute→push step (to be jit-compiled).

    One call = one microbatch of "events": the reference's per-message hot
    loop (SURVEY.md §3.1) collapsed into gather → math → scatter-add with
    zero host round-trips.

    ``presort=True``: re-order the whole microbatch by ascending store
    key on-device before the pull.  Random-row HBM traffic is the MF
    step's measured bottleneck (r2 trace: gather + scatter at ~3% of
    HBM peak); sorting makes the pull gather walk ascending addresses
    and hands the push an ``ids_sorted`` promise, so the plain scatter
    gets ``indices_are_sorted`` and the "xla_sorted" dedup skips its
    own argsort — one TPU sort (0.03 ms @64k, 1.3% of the r2 step) buys
    locality on every table touch.  Sorting changes f32 summation order
    only (same set of updates per row).  Worker outputs come back in
    SORTED order; per-record output consumers that need stream order
    should keep presort off.

    Caveat: by default "the whole microbatch" means every pytree leaf
    whose leading dimension equals the key count — that is the
    per-record contract of :mod:`..data.streams` batches.  A logic
    whose batch carries a NON-per-record array that coincidentally has
    the batch size as its leading dim (e.g. a (batch, d) per-step
    constant table) would get its rows permuted too — such logics
    should override ``BatchedWorkerLogic.per_record_leaves`` to declare
    exactly which leaves are per-record, which both exempts the
    constants and turns the convention into a trace-time-validated
    contract (a declared leaf with the wrong leading dim raises).
    """
    from . import store as store_mod

    def step(table, state, batch):
        if presort:
            ids_pre = logic.keys(batch)
            ids0 = jnp.asarray(ids_pre).astype(jnp.int32)
            if ids0.ndim != 1:
                # multi-pull logics (e.g. PA: (B, K) feature ids) have
                # no single per-record sort key — argsort along the
                # wrong axis would silently permute garbage
                raise ValueError(
                    f"presort=True needs 1-D store keys, got shape "
                    f"{tuple(ids0.shape)} (multi-pull logics are not "
                    f"presortable)"
                )
            # sort by the ROUTED key (negatives at the END, on the
            # sentinel push itself uses) so the order survives push's
            # negative-lane routing and the ids_sorted promise is honest
            routed = jnp.where(
                ids0 < 0, jnp.int32(spec.padded_capacity), ids0
            )
            order = jnp.argsort(routed)
            n = ids0.shape[0]
            marks = logic.per_record_leaves(batch)
            if marks is not None:
                # declared contract: permute exactly the marked leaves,
                # and validate the declaration at trace time
                def _permute_marked(x, m):
                    if not m:
                        return x
                    if getattr(x, "ndim", 0) < 1 or x.shape[0] != n:
                        raise ValueError(
                            f"per_record_leaves declared a leaf of shape "
                            f"{getattr(x, 'shape', None)} per-record, but "
                            f"the batch has {n} records"
                        )
                    return jnp.take(x, order, axis=0)

                batch = jax.tree.map(_permute_marked, batch, marks)
                # the declaration must cover the KEYS leaf: if it was
                # left unmarked, the batch keys stay unsorted while the
                # push-identity check below would still hand the sorted
                # scatter an honest-looking ids_sorted=True — a lie XLA
                # may miscompile.  Same trace-time identity trick: an
                # unpermuted keys leaf comes back as the same object.
                if logic.keys(batch) is ids_pre:
                    raise ValueError(
                        "per_record_leaves did not mark the leaf that "
                        "logic.keys(batch) returns — the sort keys "
                        "themselves must be declared per-record for "
                        "presort=True"
                    )
            else:
                # shape heuristic (see docstring caveat)
                batch = jax.tree.map(
                    lambda x: (
                        jnp.take(x, order, axis=0)
                        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == n
                        else x
                    ),
                    batch,
                )
        ids = logic.keys(batch)
        pulled = store_mod.pull(spec, table, ids)
        state, req, out = logic.step(state, batch, pulled)
        # the sorted promise holds only if the logic pushes the very ids
        # it pulled — trace-time object identity is exactly that check
        # (a logic pushing derived/other ids gets the unsorted path)
        table = store_mod.push(
            spec, table, req.ids, req.deltas, req.mask,
            ids_sorted=presort and (req.ids is ids),
        )
        return table, state, out

    return step


def scan_group_sharding(batch_sharding):
    """Sharding for (K, batch, ...)-stacked scan inputs: the scan axis
    prepends as unsharded, the per-batch spec shifts right.  ``None``
    passes through; sharding types without a named PartitionSpec are
    rejected loudly — silently skipping the reshard would strand a
    dp-sharded caller's data replicated on the default device."""
    if batch_sharding is None:
        return None
    spec = getattr(batch_sharding, "spec", None)
    if spec is None:
        raise ValueError(
            f"steps_per_call > 1 needs a NamedSharding batch sharding "
            f"(got {type(batch_sharding).__name__}): extending the "
            f"leading scan axis is only defined for named PartitionSpecs"
        )
    return NamedSharding(batch_sharding.mesh, PartitionSpec(None, *spec))


def stack_group(group, scan_sharding=None):
    """Stack K microbatches into (K, ...) leaves for a scanned dispatch.

    Stacks on the HOST (the data iterator yields host arrays — the
    ingestion edge), then ships each byte exactly once: ``jnp.stack``
    would commit a replicated default-device copy first and the reshard
    would move the same bytes a second time.  Device-resident leaves are
    pulled to the host once (np.asarray) — callers chasing the last
    transfer should feed host arrays, as the loaders do."""
    stacked = jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *group
    )
    if scan_sharding is not None:
        stacked = jax.tree.map(
            lambda x: jax.device_put(x, scan_sharding), stacked
        )
    return stacked


def make_scan_train_step(
    logic: BatchedWorkerLogic,
    spec,
    *,
    presort: bool = False,
) -> Callable:
    """K train steps inside ONE jitted call: ``batches`` is a pytree of
    (K, batch, ...) leaves; a ``lax.scan`` runs :func:`make_train_step`'s
    body K times on-device and returns (K, ...)-stacked outputs.

    Dispatch amortization is the point: one host→device round trip per
    K microbatches instead of per microbatch — the collective-era
    analogue of the reference's combination senders (SURVEY.md §2 #6
    batches *messages* to cut per-message overhead; this batches
    *dispatches* to cut per-step host overhead, which on a remote-TPU
    link is ~75 ms of tunnel RTT vs a ~2 ms device step, r2 bench rows).
    MEASURED (benchmarks/steps_per_call_latency.py, injected-RTT CPU
    harness; results/cpu/steps_per_call_latency.md): at 75 ms injected
    RTT, K=64 runs 50x the K=1 rate (2.59M vs 0.052M updates/sec) and
    the curve is still rising at K=64 — choose K >= rtt/t_step; K=64 is
    the recommended default over this image's tunnel.
    """
    base = make_train_step(logic, spec, presort=presort)

    def step(table, state, batches):
        def body(carry, b):
            t, s = carry
            t, s, out = base(t, s, b)
            return (t, s), out

        (table, state), outs = jax.lax.scan(body, (table, state), batches)
        return table, state, outs

    return step


def transform_batched(
    data: Iterable,
    worker_logic: BatchedWorkerLogic,
    store: ShardedParamStore,
    *,
    rng: Optional[jax.Array] = None,
    mesh: Optional[Mesh] = None,
    dp_axis: str = DP_AXIS,
    collect_outputs: bool = True,
    dump_model: bool = True,
    on_step: Optional[Callable[[int, Any], None]] = None,
    state_callback: Optional[Callable[[int, Any, Any, Any], None]] = None,
    group_callback: Optional[
        Callable[[int, int, Any, Any, Any], None]
    ] = None,
    initial_state: Any = None,
    skip_batches: int = 0,
    presort: bool = False,
    steps_per_call: int = 1,
) -> TransformResult:
    """Run the compiled PS loop over an iterable of microbatches.

    ``state_callback(step_idx, table, state, out)`` additionally sees the
    live (donated-next-step) table/state — the hook the StreamingDriver
    uses for metrics, checkpoints and profiling windows without
    duplicating this loop.  ``skip_batches`` fast-forwards the iterator
    (resume-from-cursor); ``initial_state`` overrides
    ``worker_logic.init_state`` (restored worker state); ``presort``
    sorts each microbatch by store key on-device before the pull (HBM
    locality — see :func:`make_train_step`; worker outputs then come
    back in sorted, not stream, order).

    ``steps_per_call=K`` runs K microbatches per jitted dispatch via
    :func:`make_scan_train_step` — one host round trip per K steps
    (essential when host↔device latency rivals the step time; a
    trailing group shorter than K runs through the single-step program).
    Per-step semantics are unchanged; ``on_step``/``collect_outputs``
    still see one entry per microbatch (unstacked on the host).  The
    unstacked entries are real slices, not views: jax.Array indexing
    dispatches an XLA slice producing an independent buffer, so
    retaining ``worker_outputs`` does NOT pin the (K, ...) scan output
    alive (verified empirically — a retained ``x[0]`` of a 256 MiB
    stack leaves 4 MiB live).
    ``state_callback`` needs the live table BETWEEN steps, which a scan
    cannot surface — combining it with ``steps_per_call > 1`` raises.

    ``group_callback(first_step_idx, n_steps, table, state, outs)`` is
    the GROUP-granular sibling: it fires once per jitted dispatch (any
    ``steps_per_call``) with the live (donated-next-dispatch)
    table/state and the dispatch's RAW output — the single step's
    ``out`` when ``n_steps == 1``, the (K, ...)-stacked scan output
    otherwise (no forced host unstacking; finiteness checks and other
    whole-group reductions work on either form).  This is what lets the
    StreamingDriver run with ``steps_per_call > 1``: checkpoint / NaN /
    metrics cadence rounds up to dispatch boundaries — the honest
    granularity, since between scanned steps there is no host-visible
    table at all.
    """
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    spec = store.spec
    mesh = mesh or spec.mesh
    if steps_per_call < 1:
        raise ValueError(f"steps_per_call={steps_per_call}: must be >= 1")
    if steps_per_call > 1 and state_callback is not None:
        raise ValueError(
            "steps_per_call > 1 cannot surface the live table between "
            "steps; use steps_per_call=1 with state_callback (the "
            "StreamingDriver's checkpoint/metrics hook needs per-step "
            "access)"
        )

    step = jax.jit(
        make_train_step(worker_logic, spec, presort=presort),
        donate_argnums=(0, 1),
    )
    scan_step = None
    if steps_per_call > 1:
        scan_step = jax.jit(
            make_scan_train_step(worker_logic, spec, presort=presort),
            donate_argnums=(0, 1),
        )
    # The jitted step donates (table, state); start from copies so the
    # caller's store (and any restored state they still hold) stays valid
    # — the same contract transform_dense gives (dense.py).  A fresh
    # init_state has no outside owner, so only restored state is copied.
    state = (
        jax.tree.map(jnp_copy, initial_state)
        if initial_state is not None
        else worker_logic.init_state(rng)
    )

    batch_sharding = None
    if mesh is not None and dp_axis in mesh.axis_names and mesh.shape[dp_axis] > 1:
        batch_sharding = NamedSharding(mesh, PartitionSpec(dp_axis))

    # the scanned program consumes (K, batch, ...) leaves: the dp shard
    # moves to axis 1 (axis 0 is scan time, resident on every device)
    scan_sharding = (
        scan_group_sharding(batch_sharding) if steps_per_call > 1 else None
    )

    table = jnp_copy(store.table)
    worker_outputs: List[Any] = []
    step_idx = 0

    def _run_one(table, state, batch, step_idx):
        if batch_sharding is not None:
            batch = jax.tree.map(
                lambda x: jax.device_put(x, batch_sharding), batch
            )
        table, state, out = step(table, state, batch)
        if on_step is not None:
            on_step(step_idx, out)
        if state_callback is not None:
            state_callback(step_idx, table, state, out)
        if group_callback is not None:
            group_callback(step_idx, 1, table, state, out)
        if collect_outputs:
            worker_outputs.append(out)
        return table, state

    def _run_group(table, state, group, first_idx):
        stacked = stack_group(group, scan_sharding)
        table, state, outs = scan_step(table, state, stacked)
        if on_step is not None or collect_outputs:
            for i in range(len(group)):
                out_i = jax.tree.map(lambda x: x[i], outs)
                if on_step is not None:
                    on_step(first_idx + i, out_i)
                if collect_outputs:
                    worker_outputs.append(out_i)
        if group_callback is not None:
            # raw stacked outs — whole-group reductions (finiteness) are
            # cheaper on the stack than on K unstacked slices
            group_callback(first_idx, len(group), table, state, outs)
        return table, state

    group: List[Any] = []
    for batch in data:
        if skip_batches > 0:
            skip_batches -= 1
            step_idx += 1
            continue
        if steps_per_call == 1:
            table, state = _run_one(table, state, batch, step_idx)
            step_idx += 1
            continue
        group.append(batch)
        if len(group) == steps_per_call:
            table, state = _run_group(table, state, group, step_idx)
            step_idx += len(group)
            group = []
    # trailing group shorter than K: the single-step program (a second
    # compile only when a tail exists) — never a ragged-K recompile
    for batch in group:
        table, state = _run_one(table, state, batch, step_idx)
        step_idx += 1

    final_store = ShardedParamStore(spec, table)
    server_outputs: List[Any] = []
    if dump_model:
        # close()-time model flush (reference §3.5): emit the final table.
        server_outputs.append(
            (np.arange(spec.capacity), np.asarray(final_store.values()))
        )
    finish = worker_logic.finish(state)
    if finish is not None:
        worker_outputs.append(finish)
    return TransformResult(
        worker_outputs=worker_outputs,
        server_outputs=server_outputs,
        store=final_store,
        worker_state=state,
    )


# ---------------------------------------------------------------------------
# The public overload family.
# ---------------------------------------------------------------------------


def transform(
    data: Iterable,
    worker_logic: Union[WorkerLogic, Callable[[], WorkerLogic], BatchedWorkerLogic],
    ps_logic: Union[
        ParameterServerLogic,
        Callable[[], ParameterServerLogic],
        ShardedParamStore,
        None,
    ] = None,
    *,
    param_init: Optional[Callable[[int], Any]] = None,
    param_update: Optional[Callable[[Any, Any], Any]] = None,
    worker_parallelism: int = 1,
    ps_parallelism: int = 1,
    iteration_wait_time: Optional[float] = None,  # accepted for parity; unused
    partitioner: Optional[Callable[[Any, int], int]] = None,
    input_window: Optional[int] = None,
    client_sender=None,  # SenderPolicy: client→PS combination batching
    ps_sender=None,  # SenderPolicy: PS→worker combination batching
    **batched_kwargs,
) -> TransformResult:
    """Wire ``data`` + worker logic + server logic into a PS job.

    Overloads (mirroring ``FlinkParameterServer.transform``):

    * ``transform(data, worker, param_init=f, param_update=g, ...)`` —
      simple keyed-store server (the reference's ``SimplePSLogic`` overload).
    * ``transform(data, worker, ps_logic, ...)`` — fully custom server
      logic (event API).
    * ``transform(batches, batched_worker, sharded_store, ...)`` — the
      compiled TPU path.

    ``iteration_wait_time`` is accepted for signature parity with the
    reference but ignored: termination is explicit (input exhaustion), not a
    silence timeout.  ``client_sender``/``ps_sender`` (combination
    batching) apply to the event backend only — on the batched TPU path
    the microbatch itself is the combination buffer, so they are ignored.
    """
    if isinstance(worker_logic, BatchedWorkerLogic):
        if not isinstance(ps_logic, ShardedParamStore):
            raise TypeError(
                "batched worker logic requires a ShardedParamStore server"
            )
        return transform_batched(data, worker_logic, ps_logic, **batched_kwargs)

    if ps_logic is None:
        if param_init is None or param_update is None:
            raise TypeError(
                "provide either ps_logic or (param_init, param_update)"
            )
        ps_logic = lambda: SimplePSLogic(param_init, param_update)  # noqa: E731

    workers = _instances(worker_logic, worker_parallelism, "worker")
    servers = _instances(ps_logic, ps_parallelism, "ps")
    runtime = _LocalRuntime(
        workers,
        servers,
        partitioner,
        input_window if input_window is not None else worker_parallelism,
        client_sender=client_sender,
        ps_sender=ps_sender,
    )
    runtime.run(data)
    return TransformResult(
        worker_outputs=runtime.worker_outputs,
        server_outputs=runtime.server_outputs,
    )


def transform_with_model_load(
    model: Iterable[Tuple[int, Any]],
    data: Iterable,
    worker_logic,
    ps_logic=None,
    **kwargs,
) -> TransformResult:
    """Seed the server from an initial ``(id, value)`` stream before
    training — the reference's ``transformWithModelLoad`` overload
    (SURVEY.md §2 #1, §5 "Checkpoint / resume").

    For the batched path pass a ``ShardedParamStore`` built with
    ``ShardedParamStore.from_values`` instead — this wrapper handles the
    event API.
    """
    model = list(model)

    if isinstance(ps_logic, ShardedParamStore):
        table = ps_logic.table
        ids = np.array([int(i) for i, _ in model])
        vals = jnp.asarray(np.stack([np.asarray(v) for _, v in model]))
        table = table.at[ids].set(vals.astype(table.dtype))
        seeded = ShardedParamStore(ps_logic.spec, table)
        return transform(data, worker_logic, seeded, **kwargs)

    if ps_logic is None:
        param_init = kwargs.pop("param_init", None)
        param_update = kwargs.pop("param_update", None)
        if param_init is None or param_update is None:
            raise TypeError(
                "provide either ps_logic or (param_init, param_update)"
            )
        ps_logic = lambda: SimplePSLogic(param_init, param_update)  # noqa: E731

    # Event path: deliver the model stream as pushes before training data.
    class _Seed(ParameterServer):
        def __init__(self):
            self.outs = []

        def answer_pull(self, *a):  # pragma: no cover - seeds never pull
            raise AssertionError("model-load phase must not answer pulls")

        def output(self, o):
            self.outs.append(o)

    kwargs2 = dict(kwargs)
    ps_par = kwargs2.get("ps_parallelism", 1)
    servers = _instances(ps_logic, ps_par, "ps")
    for pid, value in model:
        target = servers[stable_route_hash(pid) % ps_par]
        if isinstance(target, SimplePSLogic):
            # Model load *sets* the stored value (it is not a delta).
            target.store[pid] = value
        else:
            target.on_push_recv(pid, value, _Seed())

    def server_factory_iter():
        for s in servers:
            yield s

    it = server_factory_iter()
    kwargs2["ps_parallelism"] = ps_par
    return transform(data, worker_logic, lambda: next(it), **kwargs2)


__all__ = [
    "TransformResult",
    "transform",
    "transform_batched",
    "transform_with_model_load",
    "make_train_step",
]
