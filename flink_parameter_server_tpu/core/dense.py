"""DenseParameterServer — the PS API stretched to dense model pytrees.

Reference parity: BASELINE.json config #5 ("Transformer-base LM
data-parallel — dense allreduce — stretch the PS API").  The keyed
``pull(id)/push(id, delta)`` protocol degenerates, for a dense model, to
"pull everything / push one gradient": the server is the full parameter
pytree plus an optimizer, and a push folds the (dp-allreduced) gradient
through the optimizer update.  The allreduce is not written anywhere —
jit + dp-sharded batch shardings make XLA insert the psum over ICI, the
collective-native replacement for the reference's per-key Netty routing.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import jax
import optax

from .transform import TransformResult, jnp_copy

Array = jax.Array
PyTree = Any


class DenseParameterServer:
    """Functional (params, opt_state, optimizer) bundle with pull/push.

    ``pull()`` → the model pytree; ``push(grads)`` → new server with the
    optimizer update applied.  Same contract shape as
    :class:`ShardedParamStore`, with the id space collapsed to "all".
    """

    def __init__(
        self,
        params: PyTree,
        optimizer: optax.GradientTransformation,
        opt_state: Optional[PyTree] = None,
    ):
        self.params = params
        self.optimizer = optimizer
        self.opt_state = (
            opt_state if opt_state is not None else optimizer.init(params)
        )

    def pull(self) -> PyTree:
        return self.params

    def push(self, grads: PyTree) -> "DenseParameterServer":
        updates, new_opt_state = self.optimizer.update(
            grads, self.opt_state, self.params
        )
        new_params = optax.apply_updates(self.params, updates)
        return DenseParameterServer(new_params, self.optimizer, new_opt_state)

    def values(self) -> PyTree:
        """Close-time model dump (reference §3.5)."""
        return self.params


def opt_state_zero1_specs(
    opt_state: PyTree, mesh, dp_axis: str = "dp"
) -> PyTree:
    """Per-leaf ZeRO-1 shardings derived from a CONCRETE opt_state.

    Call this on the freshly-initialized (placed) optimizer state:
    ``optax``'s init builds m/v with ``zeros_like(params)``, so each
    leaf already carries the PARAMS' sharding (tp/sp model-parallel
    layouts included).  For every leaf this merges ``dp`` into the
    first axis that is (a) unsharded in the existing spec and (b)
    divisible by the dp size — composing with model parallelism rather
    than clobbering it (forcing ``P(dp, ...)`` on a tp-sharded leaf
    would *replicate* it across tp and invert the memory win).  Leaves
    with no eligible axis (scalars like Adam's count, or already
    dp-sharded) map to ``None`` = leave alone.
    """
    if dp_axis not in mesh.axis_names:
        raise ValueError(
            f"dp_axis={dp_axis!r} not in mesh axes {mesh.axis_names}"
        )
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp = mesh.shape[dp_axis]

    def spec_for(x):
        if getattr(x, "ndim", 0) < 1:
            return None
        cur: tuple = ()
        sharding = getattr(x, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is not None:
            cur = tuple(spec)
        cur = cur + (None,) * (x.ndim - len(cur))
        used = set()
        for e in cur:
            if isinstance(e, str):
                used.add(e)
            elif isinstance(e, (tuple, list)):
                used.update(e)
        if dp_axis in used:
            return None  # already dp-sharded somewhere
        for i in range(x.ndim):
            if cur[i] is None and x.shape[i] % dp == 0:
                merged = cur[:i] + (dp_axis,) + cur[i + 1:]
                return NamedSharding(mesh, P(*merged))
        return None

    return jax.tree.map(spec_for, opt_state)


def shard_opt_state_constraint(
    opt_state: PyTree, mesh, dp_axis: str = "dp", specs: PyTree = None
) -> PyTree:
    """Cross-replica weight-update sharding (ZeRO-1 done the XLA way).

    Constrain optimizer-state leaves to dp-sharded layouts.  Under jit,
    XLA propagates the constraint backward/forward: the gradient
    allreduce becomes reduce_scatter, each replica runs the optimizer
    math only for its 1/dp parameter slice, and the updates all_gather
    back — same collective bytes as the plain allreduce, but Adam's
    m/v (8 bytes/param fp32) stop being replicated.  This is the
    sharding-annotation form of automatic cross-replica weight-update
    sharding; nothing here hand-schedules a collective.

    ``specs``: pytree from :func:`opt_state_zero1_specs` (None entries =
    leave the leaf alone).  Without it, the fallback shards each leaf's
    LEADING axis over dp when divisible — correct for pure-dp meshes;
    for tp/sp-sharded models pass ``specs`` so dp merges into a free
    axis instead of clobbering the model-parallel layout.
    """
    if dp_axis not in mesh.axis_names:
        raise ValueError(
            f"dp_axis={dp_axis!r} not in mesh axes {mesh.axis_names}"
        )
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp = mesh.shape[dp_axis]

    if specs is not None:
        return jax.tree.map(
            lambda x, s: (
                jax.lax.with_sharding_constraint(x, s) if s is not None
                else x
            ),
            opt_state, specs,
        )

    def constrain(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] % dp == 0:
            spec = P(dp_axis, *([None] * (x.ndim - 1)))
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec)
            )
        return x

    return jax.tree.map(constrain, opt_state)


def make_dense_train_step(
    loss_fn: Callable[[PyTree, Any], Array],
    optimizer: optax.GradientTransformation,
    *,
    mesh=None,
    dp_axis: str = "dp",
    shard_opt_state: bool = False,
    opt_specs: PyTree = None,
) -> Callable:
    """Fused pull → grad → push step (jit this).  ``loss_fn(params,
    batch) -> scalar``; gradients are averaged across the dp axis by XLA
    from the shardings alone.

    ``shard_opt_state=True`` (requires ``mesh``): optimizer state is
    dp-sharded via :func:`shard_opt_state_constraint` — ZeRO-1 memory
    scaling for the dense PS path.  For tp/sp-sharded models also pass
    ``opt_specs=opt_state_zero1_specs(server.opt_state, mesh)`` so dp
    merges into a free axis of each leaf instead of overwriting the
    model-parallel layout."""
    if shard_opt_state:
        if mesh is None:
            raise ValueError("shard_opt_state=True requires mesh")
        if dp_axis not in mesh.axis_names:
            raise ValueError(
                f"dp_axis={dp_axis!r} not in mesh axes {mesh.axis_names}"
            )

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if shard_opt_state:
            opt_state = shard_opt_state_constraint(
                opt_state, mesh, dp_axis, specs=opt_specs
            )
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def transform_dense(
    data: Iterable,
    loss_fn: Callable[[PyTree, Any], Array],
    server: DenseParameterServer,
    *,
    batch_sharding=None,
    on_step: Optional[Callable[[int, Array], None]] = None,
) -> TransformResult:
    """The ``transform`` loop for the dense case: one jitted
    pull→grad→push per microbatch; returns losses as worker outputs and
    the final model as the server dump."""
    step = jax.jit(
        make_dense_train_step(loss_fn, server.optimizer),
        donate_argnums=(0, 1),
    )
    # The jitted step donates its (params, opt_state) arguments; start from
    # copies so the caller's server survives (it is a read-only input).
    params = jax.tree.map(jnp_copy, server.params)
    opt_state = jax.tree.map(jnp_copy, server.opt_state)
    losses: List[Any] = []
    for i, batch in enumerate(data):
        if batch_sharding is not None:
            batch = jax.tree.map(
                lambda x: jax.device_put(x, batch_sharding), batch
            )
        params, opt_state, loss = step(params, opt_state, batch)
        if on_step is not None:
            on_step(i, loss)
        losses.append(loss)
    final = DenseParameterServer(params, server.optimizer, opt_state)
    return TransformResult(
        worker_outputs=losses,
        server_outputs=[final.values()],
        store=None,
        worker_state=None,
    )


__all__ = [
    "DenseParameterServer",
    "make_dense_train_step",
    "opt_state_zero1_specs",
    "shard_opt_state_constraint",
    "transform_dense",
]
