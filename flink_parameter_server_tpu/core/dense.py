"""DenseParameterServer — the PS API stretched to dense model pytrees.

Reference parity: BASELINE.json config #5 ("Transformer-base LM
data-parallel — dense allreduce — stretch the PS API").  The keyed
``pull(id)/push(id, delta)`` protocol degenerates, for a dense model, to
"pull everything / push one gradient": the server is the full parameter
pytree plus an optimizer, and a push folds the (dp-allreduced) gradient
through the optimizer update.  The allreduce is not written anywhere —
jit + dp-sharded batch shardings make XLA insert the psum over ICI, the
collective-native replacement for the reference's per-key Netty routing.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import jax
import optax

from .transform import TransformResult, jnp_copy

Array = jax.Array
PyTree = Any


class DenseParameterServer:
    """Functional (params, opt_state, optimizer) bundle with pull/push.

    ``pull()`` → the model pytree; ``push(grads)`` → new server with the
    optimizer update applied.  Same contract shape as
    :class:`ShardedParamStore`, with the id space collapsed to "all".
    """

    def __init__(
        self,
        params: PyTree,
        optimizer: optax.GradientTransformation,
        opt_state: Optional[PyTree] = None,
    ):
        self.params = params
        self.optimizer = optimizer
        self.opt_state = (
            opt_state if opt_state is not None else optimizer.init(params)
        )

    def pull(self) -> PyTree:
        return self.params

    def push(self, grads: PyTree) -> "DenseParameterServer":
        updates, new_opt_state = self.optimizer.update(
            grads, self.opt_state, self.params
        )
        new_params = optax.apply_updates(self.params, updates)
        return DenseParameterServer(new_params, self.optimizer, new_opt_state)

    def values(self) -> PyTree:
        """Close-time model dump (reference §3.5)."""
        return self.params


def _merged_dp_specs(tree: PyTree, mesh, dp_axis: str) -> PyTree:
    """Per-leaf shardings merging ``dp`` into each CONCRETE leaf's
    existing spec on the first unsharded dp-divisible axis (None =
    leave the leaf alone: scalars, already-dp-sharded, no eligible
    axis).  Composes with tp/sp model-parallel layouts rather than
    clobbering them."""
    if dp_axis not in mesh.axis_names:
        raise ValueError(
            f"dp_axis={dp_axis!r} not in mesh axes {mesh.axis_names}"
        )
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp = mesh.shape[dp_axis]

    def spec_for(x):
        if getattr(x, "ndim", 0) < 1:
            return None
        cur: tuple = ()
        sharding = getattr(x, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is not None:
            cur = tuple(spec)
        cur = cur + (None,) * (x.ndim - len(cur))
        used = set()
        for e in cur:
            if isinstance(e, str):
                used.add(e)
            elif isinstance(e, (tuple, list)):
                used.update(e)
        if dp_axis in used:
            return None  # already dp-sharded somewhere
        for i in range(x.ndim):
            if cur[i] is None and x.shape[i] % dp == 0:
                merged = cur[:i] + (dp_axis,) + cur[i + 1:]
                return NamedSharding(mesh, P(*merged))
        return None

    return jax.tree.map(spec_for, tree)


def opt_state_zero1_specs(
    opt_state: PyTree, mesh, dp_axis: str = "dp"
) -> PyTree:
    """Per-leaf ZeRO-1 shardings derived from a CONCRETE opt_state.

    Call this on the freshly-initialized (placed) optimizer state:
    ``optax``'s init builds m/v with ``zeros_like(params)``, so each
    leaf already carries the PARAMS' sharding (tp/sp model-parallel
    layouts included); ``dp`` merges into the first free divisible axis
    (forcing ``P(dp, ...)`` on a tp-sharded leaf would *replicate* it
    across tp and invert the memory win)."""
    return _merged_dp_specs(opt_state, mesh, dp_axis)


def fsdp_place(params: PyTree, mesh, dp_axis: str = "dp") -> PyTree:
    """FSDP (ZeRO-3 analogue) placement: re-shard CONCRETE params over
    ``dp`` (merged into each leaf's existing tp/sp spec on a free
    axis).  Nothing else changes: under jit, XLA all_gathers a weight
    right where a matmul consumes it and reduce_scatters its gradient —
    the per-layer gather/release schedule FSDP implementations hand-roll
    is GSPMD's normal propagation here.  ``optimizer.init`` on the
    returned params inherits the sharded layout (zeros_like), so
    optimizer state is 1/dp too: params + grads + opt state all scale
    down with the mesh, at the cost of per-use weight all_gathers.
    """
    specs = _merged_dp_specs(params, mesh, dp_axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s) if s is not None else x,
        params, specs,
    )


def shard_opt_state_constraint(
    opt_state: PyTree, mesh, dp_axis: str = "dp", specs: PyTree = None
) -> PyTree:
    """Cross-replica weight-update sharding (ZeRO-1 done the XLA way).

    Constrain optimizer-state leaves to dp-sharded layouts.  Under jit,
    XLA propagates the constraint backward/forward: the gradient
    allreduce becomes reduce_scatter, each replica runs the optimizer
    math only for its 1/dp parameter slice, and the updates rejoin the
    params — same collective bytes as the plain allreduce, but Adam's
    m/v (8 bytes/param fp32) stop being replicated.  Measured
    (benchmarks/zero1_memory.py, 35M-param LM, dp=8): GSPMD propagates
    the constraint through ``apply_updates`` to the params OUTPUT too,
    so post-step params come back dp-sharded — steady-state memory
    matches :func:`fsdp_place` (0.125x replicated), with the weight
    all_gather paid at the next step's consumption sites instead of at
    update time.  This is the
    sharding-annotation form of automatic cross-replica weight-update
    sharding; nothing here hand-schedules a collective.

    ``specs``: pytree from :func:`opt_state_zero1_specs` (None entries =
    leave the leaf alone).  Without it, specs are derived from the
    leaves in place — inside jit those are tracers with no sharding, so
    the derivation sees every axis as free and shards the first
    dp-divisible one.  That is correct ONLY on a pure-dp mesh; a
    multi-axis mesh without explicit ``specs`` is rejected (silently
    re-sharding a tp-sharded leaf to dp-only would replicate it across
    tp — the exact memory win inverted).
    """
    if dp_axis not in mesh.axis_names:
        raise ValueError(
            f"dp_axis={dp_axis!r} not in mesh axes {mesh.axis_names}"
        )
    if specs is None:
        if len(mesh.axis_names) > 1:
            raise ValueError(
                f"mesh has axes {mesh.axis_names}: pass "
                f"specs=opt_state_zero1_specs(initial_opt_state, mesh) "
                f"so dp merges with the model-parallel layout instead "
                f"of overwriting it"
            )
        specs = _merged_dp_specs(opt_state, mesh, dp_axis)
    return jax.tree.map(
        lambda x, s: (
            jax.lax.with_sharding_constraint(x, s) if s is not None
            else x
        ),
        opt_state, specs,
    )


def make_dense_train_step(
    loss_fn: Callable[[PyTree, Any], Array],
    optimizer: optax.GradientTransformation,
    *,
    mesh=None,
    dp_axis: str = "dp",
    shard_opt_state: bool = False,
    opt_specs: PyTree = None,
) -> Callable:
    """Fused pull → grad → push step (jit this).  ``loss_fn(params,
    batch) -> scalar``; gradients are averaged across the dp axis by XLA
    from the shardings alone.

    ``shard_opt_state=True`` (requires ``mesh``): optimizer state is
    dp-sharded via :func:`shard_opt_state_constraint` — ZeRO-1 memory
    scaling for the dense PS path.  For tp/sp-sharded models also pass
    ``opt_specs=opt_state_zero1_specs(server.opt_state, mesh)`` so dp
    merges into a free axis of each leaf instead of overwriting the
    model-parallel layout."""
    if shard_opt_state:
        if mesh is None:
            raise ValueError("shard_opt_state=True requires mesh")
        if dp_axis not in mesh.axis_names:
            raise ValueError(
                f"dp_axis={dp_axis!r} not in mesh axes {mesh.axis_names}"
            )
        if opt_specs is None and len(mesh.axis_names) > 1:
            raise ValueError(
                f"mesh has axes {mesh.axis_names}: pass "
                f"opt_specs=opt_state_zero1_specs(server.opt_state, mesh) "
                f"so dp merges with the model-parallel layout instead of "
                f"overwriting it"
            )

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if shard_opt_state:
            opt_state = shard_opt_state_constraint(
                opt_state, mesh, dp_axis, specs=opt_specs
            )
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def transform_dense(
    data: Iterable,
    loss_fn: Callable[[PyTree, Any], Array],
    server: DenseParameterServer,
    *,
    batch_sharding=None,
    on_step: Optional[Callable[[int, Array], None]] = None,
    steps_per_call: int = 1,
) -> TransformResult:
    """The ``transform`` loop for the dense case: one jitted
    pull→grad→push per microbatch; returns losses as worker outputs and
    the final model as the server dump.

    ``steps_per_call=K`` scans K microbatches inside one jitted dispatch
    (same dispatch-amortization as ``transform_batched``; decisive when
    host↔device latency rivals the step time).  Per-step losses and
    ``on_step`` calls are preserved by unstacking; a trailing group
    shorter than K runs the single-step program.
    """
    if steps_per_call < 1:
        raise ValueError(f"steps_per_call={steps_per_call}: must be >= 1")
    from .transform import scan_group_sharding, stack_group

    base = make_dense_train_step(loss_fn, server.optimizer)
    step = jax.jit(base, donate_argnums=(0, 1))
    scan_step = None
    scan_sharding = None
    if steps_per_call > 1:
        scan_sharding = scan_group_sharding(batch_sharding)

        def _scan(params, opt_state, batches):
            def body(carry, b):
                p, o = carry
                p, o, loss = base(p, o, b)
                return (p, o), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), batches
            )
            return params, opt_state, losses

        scan_step = jax.jit(_scan, donate_argnums=(0, 1))

    # The jitted step donates its (params, opt_state) arguments; start from
    # copies so the caller's server survives (it is a read-only input).
    params = jax.tree.map(jnp_copy, server.params)
    opt_state = jax.tree.map(jnp_copy, server.opt_state)
    losses: List[Any] = []

    def _run_one(params, opt_state, batch):
        if batch_sharding is not None:
            batch = jax.tree.map(
                lambda x: jax.device_put(x, batch_sharding), batch
            )
        params, opt_state, loss = step(params, opt_state, batch)
        if on_step is not None:
            on_step(len(losses), loss)
        losses.append(loss)
        return params, opt_state

    def _run_group(params, opt_state, group):
        stacked = stack_group(group, scan_sharding)
        params, opt_state, group_losses = scan_step(
            params, opt_state, stacked
        )
        for i in range(len(group)):
            loss = group_losses[i]
            if on_step is not None:
                on_step(len(losses), loss)
            losses.append(loss)
        return params, opt_state

    group: List[Any] = []
    for batch in data:
        if steps_per_call == 1:
            params, opt_state = _run_one(params, opt_state, batch)
            continue
        group.append(batch)
        if len(group) == steps_per_call:
            params, opt_state = _run_group(params, opt_state, group)
            group = []
    for batch in group:  # tail shorter than K
        params, opt_state = _run_one(params, opt_state, batch)

    final = DenseParameterServer(params, server.optimizer, opt_state)
    return TransformResult(
        worker_outputs=losses,
        server_outputs=[final.values()],
        store=None,
        worker_state=None,
    )


__all__ = [
    "DenseParameterServer",
    "fsdp_place",
    "make_dense_train_step",
    "opt_state_zero1_specs",
    "shard_opt_state_constraint",
    "transform_dense",
]
