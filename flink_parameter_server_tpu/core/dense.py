"""DenseParameterServer — the PS API stretched to dense model pytrees.

Reference parity: BASELINE.json config #5 ("Transformer-base LM
data-parallel — dense allreduce — stretch the PS API").  The keyed
``pull(id)/push(id, delta)`` protocol degenerates, for a dense model, to
"pull everything / push one gradient": the server is the full parameter
pytree plus an optimizer, and a push folds the (dp-allreduced) gradient
through the optimizer update.  The allreduce is not written anywhere —
jit + dp-sharded batch shardings make XLA insert the psum over ICI, the
collective-native replacement for the reference's per-key Netty routing.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import jax
import optax

from .transform import TransformResult, jnp_copy

Array = jax.Array
PyTree = Any


class DenseParameterServer:
    """Functional (params, opt_state, optimizer) bundle with pull/push.

    ``pull()`` → the model pytree; ``push(grads)`` → new server with the
    optimizer update applied.  Same contract shape as
    :class:`ShardedParamStore`, with the id space collapsed to "all".
    """

    def __init__(
        self,
        params: PyTree,
        optimizer: optax.GradientTransformation,
        opt_state: Optional[PyTree] = None,
    ):
        self.params = params
        self.optimizer = optimizer
        self.opt_state = (
            opt_state if opt_state is not None else optimizer.init(params)
        )

    def pull(self) -> PyTree:
        return self.params

    def push(self, grads: PyTree) -> "DenseParameterServer":
        updates, new_opt_state = self.optimizer.update(
            grads, self.opt_state, self.params
        )
        new_params = optax.apply_updates(self.params, updates)
        return DenseParameterServer(new_params, self.optimizer, new_opt_state)

    def values(self) -> PyTree:
        """Close-time model dump (reference §3.5)."""
        return self.params


def make_dense_train_step(
    loss_fn: Callable[[PyTree, Any], Array],
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Fused pull → grad → push step (jit this).  ``loss_fn(params,
    batch) -> scalar``; gradients are averaged across the dp axis by XLA
    from the shardings alone."""

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def transform_dense(
    data: Iterable,
    loss_fn: Callable[[PyTree, Any], Array],
    server: DenseParameterServer,
    *,
    batch_sharding=None,
    on_step: Optional[Callable[[int, Array], None]] = None,
) -> TransformResult:
    """The ``transform`` loop for the dense case: one jitted
    pull→grad→push per microbatch; returns losses as worker outputs and
    the final model as the server dump."""
    step = jax.jit(
        make_dense_train_step(loss_fn, server.optimizer),
        donate_argnums=(0, 1),
    )
    # The jitted step donates its (params, opt_state) arguments; start from
    # copies so the caller's server survives (it is a read-only input).
    params = jax.tree.map(jnp_copy, server.params)
    opt_state = jax.tree.map(jnp_copy, server.opt_state)
    losses: List[Any] = []
    for i, batch in enumerate(data):
        if batch_sharding is not None:
            batch = jax.tree.map(
                lambda x: jax.device_put(x, batch_sharding), batch
            )
        params, opt_state, loss = step(params, opt_state, batch)
        if on_step is not None:
            on_step(i, loss)
        losses.append(loss)
    final = DenseParameterServer(params, server.optimizer, opt_state)
    return TransformResult(
        worker_outputs=losses,
        server_outputs=[final.values()],
        store=None,
        worker_state=None,
    )


__all__ = ["DenseParameterServer", "make_dense_train_step", "transform_dense"]
