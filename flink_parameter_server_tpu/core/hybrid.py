"""Hybrid backend: reference-style event callbacks + the device store.

The migration middle path between the two programming models
(SURVEY.md §7 "Guiding translation"):

  * the **event API** (``core.api.WorkerLogic``) runs arbitrary Python per
    record but keeps parameters in host HashMaps,
  * the **batched API** compiles everything but requires rewriting the
    logic as pure functions.

``transform_hybrid`` runs an *unmodified* ``WorkerLogic`` against a
:class:`ShardedParamStore`: per chunk of records it collects every
``pull`` the callbacks issue, answers them all with ONE sharded gather,
dispatches the answers back into ``on_pull_recv``, and folds every
``push`` with ONE sharded scatter-add.  Python still executes the per
-record math (no jit speedup for the worker logic itself), but the
parameter plane — the reference's per-message Netty traffic — becomes
two device collectives per chunk, and the model lives in HBM at any
scale.  Value-shape note: logics must push deltas matching the store's
``value_shape``.

Staleness semantics: pulls within a chunk observe the store as of the
chunk start; pushes land at chunk end (bounded staleness of one chunk —
between the reference's unbounded races and the batched backend's one
microbatch).

Custom (non-"add") store ``update`` functions: duplicate-id pushes
within one chunk are summed BEFORE ``update`` applies once per id
(:class:`~..core.store.StoreSpec` semantics) — the event backend applies
``update`` per push instead, so non-commutative updates diverge between
the two backends for intra-chunk duplicates.  Use ``chunk_size=1`` for
exact per-push semantics.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from .api import ParameterServerClient, WorkerLogic
from .store import ShardedParamStore
from .transform import TransformResult, _instances


class _HybridClient(ParameterServerClient):
    """Buffers the callbacks' pull/push traffic for chunk-level batching."""

    def __init__(self):
        self.pull_requests: List[int] = []
        self.push_ids: List[int] = []
        self.push_deltas: List[Any] = []
        self.outputs: List[Any] = []

    def pull(self, param_id: int) -> None:
        self.pull_requests.append(param_id)

    def push(self, param_id: int, delta) -> None:
        self.push_ids.append(param_id)
        self.push_deltas.append(np.asarray(delta))

    def output(self, w_out) -> None:
        self.outputs.append(w_out)


def transform_hybrid(
    data: Iterable,
    worker_logic: Union[WorkerLogic, Callable[[], WorkerLogic]],
    store: ShardedParamStore,
    *,
    chunk_size: int = 1024,
    worker_parallelism: int = 1,
    partitioner: Optional[Callable[[Any, int], int]] = None,
    dump_model: bool = True,
) -> TransformResult:
    """Run an event-API worker logic against a sharded device store.

    Per chunk: deliver records (``on_recv``) buffering pulls → one
    ``store.pull`` for all unique ids → deliver answers
    (``on_pull_recv``), buffering any follow-up pulls/pushes (follow-up
    pulls are answered from the same chunk snapshot) → one
    ``store.push`` of all buffered deltas.
    """
    workers = _instances(worker_logic, worker_parallelism, "worker")
    clients = [_HybridClient() for _ in workers]
    worker_outputs: List[Any] = []

    import itertools

    rr = itertools.cycle(range(len(workers)))

    def check_ids(ids, what: str) -> None:
        # unlike the event backend (arbitrary hashable keys), the device
        # store is integer-indexed: fail loudly instead of crashing deep
        # inside JAX (non-int) or silently clipping/dropping (OOB)
        for pid in ids:
            if not isinstance(pid, (int, np.integer)):
                raise TypeError(
                    f"transform_hybrid requires integer param ids; "
                    f"{what} got {pid!r} — remap keys to ints for the "
                    f"device store"
                )
            if not 0 <= pid < store.spec.capacity:
                raise ValueError(
                    f"{what} id {pid} out of range for store capacity "
                    f"{store.spec.capacity}"
                )

    def flush_chunk(records: List[Tuple[int, Any]]) -> None:
        nonlocal store
        # 1. deliver records; callbacks buffer pulls/pushes
        for widx, record in records:
            workers[widx].on_recv(record, clients[widx])
        # 2. answer ALL buffered pulls — deduped, one snapshot gather per
        # round; follow-up pulls issued inside on_pull_recv are answered
        # against the same snapshot until none remain
        while any(c.pull_requests for c in clients):
            requests = [(w, pid) for w, c in enumerate(clients)
                        for pid in c.pull_requests]
            for c in clients:
                c.pull_requests = []
            check_ids([pid for _w, pid in requests], "pull")
            unique, inverse = np.unique(
                np.asarray([pid for _w, pid in requests], np.int64),
                return_inverse=True,
            )
            values = np.asarray(store.pull(jnp.asarray(unique, jnp.int32)))
            for (widx, pid), uidx in zip(requests, inverse):
                workers[widx].on_pull_recv(pid, values[uidx], clients[widx])
        # 3. one scatter-add for every buffered push
        all_ids = [pid for c in clients for pid in c.push_ids]
        check_ids(all_ids, "push")
        if all_ids:
            all_deltas = np.stack(
                [d for c in clients for d in c.push_deltas]
            ).astype(store.table.dtype)
            store = store.push(
                jnp.asarray(all_ids, jnp.int32), jnp.asarray(all_deltas)
            )
        for c in clients:
            c.push_ids, c.push_deltas = [], []
            worker_outputs.extend(c.outputs)
            c.outputs = []

    chunk: List[Tuple[int, Any]] = []
    for record in data:
        widx = (
            partitioner(record, len(workers)) if partitioner else next(rr)
        )
        chunk.append((widx, record))
        if len(chunk) >= chunk_size:
            flush_chunk(chunk)
            chunk = []
    if chunk:
        flush_chunk(chunk)

    for w in workers:
        w.close()

    server_outputs: List[Any] = []
    if dump_model:
        server_outputs.append(
            (np.arange(store.spec.capacity), np.asarray(store.values()))
        )
    return TransformResult(
        worker_outputs=worker_outputs,
        server_outputs=server_outputs,
        store=store,
    )


__all__ = ["transform_hybrid"]
