"""ctypes bindings for the native (C++) rating loader/batcher.

Reference parity: the reference's ingestion layer is Flink's JVM runtime
(SURVEY.md §1 L1 — sources, serialization, network).  Here the ingestion
edge is ``native/fps_loader.cpp``: mmap'd parsing plus a background-thread
ring-buffer batcher, keeping batch assembly off the Python GIL while the
device runs the previous step.

The shared library is built on first use with the system ``g++`` (no
pip/pybind dependency — plain C ABI via ctypes) and cached under
``native/build/``.  Every entry point falls back to the pure-numpy path in
:mod:`.movielens` when a compiler is unavailable.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Iterator, Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC = os.path.abspath(os.path.join(_NATIVE_DIR, "fps_loader.cpp"))
_SO = os.path.abspath(os.path.join(_NATIVE_DIR, "build", "libfps_loader.so"))

_lib = None
_lib_lock = threading.Lock()


class NativeUnavailable(RuntimeError):
    pass


def _build() -> str:
    try:
        os.makedirs(os.path.dirname(_SO), exist_ok=True)
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(
            _SRC
        ):
            return _SO
        cmd = [
            "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
            _SRC, "-o", _SO,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
    except (OSError, subprocess.SubprocessError) as e:
        raise NativeUnavailable(f"building {_SO} failed: {e}") from e
    return _SO


def get_lib() -> ctypes.CDLL:
    """Load (building if needed) the native library."""
    global _lib
    # fpsanalyze: allow[B001] build-once double-checked lock: every caller MUST wait for the one-time g++ build — blocking here is the contract
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_build())
        lib.fps_parse.restype = ctypes.c_void_p
        lib.fps_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.fps_num_rows.restype = ctypes.c_int64
        lib.fps_num_rows.argtypes = [ctypes.c_void_p]
        lib.fps_columns.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.fps_free.argtypes = [ctypes.c_void_p]
        lib.fps_stream_open.restype = ctypes.c_void_p
        lib.fps_stream_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int64,
        ]
        lib.fps_stream_next.restype = ctypes.c_int64
        lib.fps_stream_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.fps_stream_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def load_ratings(
    path: str, *, max_rows: int = -1, compact_ids: bool = True,
    normalize: bool = False,
) -> Dict[str, np.ndarray]:
    """Parse a MovieLens-format ratings file natively into columns
    (same contract as :func:`.movielens.load_movielens`); falls back to
    the pure-numpy loader when no C++ toolchain is available."""
    try:
        lib = get_lib()
    except NativeUnavailable:
        from .movielens import load_movielens

        if not os.path.exists(path):
            raise FileNotFoundError(path)
        out = load_movielens(
            path,
            max_ratings=None if max_rows < 0 else max_rows,
            normalize=normalize,
        )
        if not compact_ids:
            raise NativeUnavailable(
                "compact_ids=False requires the native loader"
            )
        return out
    handle = lib.fps_parse(path.encode(), max_rows)
    if not handle:
        raise FileNotFoundError(path)
    try:
        n = lib.fps_num_rows(handle)
        users = np.empty(n, np.int64)
        items = np.empty(n, np.int64)
        ratings = np.empty(n, np.float32)
        lib.fps_columns(
            handle, _ptr(users, ctypes.c_int64), _ptr(items, ctypes.c_int64),
            _ptr(ratings, ctypes.c_float),
        )
    finally:
        lib.fps_free(handle)
    if compact_ids:
        _, users = np.unique(users, return_inverse=True)
        _, items = np.unique(items, return_inverse=True)
    if normalize:
        ratings = (ratings - ratings.mean()) / 2.0
    return {
        "user": users.astype(np.int32),
        "item": items.astype(np.int32),
        "rating": ratings,
    }


def stream_batches(
    path: str,
    batch_size: int,
    *,
    epochs: int = 1,
    shuffle_seed: Optional[int] = None,
    ring_capacity: int = 4,
    pad_to_batch: bool = True,
) -> Iterator[Dict[str, np.ndarray]]:
    """Stream rating microbatches assembled by the native background
    thread (ids are raw file ids — pair with ``compact_ids=False``
    semantics; remap host-side if needed).  Falls back to a pure-numpy
    generator (same batch contract) without a C++ toolchain."""
    try:
        lib = get_lib()
    except NativeUnavailable:
        yield from _numpy_stream(
            path, batch_size, epochs=epochs, shuffle_seed=shuffle_seed,
            pad_to_batch=pad_to_batch,
        )
        return
    handle = lib.fps_stream_open(
        path.encode(), batch_size, epochs,
        1 if shuffle_seed is not None else 0,
        shuffle_seed or 0, ring_capacity,
    )
    if not handle:
        raise FileNotFoundError(path)
    try:
        u = np.empty(batch_size, np.int64)
        i = np.empty(batch_size, np.int64)
        r = np.empty(batch_size, np.float32)
        while True:
            n = lib.fps_stream_next(
                handle, _ptr(u, ctypes.c_int64), _ptr(i, ctypes.c_int64),
                _ptr(r, ctypes.c_float),
            )
            if n == 0:
                return
            if n == batch_size or not pad_to_batch:
                batch = {
                    "user": u[:n].astype(np.int32),
                    "item": i[:n].astype(np.int32),
                    "rating": r[:n].copy(),
                    "mask": np.ones(int(n), bool),
                }
            else:
                pad = batch_size - int(n)
                batch = {
                    "user": np.concatenate(
                        [u[:n], np.zeros(pad, np.int64)]
                    ).astype(np.int32),
                    "item": np.concatenate(
                        [i[:n], np.zeros(pad, np.int64)]
                    ).astype(np.int32),
                    "rating": np.concatenate([r[:n], np.zeros(pad, np.float32)]),
                    "mask": np.arange(batch_size) < int(n),
                }
            yield batch
    finally:
        lib.fps_stream_close(handle)


def _numpy_stream(path, batch_size, *, epochs, shuffle_seed, pad_to_batch):
    """Fallback batcher (numpy).  Divergence from the native stream: ids
    come out *compacted* (the numpy loader's contract), not raw file ids."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    from .movielens import load_movielens

    cols = load_movielens(path, normalize=False)
    n = len(cols["user"])
    rng = (
        np.random.default_rng(shuffle_seed) if shuffle_seed is not None else None
    )
    for _ in range(epochs):
        order = rng.permutation(n) if rng is not None else np.arange(n)
        for s in range(0, n, batch_size):
            idx = order[s : s + batch_size]
            m = len(idx)
            if m < batch_size and pad_to_batch:
                pad = batch_size - m
                yield {
                    "user": np.concatenate(
                        [cols["user"][idx], np.zeros(pad, np.int32)]
                    ),
                    "item": np.concatenate(
                        [cols["item"][idx], np.zeros(pad, np.int32)]
                    ),
                    "rating": np.concatenate(
                        [cols["rating"][idx], np.zeros(pad, np.float32)]
                    ),
                    "mask": np.arange(batch_size) < m,
                }
            else:
                yield {
                    "user": cols["user"][idx],
                    "item": cols["item"][idx],
                    "rating": cols["rating"][idx],
                    "mask": np.ones(m, bool),
                }


__all__ = ["get_lib", "load_ratings", "stream_batches", "NativeUnavailable"]
