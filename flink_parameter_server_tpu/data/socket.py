"""Unbounded socket text source — the ``socketTextStream`` stand-in.

Reference parity: Flink's canonical unbounded-source demo reads
newline-delimited text from a TCP socket (``env.socketTextStream``), and
the reference's streaming jobs are written against exactly that kind of
source (SURVEY.md §1 L1, §5 "Config / examples parse args or
hardcode").  This module is the rebuild's host-side equivalent: a
generator of decoded lines, plus a bounded-buffer bridge that turns an
unbounded record stream into the fixed-shape microbatches the jitted
step needs.

Design notes (TPU-first):
  * ingestion stays on the HOST — the device only ever sees the
    fixed-shape microbatch pytrees (SURVEY.md §7 "Dynamic shapes");
  * the source is a plain generator, so every downstream tool
    (``microbatches`` via :func:`batches_from_records`, ``prefetch``,
    the event backend's per-record loop) composes unchanged;
  * end-of-stream is EXPLICIT (peer closes the connection), not a
    silence timeout — the reference's ``iterationWaitTime`` hack is
    deliberately not reproduced (SURVEY.md §3.5).
"""
from __future__ import annotations

import socket
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np


def socket_text_stream(
    host: str,
    port: int,
    *,
    encoding: str = "utf-8",
    errors: str = "replace",
    connect_timeout: float = 10.0,
    max_line_bytes: int = 1 << 20,
    reconnect: bool = True,
    max_reconnects: int = 8,
    backoff_base_s: float = 0.05,
    backoff_cap_s: float = 5.0,
    backoff_seed: int = 0,
) -> Iterator[str]:
    """Yield newline-delimited lines from a TCP server until the peer
    closes CLEANLY.  The trailing partial line (no newline before EOF)
    is yielded too — matching file semantics, so a line-oriented
    producer never silently loses its last record.

    Reconnect (``reconnect=True``, the default): an ABRUPT connection
    failure — reset, broken pipe, timeout, refused connect — no longer
    kills the unbounded job it feeds (the exact weakness the reference's
    socket jobs had); the stream re-dials with capped exponential
    backoff + full jitter, up to ``max_reconnects`` CONSECUTIVE failed
    attempts (the streak resets once a reconnected socket delivers
    bytes), then raises the last error.  The returned iterator exposes
    a ``reconnects`` counter (successful re-dials — the observability
    hook, like ``batches_from_records.dropped``).  A clean peer close
    (EOF) is still the explicit end-of-stream — never retried.  A
    partial line buffered when the connection drops abruptly is
    DISCARDED (its tail is unrecoverable; a half-record must not be
    yielded as a record) — producers that need exactly-once should
    sequence-number their lines.  ``reconnect=False`` preserves the old
    die-on-error behavior.

    ``errors="replace"`` (the default) maps undecodable bytes to U+FFFD
    instead of raising: one corrupt byte must not kill an unbounded
    streaming job — the mangled line then fails ``parse`` downstream
    and is *counted* (``batches_from_records.dropped``), which is the
    observable place for it.  Pass ``errors="strict"`` to crash on
    corruption instead.

    ``max_line_bytes`` bounds the reassembly buffer: a producer that
    never sends a newline would otherwise grow it without limit."""
    return _SocketLineStream(
        host, port, encoding=encoding, errors=errors,
        connect_timeout=connect_timeout, max_line_bytes=max_line_bytes,
        reconnect=reconnect, max_reconnects=max_reconnects,
        backoff_base_s=backoff_base_s, backoff_cap_s=backoff_cap_s,
        backoff_seed=backoff_seed,
    )


class _SocketLineStream:
    """Iterator with a visible ``reconnects`` counter (the socket-side
    sibling of ``_RecordBatcher.dropped``)."""

    def __init__(self, host, port, *, encoding, errors, connect_timeout,
                 max_line_bytes, reconnect, max_reconnects,
                 backoff_base_s, backoff_cap_s, backoff_seed):
        self.reconnects = 0
        self._gen = self._run(
            host, port, encoding, errors, connect_timeout, max_line_bytes,
            reconnect, max_reconnects, backoff_base_s, backoff_cap_s,
            backoff_seed,
        )

    def _run(self, host, port, encoding, errors, connect_timeout,
             max_line_bytes, reconnect, max_reconnects, backoff_base_s,
             backoff_cap_s, backoff_seed):
        rng = np.random.default_rng(backoff_seed)
        failures = 0  # consecutive failed dial/read attempts
        connected_once = False
        while True:
            try:
                s = socket.create_connection(
                    (host, port), timeout=connect_timeout
                )
            except OSError as e:
                if not reconnect:
                    raise
                failures += 1
                if failures > max_reconnects:
                    raise ConnectionError(
                        f"socket source gave up after {max_reconnects} "
                        f"consecutive failed reconnect attempts to "
                        f"{host}:{port}"
                    ) from e
                time.sleep(self._backoff(failures, backoff_base_s,
                                         backoff_cap_s, rng))
                continue
            if connected_once:
                self.reconnects += 1
                # unified plane: re-dials are a recovery signal the
                # run report rolls up (telemetry/report.py "reconnects")
                from ..telemetry.registry import get_registry

                get_registry().counter(
                    "ingest_reconnects_total", component="ingest"
                ).inc()
            connected_once = True
            buf = b""
            got_bytes = False
            try:
                with s:
                    # liveness beats latency here: the batcher downstream
                    # absorbs jitter, so no artificial read timeout once
                    # connected
                    s.settimeout(None)
                    while True:
                        chunk = s.recv(1 << 16)
                        if not chunk:
                            # clean EOF: the EXPLICIT end-of-stream —
                            # flush the trailing partial line and stop
                            if buf:
                                yield buf.decode(encoding, errors)
                            return
                        got_bytes = True
                        failures = 0  # live again: reset the streak
                        buf += chunk
                        if len(buf) > max_line_bytes and b"\n" not in buf:
                            raise ValueError(
                                f"socket line exceeded {max_line_bytes} "
                                f"bytes with no newline — not a "
                                f"line-delimited stream?"
                            )
                        *lines, buf = buf.split(b"\n")
                        for ln in lines:
                            yield ln.decode(encoding, errors)
            except OSError as e:
                if not reconnect:
                    raise
                # abrupt death mid-stream: drop the partial line (its
                # tail is gone), back off, re-dial
                if not got_bytes:
                    failures += 1
                if failures > max_reconnects:
                    raise ConnectionError(
                        f"socket source gave up after {max_reconnects} "
                        f"consecutive failed reconnect attempts to "
                        f"{host}:{port}"
                    ) from e
                time.sleep(self._backoff(max(1, failures), backoff_base_s,
                                         backoff_cap_s, rng))

    @staticmethod
    def _backoff(attempt, base, cap, rng):
        # capped exponential with full jitter (decorrelates a fleet of
        # consumers re-dialing one recovered producer)
        return float(rng.uniform(0.0, min(cap, base * (2 ** (attempt - 1)))))

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)


def batches_from_records(
    records: Iterator[Any],
    batch_size: int,
    parse: Callable[[Any], Optional[Dict[str, Any]]],
    *,
    pad_value: int = 0,
    drop_remainder: bool = False,
) -> Iterator[Dict[str, np.ndarray]]:
    """Bridge an UNBOUNDED record stream to fixed-shape microbatches.

    ``parse(record)`` returns a dict of scalars/arrays for one event, or
    ``None`` to drop the record (bad lines must not kill a streaming
    job — they are counted on the returned iterator's ``.dropped``
    attribute instead).  Batches are emitted as soon as ``batch_size``
    records accumulate — no epoch/shuffle machinery, because an
    unbounded stream has neither.  The final partial batch is zero-
    padded with a ``"mask"`` column (static shapes — SURVEY.md §7), or
    dropped with ``drop_remainder=True``.
    """
    return _RecordBatcher(records, batch_size, parse, pad_value,
                          drop_remainder)


class _RecordBatcher:
    """Iterator with a visible ``dropped`` malformed-record counter."""

    def __init__(self, records, batch_size, parse, pad_value,
                 drop_remainder):
        self.dropped = 0
        self._gen = self._run(records, batch_size, parse, pad_value,
                              drop_remainder)

    def _run(self, records, batch_size, parse, pad_value, drop_remainder):
        rows: List[Dict[str, Any]] = []
        expected_keys = None
        for rec in records:
            parsed = None
            try:
                parsed = parse(rec)
            except Exception:
                # ANY parse failure is a malformed record: count +
                # continue.  A narrower catch list (ValueError, ...)
                # would let a TypeError/AttributeError from one bad
                # line kill the whole unbounded job — the exact crash
                # this bridge exists to absorb.  The .dropped counter
                # keeps failures observable.
                pass
            if parsed is None:
                self.dropped += 1
                continue
            # Per-row key validation (not just rows[0]): a parse() that
            # returns inconsistent dict keys across records would
            # otherwise raise an uncaught KeyError at stack time —
            # killing the unbounded job this bridge exists to protect.
            # Inconsistent rows are malformed records: count + continue.
            if "mask" in parsed:
                # reserved-name misuse is a PROGRAMMING error on every
                # row it appears on, not stream corruption — stay loud
                # (checked per row, so a row-3-only 'mask' no longer
                # slips past the old rows[0]-only guard)
                raise ValueError(
                    "'mask' is reserved for the padding mask; have "
                    "parse() return the column under another name"
                )
            if expected_keys is None:
                expected_keys = frozenset(parsed)
            elif frozenset(parsed) != expected_keys:
                self.dropped += 1
                continue
            rows.append(parsed)
            if len(rows) == batch_size:
                yield self._stack(rows, batch_size, pad_value)
                rows = []
        if rows and not drop_remainder:
            yield self._stack(rows, batch_size, pad_value)

    @staticmethod
    def _stack(rows, batch_size, pad_value):
        if "mask" in rows[0]:
            # the padding mask is written below under this exact name;
            # silently clobbering a parse-produced column would train
            # with a wrong mask
            raise ValueError(
                "'mask' is reserved for the padding mask; have parse() "
                "return the column under another name"
            )
        batch: Dict[str, np.ndarray] = {}
        n = len(rows)
        for k in rows[0]:
            col = np.asarray([r[k] for r in rows])
            if n < batch_size:
                pad = np.full(
                    (batch_size - n,) + col.shape[1:], pad_value, col.dtype
                )
                col = np.concatenate([col, pad])
            batch[k] = col
        batch["mask"] = np.arange(batch_size) < n
        return batch

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)


__all__ = ["socket_text_stream", "batches_from_records"]
