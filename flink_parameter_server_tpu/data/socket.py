"""Unbounded socket text source — the ``socketTextStream`` stand-in.

Reference parity: Flink's canonical unbounded-source demo reads
newline-delimited text from a TCP socket (``env.socketTextStream``), and
the reference's streaming jobs are written against exactly that kind of
source (SURVEY.md §1 L1, §5 "Config / examples parse args or
hardcode").  This module is the rebuild's host-side equivalent: a
generator of decoded lines, plus a bounded-buffer bridge that turns an
unbounded record stream into the fixed-shape microbatches the jitted
step needs.

Design notes (TPU-first):
  * ingestion stays on the HOST — the device only ever sees the
    fixed-shape microbatch pytrees (SURVEY.md §7 "Dynamic shapes");
  * the source is a plain generator, so every downstream tool
    (``microbatches`` via :func:`batches_from_records`, ``prefetch``,
    the event backend's per-record loop) composes unchanged;
  * end-of-stream is EXPLICIT (peer closes the connection), not a
    silence timeout — the reference's ``iterationWaitTime`` hack is
    deliberately not reproduced (SURVEY.md §3.5).
"""
from __future__ import annotations

import socket
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np


def socket_text_stream(
    host: str,
    port: int,
    *,
    encoding: str = "utf-8",
    errors: str = "replace",
    connect_timeout: float = 10.0,
    max_line_bytes: int = 1 << 20,
) -> Iterator[str]:
    """Yield newline-delimited lines from a TCP server until the peer
    closes.  The trailing partial line (no newline before EOF) is
    yielded too — matching file semantics, so a line-oriented producer
    never silently loses its last record.

    ``errors="replace"`` (the default) maps undecodable bytes to U+FFFD
    instead of raising: one corrupt byte must not kill an unbounded
    streaming job — the mangled line then fails ``parse`` downstream
    and is *counted* (``batches_from_records.dropped``), which is the
    observable place for it.  Pass ``errors="strict"`` to crash on
    corruption instead.

    ``max_line_bytes`` bounds the reassembly buffer: a producer that
    never sends a newline would otherwise grow it without limit."""
    with socket.create_connection((host, port), timeout=connect_timeout) as s:
        # liveness beats latency here: the batcher downstream absorbs
        # jitter, so no artificial read timeout once connected
        s.settimeout(None)
        buf = b""
        while True:
            chunk = s.recv(1 << 16)
            if not chunk:
                break
            buf += chunk
            if len(buf) > max_line_bytes and b"\n" not in buf:
                raise ValueError(
                    f"socket line exceeded {max_line_bytes} bytes with no "
                    f"newline — not a line-delimited stream?"
                )
            *lines, buf = buf.split(b"\n")
            for ln in lines:
                yield ln.decode(encoding, errors)
        if buf:
            yield buf.decode(encoding, errors)


def batches_from_records(
    records: Iterator[Any],
    batch_size: int,
    parse: Callable[[Any], Optional[Dict[str, Any]]],
    *,
    pad_value: int = 0,
    drop_remainder: bool = False,
) -> Iterator[Dict[str, np.ndarray]]:
    """Bridge an UNBOUNDED record stream to fixed-shape microbatches.

    ``parse(record)`` returns a dict of scalars/arrays for one event, or
    ``None`` to drop the record (bad lines must not kill a streaming
    job — they are counted on the returned iterator's ``.dropped``
    attribute instead).  Batches are emitted as soon as ``batch_size``
    records accumulate — no epoch/shuffle machinery, because an
    unbounded stream has neither.  The final partial batch is zero-
    padded with a ``"mask"`` column (static shapes — SURVEY.md §7), or
    dropped with ``drop_remainder=True``.
    """
    return _RecordBatcher(records, batch_size, parse, pad_value,
                          drop_remainder)


class _RecordBatcher:
    """Iterator with a visible ``dropped`` malformed-record counter."""

    def __init__(self, records, batch_size, parse, pad_value,
                 drop_remainder):
        self.dropped = 0
        self._gen = self._run(records, batch_size, parse, pad_value,
                              drop_remainder)

    def _run(self, records, batch_size, parse, pad_value, drop_remainder):
        rows: List[Dict[str, Any]] = []
        expected_keys = None
        for rec in records:
            parsed = None
            try:
                parsed = parse(rec)
            except Exception:
                # ANY parse failure is a malformed record: count +
                # continue.  A narrower catch list (ValueError, ...)
                # would let a TypeError/AttributeError from one bad
                # line kill the whole unbounded job — the exact crash
                # this bridge exists to absorb.  The .dropped counter
                # keeps failures observable.
                pass
            if parsed is None:
                self.dropped += 1
                continue
            # Per-row key validation (not just rows[0]): a parse() that
            # returns inconsistent dict keys across records would
            # otherwise raise an uncaught KeyError at stack time —
            # killing the unbounded job this bridge exists to protect.
            # Inconsistent rows are malformed records: count + continue.
            if "mask" in parsed:
                # reserved-name misuse is a PROGRAMMING error on every
                # row it appears on, not stream corruption — stay loud
                # (checked per row, so a row-3-only 'mask' no longer
                # slips past the old rows[0]-only guard)
                raise ValueError(
                    "'mask' is reserved for the padding mask; have "
                    "parse() return the column under another name"
                )
            if expected_keys is None:
                expected_keys = frozenset(parsed)
            elif frozenset(parsed) != expected_keys:
                self.dropped += 1
                continue
            rows.append(parsed)
            if len(rows) == batch_size:
                yield self._stack(rows, batch_size, pad_value)
                rows = []
        if rows and not drop_remainder:
            yield self._stack(rows, batch_size, pad_value)

    @staticmethod
    def _stack(rows, batch_size, pad_value):
        if "mask" in rows[0]:
            # the padding mask is written below under this exact name;
            # silently clobbering a parse-produced column would train
            # with a wrong mask
            raise ValueError(
                "'mask' is reserved for the padding mask; have parse() "
                "return the column under another name"
            )
        batch: Dict[str, np.ndarray] = {}
        n = len(rows)
        for k in rows[0]:
            col = np.asarray([r[k] for r in rows])
            if n < batch_size:
                pad = np.full(
                    (batch_size - n,) + col.shape[1:], pad_value, col.dtype
                )
                col = np.concatenate([col, pad])
            batch[k] = col
        batch["mask"] = np.arange(batch_size) < n
        return batch

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)


__all__ = ["socket_text_stream", "batches_from_records"]
