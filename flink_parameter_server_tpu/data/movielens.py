"""Rating-stream datasets for the MF example and benchmarks.

The reference's canonical demo trains on MovieLens streams (SURVEY.md §6,
BASELINE.json configs).  This environment has no network egress, so we
provide (a) a loader for on-disk MovieLens-format files if present and (b)
a synthetic low-rank generator with MovieLens-like marginals (Zipfian item
popularity, user activity skew) — the skew is what stresses the sharded
scatter-add path, so the synthetic set preserves it.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def synthetic_ratings(
    num_users: int = 1000,
    num_items: int = 1200,
    num_ratings: int = 50_000,
    *,
    rank: int = 8,
    noise: float = 0.05,
    zipf_a: float = 1.2,
    seed: int = 0,
) -> Dict[str, np.ndarray]:
    """Low-rank ground-truth ratings with Zipf-skewed item popularity.

    Returns columns ``user``, ``item``, ``rating`` (float32 in ~[-1, 1])
    suitable for :func:`..data.streams.microbatches`.
    """
    rng = np.random.default_rng(seed)
    P = rng.normal(0, 1.0 / np.sqrt(rank), (num_users, rank)).astype(np.float32)
    Q = rng.normal(0, 1.0 / np.sqrt(rank), (num_items, rank)).astype(np.float32)
    users = rng.integers(0, num_users, num_ratings).astype(np.int32)
    # Zipf over item ranks, clipped to catalogue size.
    items = (rng.zipf(zipf_a, num_ratings) - 1) % num_items
    items = items.astype(np.int32)
    ratings = np.einsum("ij,ij->i", P[users], Q[items]).astype(np.float32)
    ratings += rng.normal(0, noise, num_ratings).astype(np.float32)
    return {"user": users, "item": items, "rating": ratings}


def load_movielens(
    path: str, *, max_ratings: Optional[int] = None, normalize: bool = True
) -> Dict[str, np.ndarray]:
    """Parse MovieLens ``ratings`` files (``u.data`` tab-separated 100K
    format or ``ratings.csv``/``ratings.dat`` 1M/20M formats) into columns.

    Ids are compacted to dense ranges; ratings optionally centred to
    ~[-1, 1] (mean-centred, /2) the way streaming-MF setups normalise."""
    if path.endswith(".csv"):
        raw = np.genfromtxt(
            path, delimiter=",", skip_header=1, usecols=(0, 1, 2), dtype=np.float64
        )
    elif "::" in open(path, "r").readline():
        raw = np.genfromtxt(path, delimiter="::", usecols=(0, 1, 2), dtype=np.float64)
    else:
        raw = np.genfromtxt(path, delimiter="\t", usecols=(0, 1, 2), dtype=np.float64)
    if max_ratings is not None:
        raw = raw[:max_ratings]
    users_raw = raw[:, 0].astype(np.int64)
    items_raw = raw[:, 1].astype(np.int64)
    ratings = raw[:, 2].astype(np.float32)
    _, users = np.unique(users_raw, return_inverse=True)
    _, items = np.unique(items_raw, return_inverse=True)
    if normalize:
        ratings = (ratings - ratings.mean()) / 2.0
    return {
        "user": users.astype(np.int32),
        "item": items.astype(np.int32),
        "rating": ratings,
    }


__all__ = ["synthetic_ratings", "load_movielens"]
