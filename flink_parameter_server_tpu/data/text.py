"""Token-stream data for word2vec and the sketch apps.

No network egress in this environment, so alongside a plain text-file
tokenizer we provide a synthetic Zipf corpus with planted co-occurrence
structure (topic blocks), preserving the skewed unigram distribution that
stresses the sharded scatter-add path.
"""
from __future__ import annotations

from typing import Dict, Iterator

import numpy as np


def synthetic_corpus(
    vocab_size: int = 5000,
    length: int = 200_000,
    *,
    num_topics: int = 10,
    zipf_a: float = 1.3,
    topic_stickiness: float = 0.98,
    seed: int = 0,
) -> np.ndarray:
    """Token stream with Zipf marginals and topical co-occurrence: words
    are partitioned into topics; the stream is a sticky Markov chain over
    topics, drawing Zipf-ranked words within the current topic."""
    rng = np.random.default_rng(seed)
    words_per_topic = vocab_size // num_topics
    topic = 0
    # per-topic Zipf ranks
    ranks = (rng.zipf(zipf_a, length) - 1) % words_per_topic
    switches = rng.random(length) > topic_stickiness
    topics = np.empty(length, np.int32)
    for i in range(length):
        if switches[i]:
            topic = rng.integers(0, num_topics)
        topics[i] = topic
    tokens = (topics * words_per_topic + ranks).astype(np.int32)
    return tokens


def unigram_table(tokens: np.ndarray, vocab_size: int, power: float = 0.75):
    counts = np.bincount(tokens, minlength=vocab_size).astype(np.float64)
    probs = counts**power
    probs /= probs.sum()
    return probs


def skipgram_batches(
    tokens: np.ndarray,
    vocab_size: int,
    *,
    batch_size: int = 1024,
    window: int = 4,
    num_negatives: int = 5,
    epochs: int = 1,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """(center, context, negatives) microbatches with unigram^0.75
    negative sampling — the host-side pair generator feeding the jitted
    SGNS step."""
    rng = np.random.default_rng(seed)
    probs = unigram_table(tokens, vocab_size)
    n = len(tokens)
    for _ in range(epochs):
        centers, contexts = [], []
        # dynamic window like word2vec: uniform in [1, window]
        for i in rng.permutation(n):
            w = rng.integers(1, window + 1)
            j = i + rng.integers(-w, w + 1)
            if j == i or j < 0 or j >= n:
                continue
            centers.append(tokens[i])
            contexts.append(tokens[j])
            if len(centers) == batch_size:
                yield _pair_batch(centers, contexts, batch_size, rng,
                                  vocab_size, num_negatives, probs)
                centers, contexts = [], []
        if centers:  # pad+mask the epoch's tail (framework convention)
            yield _pair_batch(centers, contexts, batch_size, rng,
                              vocab_size, num_negatives, probs)


def _pair_batch(centers, contexts, batch_size, rng, vocab_size,
                num_negatives, probs) -> Dict[str, np.ndarray]:
    n = len(centers)
    pad = batch_size - n
    return {
        "center": np.array(centers + [0] * pad, np.int32),
        "context": np.array(contexts + [0] * pad, np.int32),
        "negatives": rng.choice(
            vocab_size, (batch_size, num_negatives), p=probs
        ).astype(np.int32),
        "mask": np.arange(batch_size) < n,
    }


def cooccurrence_pairs(
    tokens: np.ndarray,
    *,
    window: int = 2,
    batch_size: int = 2048,
) -> Iterator[Dict[str, np.ndarray]]:
    """Sliding-window unordered co-occurrence pairs for the bloom sketch."""
    a_buf, b_buf = [], []
    n = len(tokens)

    def emit(a_buf, b_buf):
        pad = batch_size - len(a_buf)
        return {
            "word_a": np.array(a_buf + [0] * pad, np.int32),
            "word_b": np.array(b_buf + [0] * pad, np.int32),
            "mask": np.arange(batch_size) < len(a_buf),
        }

    for i in range(n - 1):
        for j in range(i + 1, min(i + 1 + window, n)):
            a_buf.append(tokens[i])
            b_buf.append(tokens[j])
            if len(a_buf) == batch_size:
                yield emit(a_buf, b_buf)
                a_buf, b_buf = [], []
    if a_buf:  # pad+mask the tail instead of dropping it
        yield emit(a_buf, b_buf)


__all__ = [
    "synthetic_corpus",
    "unigram_table",
    "skipgram_batches",
    "cooccurrence_pairs",
]
