"""Host-side data ingestion — the ``DataStream`` stand-in.

Reference parity: the reference trains from a Flink ``DataStream[T]``
(collection sources in tests, file/Kafka sources in examples — SURVEY.md
§4, §2 #11).  The rebuild keeps a thin host-side streaming driver: plain
Python iterables for the event backend, and microbatch iterators (numpy
pytrees, static shapes) feeding the jitted step for the TPU backend —
host→device transfer happens only at this edge (SURVEY.md §2 "TPU-native
equivalent").
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, Optional, Sequence

import numpy as np


def from_collection(records: Sequence[Any]) -> Iterable[Any]:
    """Parity helper for ``env.fromCollection`` (reference tests' source)."""
    return list(records)


def microbatches(
    arrays: Dict[str, np.ndarray],
    batch_size: int,
    *,
    epochs: int = 1,
    drop_remainder: bool = False,
    pad_value: int = 0,
    shuffle_seed: Optional[int] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Slice column arrays into fixed-shape microbatches.

    The last partial batch is zero-padded with a ``"mask"`` column added
    (static shapes keep XLA from recompiling — SURVEY.md §7 "Dynamic
    shapes"); set ``drop_remainder`` to skip it instead.
    """
    n = len(next(iter(arrays.values())))
    for k, v in arrays.items():
        assert len(v) == n, f"column {k} length {len(v)} != {n}"
    rng = np.random.default_rng(shuffle_seed) if shuffle_seed is not None else None
    for _ in range(epochs):
        order = rng.permutation(n) if rng is not None else np.arange(n)
        for start in range(0, n, batch_size):
            idx = order[start : start + batch_size]
            if len(idx) < batch_size:
                if drop_remainder:
                    break
                pad = batch_size - len(idx)
                batch = {
                    k: np.concatenate(
                        [v[idx], np.full((pad,) + v.shape[1:], pad_value, v.dtype)]
                    )
                    for k, v in arrays.items()
                }
                batch["mask"] = np.concatenate(
                    [np.ones(len(idx), bool), np.zeros(pad, bool)]
                )
            else:
                batch = {k: v[idx] for k, v in arrays.items()}
                batch["mask"] = np.ones(batch_size, bool)
            yield batch


def partitioned_microbatches(
    arrays: Dict[str, np.ndarray],
    batch_size: int,
    num_partitions: int,
    *,
    key: str,
    capacity: int,
    epochs: int = 1,
    shuffle_seed: Optional[int] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Microbatches whose row-blocks are aligned to a dp partitioning of
    the ``key`` column (``partition = key * num_partitions // capacity``).

    The reference keys its MF input stream by user so each worker owns its
    users' state locally (SURVEY.md §2 "Data parallelism").  The TPU
    analogue: when worker state is dp-sharded by blocks of ``capacity //
    num_partitions`` rows, feeding batches whose i-th row-block only
    contains partition-i keys makes the state gather/scatter shard-local —
    zero cross-dp traffic for worker state.

    Each step emits ``batch_size`` rows = ``num_partitions`` equal blocks
    (padded + masked per block as partitions run dry); iteration ends when
    every partition is exhausted.
    """
    assert batch_size % num_partitions == 0, (batch_size, num_partitions)
    per = batch_size // num_partitions
    n = len(arrays[key])
    part_of = (
        arrays[key].astype(np.int64) * num_partitions // capacity
    ).clip(0, num_partitions - 1)
    rng = np.random.default_rng(shuffle_seed) if shuffle_seed is not None else None
    for _ in range(epochs):
        part_indices = []
        for p in range(num_partitions):
            idx = np.nonzero(part_of == p)[0]
            if rng is not None:
                idx = rng.permutation(idx)
            part_indices.append(idx)
        cursors = [0] * num_partitions
        while any(c < len(part_indices[p]) for p, c in enumerate(cursors)):
            blocks = {k: [] for k in arrays}
            mask_blocks = []
            for p in range(num_partitions):
                idx = part_indices[p][cursors[p] : cursors[p] + per]
                cursors[p] += per
                pad = per - len(idx)
                for k, v in arrays.items():
                    col = v[idx]
                    if pad:
                        col = np.concatenate(
                            [col, np.zeros((pad,) + v.shape[1:], v.dtype)]
                        )
                    blocks[k].append(col)
                mask_blocks.append(np.arange(per) < len(idx))
            batch = {k: np.concatenate(v) for k, v in blocks.items()}
            batch["mask"] = np.concatenate(mask_blocks)
            yield batch


def sparse_feature_batches(
    X: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    *,
    epochs: int = 1,
    shuffle_seed: Optional[int] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Densify a sparse (N, F) example matrix into the padded sparse batch
    contract consumed by the PA and FM logics: ``ids``/``values``/
    ``feat_mask`` (B, K) with K = max nonzeros, plus ``label``/``mask``.

    The multi-pull pattern (SURVEY.md §3.4): only present feature ids are
    pulled, padding lanes masked out.
    """
    n, _f = X.shape
    nnz_max = max(int((X != 0).sum(1).max()), 1)
    rng = np.random.default_rng(shuffle_seed) if shuffle_seed is not None else None
    for _ in range(epochs):
        order = rng.permutation(n) if rng is not None else np.arange(n)
        for s in range(0, n, batch_size):
            idx = order[s : s + batch_size]
            m = len(idx)
            ids = np.zeros((batch_size, nnz_max), np.int32)
            vals = np.zeros((batch_size, nnz_max), np.float32)
            fm = np.zeros((batch_size, nnz_max), bool)
            for r, i in enumerate(idx):
                nz = np.nonzero(X[i])[0]
                ids[r, : len(nz)] = nz
                vals[r, : len(nz)] = X[i, nz]
                fm[r, : len(nz)] = True
            labels = np.zeros(batch_size, np.float32)
            labels[:m] = y[idx]
            yield {
                "ids": ids,
                "values": vals,
                "feat_mask": fm,
                "label": labels,
                "mask": np.arange(batch_size) < m,
            }


def prefetch(it: Iterator[Any], size: int = 2) -> Iterator[Any]:
    """Background-thread prefetch of host batches (keeps the device fed
    while the host prepares the next microbatch)."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=size)
    sentinel = object()
    failure = object()

    def worker():
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # propagate, never swallow (a crashed
            q.put((failure, e))     # stream must not look like a clean end)
            return
        q.put(sentinel)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is sentinel:
            return
        if isinstance(item, tuple) and len(item) == 2 and item[0] is failure:
            raise item[1]
        yield item


__all__ = [
    "from_collection",
    "microbatches",
    "partitioned_microbatches",
    "sparse_feature_batches",
    "prefetch",
]
