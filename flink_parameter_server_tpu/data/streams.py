"""Host-side data ingestion — the ``DataStream`` stand-in.

Reference parity: the reference trains from a Flink ``DataStream[T]``
(collection sources in tests, file/Kafka sources in examples — SURVEY.md
§4, §2 #11).  The rebuild keeps a thin host-side streaming driver: plain
Python iterables for the event backend, and microbatch iterators (numpy
pytrees, static shapes) feeding the jitted step for the TPU backend —
host→device transfer happens only at this edge (SURVEY.md §2 "TPU-native
equivalent").
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np


def from_collection(records: Sequence[Any]) -> Iterable[Any]:
    """Parity helper for ``env.fromCollection`` (reference tests' source)."""
    return list(records)


def microbatches(
    arrays: Dict[str, np.ndarray],
    batch_size: int,
    *,
    epochs: int = 1,
    drop_remainder: bool = False,
    pad_value: int = 0,
    shuffle_seed: Optional[int] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Slice column arrays into fixed-shape microbatches.

    The last partial batch is zero-padded with a ``"mask"`` column added
    (static shapes keep XLA from recompiling — SURVEY.md §7 "Dynamic
    shapes"); set ``drop_remainder`` to skip it instead.
    """
    n = len(next(iter(arrays.values())))
    for k, v in arrays.items():
        assert len(v) == n, f"column {k} length {len(v)} != {n}"
    rng = np.random.default_rng(shuffle_seed) if shuffle_seed is not None else None
    for _ in range(epochs):
        order = rng.permutation(n) if rng is not None else np.arange(n)
        for start in range(0, n, batch_size):
            idx = order[start : start + batch_size]
            if len(idx) < batch_size:
                if drop_remainder:
                    break
                pad = batch_size - len(idx)
                batch = {
                    k: np.concatenate(
                        [v[idx], np.full((pad,) + v.shape[1:], pad_value, v.dtype)]
                    )
                    for k, v in arrays.items()
                }
                batch["mask"] = np.concatenate(
                    [np.ones(len(idx), bool), np.zeros(pad, bool)]
                )
            else:
                batch = {k: v[idx] for k, v in arrays.items()}
                batch["mask"] = np.ones(batch_size, bool)
            yield batch


def prefetch(it: Iterator[Any], size: int = 2) -> Iterator[Any]:
    """Background-thread prefetch of host batches (keeps the device fed
    while the host prepares the next microbatch)."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=size)
    sentinel = object()
    failure = object()

    def worker():
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # propagate, never swallow (a crashed
            q.put((failure, e))     # stream must not look like a clean end)
            return
        q.put(sentinel)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is sentinel:
            return
        if isinstance(item, tuple) and len(item) == 2 and item[0] is failure:
            raise item[1]
        yield item


__all__ = ["from_collection", "microbatches", "prefetch"]
