"""Duplicate-compressing scatter-add in pure XLA: sort → segment-sum →
one scatter per UNIQUE row, declared ``unique_indices=True``.

Reference parity (SURVEY.md §7 "Hard parts"): the reference's servers
fold each push message into a JVM hash map — duplicate keys cost one map
update each, cheap on a CPU.  On TPU, XLA lowers ``table.at[ids].add``
with duplicate indices to a serialized read-modify-write chain per
conflicting row: a Zipf-hot batch (the recommender workload) can send
hundreds of lanes at the SAME hot row, and the scatter's critical path
becomes the hottest row's duplicate count.  That serialization — not
bytes moved — is why the r2 trace shows the scatter fusion at ~3% of
HBM peak.

This module removes the duplicates *before* the scatter, entirely in
XLA (no Mosaic shape constraints, any dtype/width/backend):

  1. ``argsort`` the flat ids (TPU sort is fast — 1.3% of the r2 step),
  2. segment-sum runs of equal ids (``indices_are_sorted=True``),
  3. scatter the per-unique sums at the first-occurrence rows with
     ``unique_indices=True`` — XLA may now vectorize the RMW freely,
     no conflict serialization.

Empty slots (batch had fewer unique ids than lanes) are routed to
DISTINCT out-of-bounds ids: ``mode="drop"`` discards them, and
distinctness keeps the ``unique_indices`` promise honest — a shared
sentinel would be a lie XLA is allowed to miscompile.

This is the third ``scatter_impl`` arm ("xla_sorted"), between plain
"xla" and the Pallas kernel: same sorted-window idea as
:mod:`.pallas_scatter`, but letting XLA schedule the memory traffic.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def sorted_dedup_scatter_add(
    table: Array,
    ids: Array,
    deltas: Array,
    mask: Optional[Array] = None,
    *,
    oob: Optional[int] = None,
    ids_sorted: bool = False,
) -> Array:
    """``table.at[ids].add(deltas)`` with duplicates pre-combined.

    ``ids``: (n,) int32, out-of-range values (>= table rows, or >= oob)
    are dropped.  ``deltas``: (n, *value_shape).  ``mask``: optional (n,)
    bool — masked lanes never change the table.  On the default
    (unsorted) path their ids are routed out of bounds, so they don't
    even join a row's segment; under ``ids_sorted=True`` they instead
    contribute a zero-add to their own row's segment — the zero comes
    from a ``where``-SELECT of the delta (not a multiply), so even a
    NaN-poisoned masked delta is inert.

    ``ids_sorted=True`` is the caller's PROMISE that ``ids`` is already
    ascending **as given** (e.g. a batch pre-sorted by
    :func:`~..core.transform.make_train_step`'s ``presort``) — the
    argsort + delta permute are skipped, saving two batch-sized HBM
    passes.  "Ascending as given" includes any negative ids: they must
    sit at the FRONT of the array, because the invalid-lane handling
    below clips them to row 0 and a negative anywhere else would clip
    non-monotonically — making the ``indices_are_sorted`` assertion to
    XLA a lie it is allowed to miscompile.  Sentinel-routed arrays from
    this package's push path satisfy the precondition automatically:
    the routing sentinel is >= every valid id, so routed lanes sort to
    the END and the array stays ascending.  Do NOT pass a raw
    "negatives at the end" array directly.  Masked lanes and
    beyond-``oob`` tails are safe anywhere (zeroed delta + monotone
    clip keeps them inert and in order).
    """
    rows = table.shape[0]
    if oob is None:
        oob = rows
    n = ids.shape[0]
    if oob < rows:
        # oob below the table would make the routed-out lanes land on a
        # REAL row (id ``oob``) and add their un-zeroed delta sums to it
        # — the drop contract would be silently violated.
        raise ValueError(f"oob={oob} must be >= table rows ({rows})")
    if oob + n - 1 > jnp.iinfo(jnp.int32).max:
        # rep ids run up to oob + n - 1 in int32 lanes; beyond that they
        # wrap negative and mode="drop" can no longer be trusted to drop
        # them.  Tables this close to 2**31 rows need a sharded store
        # (per-shard local ids), not a bigger flat id space.
        raise ValueError(
            f"oob + n - 1 = {oob + n - 1} overflows int32 id space"
        )
    ids = ids.astype(jnp.int32)
    if ids_sorted:
        # Order-preserving invalid-lane handling: zero the delta and
        # CLIP the id (monotone) rather than re-routing it — negatives
        # become inert zero-adds on row 0, masked lanes zero-adds on
        # their own row, beyond-oob tails clip to oob and drop.
        invalid = ids < 0
        if mask is not None:
            invalid = invalid | ~mask
        deltas = jnp.where(
            invalid.reshape(invalid.shape + (1,) * (deltas.ndim - 1)),
            jnp.zeros_like(deltas),
            deltas,
        )
        sid = jnp.clip(ids, 0, oob)
        sdl = deltas
    else:
        if mask is not None:
            ids = jnp.where(mask, ids, oob)
        # Route negatives (would wrap before mode="drop") AND any id
        # beyond ``oob`` to exactly ``oob``: sorted ids then never
        # exceed ``oob``, so the empty-slot reps ``oob + slot``
        # (slot >= 1) cannot collide with a real segment's rep — the
        # unique_indices promise holds for arbitrary caller ids.
        ids = jnp.where((ids < 0) | (ids > oob), oob, ids)
        order = jnp.argsort(ids)
        sid = jnp.take(ids, order)
        sdl = jnp.take(deltas, order, axis=0)

    first = jnp.concatenate(
        [jnp.ones((1,), bool), sid[1:] != sid[:-1]]
    )
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1  # (n,) 0-based segment
    sums = jax.ops.segment_sum(
        sdl, seg, num_segments=n, indices_are_sorted=True
    )
    # representative id per segment slot; empty slots get DISTINCT
    # out-of-bounds ids (see module docstring)
    rep = oob + jnp.arange(n, dtype=jnp.int32)
    rep = rep.at[seg].set(sid)  # duplicate writers carry equal values
    # rep is ASCENDING by construction: slots 0..nseg-1 hold the sorted
    # unique ids (all <= oob), slots nseg.. hold oob+slot > oob — so the
    # scatter can also promise sorted indices to XLA.
    return table.at[rep].add(
        sums.astype(table.dtype), mode="drop",
        unique_indices=True, indices_are_sorted=True,
    )


__all__ = ["sorted_dedup_scatter_add"]
