"""Top-K maximum-inner-product search over the sharded item table.

Reference parity (SURVEY.md §2 #8): the reference's top-K recommendation
uses **LEMP-style pruning** (length-based candidate pruning with LI / COORD
/ INCR strategies) to avoid scoring every item per query on a CPU.  On TPU
the economics invert: a dense ``(B, dim) @ (dim, rows)`` block on the MXU
scores millions of items faster than branchy pruning, so we verify *output
parity, not mechanism parity* (SURVEY.md §7 "Hard parts"): exact top-K via

  1. each ``ps`` shard scores its rows with one matmul and takes a local
     ``lax.top_k`` (the TPU analogue of LEMP's bucket pruning — candidates
     are cut from ``rows`` to ``k`` *before* any communication),
  2. one all-gather of the per-shard (k scores, k ids) over ICI,
  3. a final ``top_k`` over ``shards·k`` candidates.

Communication is ``O(shards·k)`` per query instead of ``O(rows)`` — the
same asymptotic saving LEMP's pruning buys the reference.

All functions keep a static ``(B, k)`` output shape: when fewer than ``k``
candidates exist, the tail is padded with ``-inf`` scores and id ``-1``.

Round-5 decision note: an earlier ``approx_recall`` parameter routed the
row scan to ``jax.lax.approx_max_k`` (the TPU approximate-top-k unit).
Off-TPU that op computes exactly, so its recall/speedup claim at our
shapes was untestable in this environment, and no hardware window opened
across rounds 3–5 to measure it — per the round-4 verdict's decision
rule the unproven parameter was REMOVED from the public surface.  The
on-chip A/B (recall + speedup at 1M rows) lives self-contained in
``benchmarks/microbench.py topk``; reinstating the parameter is a
two-line change once hardware shows a win.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.compat import shard_map

Array = jax.Array


def _pad_topk(scores: Array, ids: Array, k: int) -> Tuple[Array, Array]:
    """Pad a (B, k_eff) top-k result out to the requested static k."""
    k_eff = scores.shape[-1]
    if k_eff >= k:
        return scores[..., :k], ids[..., :k]
    pad = k - k_eff
    scores = jnp.pad(scores, ((0, 0), (0, pad)), constant_values=-jnp.inf)
    ids = jnp.pad(ids, ((0, 0), (0, pad)), constant_values=-1)
    return scores, ids


def dense_topk(
    table: Array,
    queries: Array,
    k: int,
    *,
    valid_rows: Optional[int] = None,
) -> Tuple[Array, Array]:
    """Single-device exact top-k: one MXU matmul + ``lax.top_k``.

    Returns (scores (B,k), ids (B,k)); padded with -inf/-1 when the table
    has fewer than ``k`` rows."""
    scores = queries @ table.T  # (B, rows)
    if valid_rows is not None and valid_rows < table.shape[0]:
        pad = jnp.arange(table.shape[0]) >= valid_rows
        scores = jnp.where(pad[None, :], -jnp.inf, scores)
    k_eff = min(k, table.shape[0])
    top_scores, top_ids = jax.lax.top_k(scores, k_eff)
    return _pad_topk(top_scores, top_ids, k)


def sharded_topk(
    table: Array,
    queries: Array,
    k: int,
    *,
    mesh: Mesh,
    ps_axis: str = "ps",
    valid_rows: Optional[int] = None,
) -> Tuple[Array, Array]:
    """Exact top-k over a ps-sharded table (see module docstring).

    ``table``: (padded_rows, dim) sharded P(ps, None).
    ``queries``: (B, dim), replicated.
    Returns replicated (scores (B,k), ids (B,k)) with *global* row ids,
    padded with -inf/-1 when fewer than ``k`` rows exist.
    """
    num_shards = mesh.shape[ps_axis]

    def body(local_table: Array, q: Array):
        rows = local_table.shape[0]
        shard = jax.lax.axis_index(ps_axis)
        lo = shard * rows
        scores = q @ local_table.T  # (B, rows_local) — MXU block
        if valid_rows is not None:
            global_row = lo + jnp.arange(rows)
            scores = jnp.where(
                (global_row >= valid_rows)[None, :], -jnp.inf, scores
            )
        kk = min(k, rows)
        local_scores, local_ids = jax.lax.top_k(scores, kk)
        local_ids = local_ids + lo
        # all-gather candidates over ICI: (shards, B, kk) → (B, shards*kk)
        all_scores = jax.lax.all_gather(local_scores, ps_axis)
        all_ids = jax.lax.all_gather(local_ids, ps_axis)
        all_scores = jnp.moveaxis(all_scores, 0, 1).reshape(q.shape[0], -1)
        all_ids = jnp.moveaxis(all_ids, 0, 1).reshape(q.shape[0], -1)
        k_eff = min(k, num_shards * kk)
        final_scores, pos = jax.lax.top_k(all_scores, k_eff)
        final_ids = jnp.take_along_axis(all_ids, pos, axis=1)
        return _pad_topk(final_scores, final_ids, k)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(ps_axis, None), P(*(None,) * queries.ndim)),
        out_specs=(P(None, None), P(None, None)),
        # After the all_gather every ps shard computes the identical final
        # top-k; the VMA checker can't infer that replication statically.
        check_vma=False,
    )(table, queries)


__all__ = ["dense_topk", "sharded_topk"]
