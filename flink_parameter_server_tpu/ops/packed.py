"""Lane-packed table layout: k narrow rows per 128-lane physical row.

Reference parity: the reference's stores hold *narrow* values — MF item
factors (dim 64), FM rows (dim 17), PA scalar weights — as JVM objects
where row width is free (SURVEY.md §2 #3, #7, #9).  On TPU, width is NOT
free: the VPU/MXU lane width is 128 and real Mosaic requires 128-aligned
minor dims for dynamic-offset DMA (measured — benchmarks/mosaic_probe.py).
A (capacity, 17) table either wastes 7/8 of every vector register or is
ineligible for the pallas scatter kernel entirely.

The TPU-native answer is a *packed physical layout*: ``k = 128 // d``
logical rows live side-by-side in one ``(phys_capacity, 128)`` physical
row.  Logical row ``r`` maps to physical row ``r // k``, lane offset
``(r % k) * d``:

  * **pull** = one physical-row gather + one ``take_along_axis`` lane
    slice (both vectorized XLA gathers, batch-sized),
  * **push** = lane-shift each delta row to its offset (one batch-sized
    gather), then scatter-add at PHYSICAL row granularity — which is
    exactly the shape the pallas sorted-window kernel wants (width 128).
    Two logical rows sharing a physical row collide in different lanes,
    so the add semantics are unchanged, and Zipf-hot neighbours now
    share windows (fewer HBM round trips, fuller DMAs).

Everything here is pure XLA; the pallas kernel consumes the packed form
unmodified.  ``ShardedParamStore(layout="packed")`` wires it in.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

LANES = 128


def pack_k(row_width: int) -> int:
    """Logical rows per 128-lane physical row (1 when width >= 128)."""
    if row_width <= 0:
        raise ValueError(f"row width must be positive, got {row_width}")
    return max(1, LANES // row_width)


def phys_width(row_width: int) -> int:
    """Physical lane width: 128 for narrow rows, else the padded width."""
    if row_width >= LANES:
        return ((row_width + LANES - 1) // LANES) * LANES
    return LANES


def phys_rows(capacity: int, row_width: int) -> int:
    """Physical rows needed for ``capacity`` logical rows."""
    k = pack_k(row_width)
    return (capacity + k - 1) // k


def pack_table(values: Array, capacity_phys: Optional[int] = None) -> Array:
    """(capacity, d) logical values -> (capacity_phys, phys_width) packed."""
    capacity, d = values.shape
    k = pack_k(d)
    w = phys_width(d)
    if capacity_phys is None:
        capacity_phys = phys_rows(capacity, d)
    pad_rows = capacity_phys * k - capacity
    v = jnp.pad(values, ((0, pad_rows), (0, 0)))
    v = v.reshape(capacity_phys, k * d)
    return jnp.pad(v, ((0, 0), (0, w - k * d)))


def unpack_table(packed: Array, capacity: int, row_width: int) -> Array:
    """(capacity_phys, phys_width) packed -> (capacity, d) logical values."""
    capacity_phys, w = packed.shape
    k = pack_k(row_width)
    v = packed[:, : k * row_width].reshape(capacity_phys * k, row_width)
    return v[:capacity]


def packed_pull(packed: Array, ids: Array, row_width: int) -> Array:
    """Gather logical rows ``ids`` (pre-clipped) from the packed table."""
    k = pack_k(row_width)
    ids = ids.astype(jnp.int32)
    phys_vals = jnp.take(packed, ids // k, axis=0)  # (n, phys_width)
    if k == 1:
        return phys_vals[:, :row_width]
    cols = (ids % k)[:, None] * row_width + jnp.arange(row_width)[None, :]
    return jnp.take_along_axis(phys_vals, cols, axis=1)


def lane_shift_deltas(deltas: Array, ids: Array, row_width: int) -> Array:
    """(n, d) deltas -> (n, phys_width) rows shifted to their lane offset.

    Row ``i`` carries ``deltas[i]`` at lanes ``[(ids[i] % k) * d, ... + d)``
    and zeros elsewhere — ready to scatter-add at physical-row granularity.
    """
    n, d = deltas.shape
    assert d == row_width, (d, row_width)
    k = pack_k(d)
    w = phys_width(d)
    if k == 1:
        return jnp.pad(deltas, ((0, 0), (0, w - d)))
    t = (ids.astype(jnp.int32) % k)[:, None]  # (n, 1) sub-row index
    lane = jnp.arange(w)[None, :]  # (1, w)
    src = lane - t * d  # source column per output lane
    valid = (src >= 0) & (src < d)
    padded = jnp.pad(deltas, ((0, 0), (0, w - d)))
    out = jnp.take_along_axis(padded, jnp.clip(src, 0, w - 1), axis=1)
    return jnp.where(valid, out, jnp.zeros_like(out))


def lane_unshift(rows: Array, ids: Array, row_width: int) -> Array:
    """Inverse of :func:`lane_shift_deltas`: slice each (phys_width,)
    row back down to the (row_width,) slice at its id's lane offset."""
    k = pack_k(row_width)
    if k == 1:
        return rows[:, :row_width]
    cols = (
        (ids.astype(jnp.int32) % k)[:, None] * row_width
        + jnp.arange(row_width)[None, :]
    )
    return jnp.take_along_axis(rows, cols, axis=1)


def packed_phys_ids(ids: Array, row_width: int) -> Array:
    """Logical ids -> physical row ids (sorting by these keeps id order)."""
    return ids.astype(jnp.int32) // pack_k(row_width)


__all__ = [
    "LANES",
    "pack_k",
    "phys_width",
    "phys_rows",
    "pack_table",
    "unpack_table",
    "packed_pull",
    "lane_shift_deltas",
    "lane_unshift",
    "packed_phys_ids",
]
