"""Vectorised hash families for sketches and id load-balancing.

Reference parity: the reference's sketch package relies on families of
pairwise-independent hash functions for bloom/count and tug-of-war (AMS)
sketches (SURVEY.md §2 #10), and routes parameters to server subtasks by
``hash(paramId) % psParallelism`` (§2 "Model parallelism").

TPU-first: TPUs have no fast int64 path, so everything here is pure
**uint32** arithmetic with natural wraparound — multiply-xorshift mixing
(murmur3-finalizer style), branch-free, vmappable, fusable into one
elementwise kernel per microbatch.  Also works under ``jax_enable_x64=0``.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_MIX1 = np.uint32(0x85EBCA6B)
_MIX2 = np.uint32(0xC2B2AE35)
_GOLDEN = np.uint32(0x9E3779B1)


def _fmix32(h: Array) -> Array:
    """murmur3 finalizer: full-avalanche uint32 mixing."""
    h = h ^ (h >> np.uint32(16))
    h = h * _MIX1
    h = h ^ (h >> np.uint32(13))
    h = h * _MIX2
    h = h ^ (h >> np.uint32(16))
    return h


def fmix32_np(h: np.ndarray) -> np.ndarray:
    """Host-side (numpy) mirror of :func:`_fmix32` — same constants,
    same avalanche, so routing decisions taken on the HOST (the cluster
    partitioner picking a shard before a network send,
    ``cluster/partition.py``) agree bit-for-bit with any device-side
    use of this family.  Input is coerced to uint32; wraparound is the
    hash, so the overflow warnings numpy would raise are suppressed
    locally."""
    with np.errstate(over="ignore"):
        h = np.asarray(h).astype(np.uint32)
        h ^= h >> np.uint32(16)
        h = (h * _MIX1).astype(np.uint32)
        h ^= h >> np.uint32(13)
        h = (h * _MIX2).astype(np.uint32)
        h ^= h >> np.uint32(16)
    return h


def hash_params(num_hashes: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Draw per-hash (a, b) uint32 constants (a odd), deterministic in
    ``seed``."""
    rng = np.random.default_rng(seed)
    a = rng.integers(1, 2**32, num_hashes, dtype=np.uint64).astype(np.uint32) | 1
    b = rng.integers(0, 2**32, num_hashes, dtype=np.uint64).astype(np.uint32)
    return a, b


def bucket_hash(x: Array, a: np.ndarray, b: np.ndarray, m: int) -> Array:
    """``h_i(x) = fmix32(a_i·x + b_i) mod m`` for every hash i.

    ``x``: (...,) non-negative int ids.  Returns (..., num_hashes) int32
    buckets in [0, m).
    """
    xu = x.astype(jnp.uint32)[..., None]
    h = _fmix32(jnp.asarray(a)[None, :] * xu + jnp.asarray(b)[None, :])
    return (h % jnp.uint32(m)).astype(jnp.int32)


def sign_hash(x: Array, a: np.ndarray, b: np.ndarray) -> Array:
    """±1 hash per (x, hash i) — the tug-of-war sketch's sign family.
    Returns (..., num_hashes) float32 in {-1, +1}."""
    xu = x.astype(jnp.uint32)[..., None]
    h = _fmix32(jnp.asarray(a)[None, :] * xu + jnp.asarray(b)[None, :])
    return jnp.where((h >> np.uint32(31)) == 0, 1.0, -1.0).astype(jnp.float32)


def pair_key(x: Array, y: Array, num_keys: int) -> Array:
    """Stable key for an unordered (x, y) co-occurrence pair, folded into
    [0, num_keys) — the bloom co-occurrence sketch's pair id."""
    lo = jnp.minimum(x, y).astype(jnp.uint32)
    hi = jnp.maximum(x, y).astype(jnp.uint32)
    k = _fmix32(hi * _GOLDEN + lo)
    return (k % jnp.uint32(num_keys)).astype(jnp.int32)


def permute_ids(ids: Array, capacity: int, seed: int = 0x5BD1) -> Array:
    """Bijective spreading of ids across [0, capacity): defeats
    block-sharding hotspots for Zipf-skewed ids (the rebuild's answer to
    the reference's mod-hash routing under skew — see
    parallel/collectives.py docstring).

    ``capacity`` must be a power of two (the padded table capacity
    usually is): an odd-multiplier affine map mod 2^k is a permutation,
    and uint32 wraparound composes correctly with the final mask.
    """
    assert capacity & (capacity - 1) == 0, (
        f"permute_ids requires power-of-two capacity, got {capacity}"
    )
    a = np.uint32(((((seed << 1) | 1) * 0x9E3779B1) & 0xFFFFFFFF) | 1)
    h = ids.astype(jnp.uint32) * a + np.uint32(0x7F4A7C15)
    return (h & jnp.uint32(capacity - 1)).astype(jnp.int32)


__all__ = [
    "fmix32_np",
    "hash_params",
    "bucket_hash",
    "sign_hash",
    "pair_key",
    "permute_ids",
]
