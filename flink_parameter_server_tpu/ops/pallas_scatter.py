"""Pallas TPU kernel: sorted window scatter-add ("the native component").

Reference parity: SURVEY.md §7 "Hard parts" names sparse scatter-add under
skewed id distributions (Criteo, word2vec) as the rebuild's native-kernel
obligation — the role CUDA kernels would play in a GPU framework.

Algorithm (duplicate-compressing windowed read-modify-write):

  1. XLA-side, sort the (ids, deltas) batch by id — hot ids become
     contiguous *runs*.
  2. The kernel walks the sorted lanes in GROUPS OF 8 with a sequential
     TPU grid; per-lane ids sit in SMEM via scalar prefetch.  Table rows
     are read and written in aligned 8-row WINDOWS (row ``r`` lives in
     window ``r // 8`` at slot ``r % 8``): the current window's deltas
     accumulate into an (8, d) f32 register, and each unique window gets
     ONE HBM read-modify-write (async 8-row DMA in, add, DMA out).  A
     Zipf-hot id touches HBM once per microbatch instead of once per
     occurrence, and adjacent hot ids share a window — HBM traffic is
     O(unique windows) · 8 rows instead of O(batch) serialized rows.
  3. Lane placement never slices a VMEM ref at a per-lane offset (real
     Mosaic rejects sub-8-row dynamic slices — see
     benchmarks/mosaic_probe.py for the measured rules).  A group's 8
     delta rows are loaded as one aligned (8, d) tile and placed into
     window slots with an 8×8 one-hot select matmul; groups that sit in
     a single window (the common case for sorted Zipf ids) take one
     matmul for all 8 lanes.
  4. Run carry state (current window + partial sums) lives in scratch
     that persists across grid steps (TPU grids execute sequentially),
     so windows spanning chunk boundaries are handled for free.

Mosaic-measured shape requirements for the compiled path (the store and
the collective plane fall back to XLA scatter — with a warning — when
they are not met; see :func:`supports_shape`):

  - flattened row width ``d`` must be a multiple of 128 (lane width:
    dynamic-offset HBM DMAs require 128-aligned minor extents),
  - table capacity must be a multiple of 8 (windows must not overrun).

``scatter_add(...)`` is the public wrapper: turns OOB/masked lanes into
zero-deltas on the last row, sorts, and invokes the kernel with
``input_output_aliases`` (the table is updated in place when the caller's
jit donates it; on an eager call the wrapper copies the table first so the
functional all-mutators-return-new-stores contract holds).  On non-TPU
backends it runs in interpreter mode (slow but exact) so the unit tests
cover the kernel logic on the CPU mesh; ``use_pallas="auto"`` in callers
picks the XLA path off-TPU instead.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

WINDOW = 8  # table rows per DMA window (Mosaic sublane tile)

# In-kernel lane shifting costs sub_k unrolled roll+select ops per 8-lane
# group; past this point (e.g. scalar rows, sub_k=128) the XLA-side
# pre-shift (ops.packed.lane_shift_deltas + physical ids) is cheaper
# despite its phys-width delta buffer.
MAX_INKERNEL_SUB_K = 16


def supports_shape(capacity: int, dim: int) -> bool:
    """True if the compiled kernel supports a (capacity, dim) table."""
    return dim % 128 == 0 and capacity % WINDOW == 0


def _kernel(ids_ref, deltas_ref, table_ref, out_ref,
            acc_ref, win_ref, carry_ref, sem_in, sem_out, *, chunk: int,
            sub_k: int = 1, sub_width: int = 0):
    """One grid step = one chunk of sorted lanes (chunk % 8 == 0).

    ids_ref: (N,) int32 in SMEM (scalar-prefetched, whole batch).
      With ``sub_k > 1`` (lane-packed table, ops/packed.py) these are
      sorted LOGICAL ids; id ``i`` lives in physical row ``i // sub_k``
      at lane offset ``(i % sub_k) * sub_width``.
    deltas_ref: (chunk, d) VMEM block for this grid step (table dtype).
      Packed: d is the LOGICAL width — the kernel lane-shifts each
      group's rows in-register (``sub_k`` static rolls), so the HBM
      delta buffer never pays the phys-width expansion.
    table_ref/out_ref: aliased (capacity, W) HBM table (dropped lanes
      arrive as zero-deltas on the last row, so no sentinel is needed).
    acc_ref: (8, W) VMEM — the current window's accumulated deltas
      (f32 for float tables; table dtype for integer tables, where an
      f32 round trip would drop increments past 2**24).
    win_ref: (8, W) VMEM staging window for the HBM read-modify-write.
    carry_ref: (1,) int32 SMEM — the current window index (-1 = none).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    c = pl.program_id(0)
    num_chunks = pl.num_programs(0)
    base = c * chunk
    table_w = win_ref.shape[1]

    @pl.when(c == 0)
    def _init():
        carry_ref[0] = -1
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def flush(w):
        """table[w*8 : w*8+8] += acc (one 8-row RMW round trip)."""
        dma_in = pltpu.make_async_copy(
            table_ref.at[pl.ds(w * WINDOW, WINDOW)], win_ref, sem_in
        )
        dma_in.start()
        dma_in.wait()
        win_ref[:] = (
            win_ref[:].astype(acc_ref.dtype) + acc_ref[:]
        ).astype(win_ref.dtype)
        dma_out = pltpu.make_async_copy(
            win_ref, out_ref.at[pl.ds(w * WINDOW, WINDOW)], sem_out
        )
        dma_out.start()
        dma_out.wait()

    slot_iota = jax.lax.broadcasted_iota(jnp.int32, (WINDOW, 1), 0)

    def place(G, j, s_j):
        """acc[s_j, :] += G[j, :] — static row slice + iota-mask
        broadcast (exact VPU ops; no per-lane VMEM slicing)."""
        row = G[j:j + 1, :]  # static slice of a loaded value
        sel = (slot_iota == s_j).astype(acc_ref.dtype)  # (8, 1) one-hot
        acc_ref[:] = acc_ref[:] + sel * row

    def shift_group(G, gbase):
        """Lane-shift a packed group's (8, d) logical rows to their
        (8, W) physical-lane positions: ``sub_k`` STATIC rolls selected
        by each lane's sub-row index (no dynamic lane indexing)."""
        lane8 = jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0)
        t_col = jnp.zeros((8, 1), jnp.int32)
        for j in range(8):
            t_j = ids_ref[gbase + j] % sub_k
            t_col = t_col + jnp.where(lane8 == j, t_j, 0)
        G_pad = jnp.pad(G, ((0, 0), (0, table_w - sub_width)))
        out = jnp.zeros_like(G_pad)
        for tt in range(sub_k):
            sel_t = (t_col == tt).astype(G_pad.dtype)
            out = out + sel_t * jnp.roll(G_pad, tt * sub_width, axis=1)
        return out

    def group(g, _):
        gbase = base + g * 8
        G = deltas_ref[pl.ds(g * 8, 8), :].astype(acc_ref.dtype)
        if sub_k > 1:
            G = shift_group(G, gbase)
            w_first = (ids_ref[gbase] // sub_k) // WINDOW
            w_last = (ids_ref[gbase + 7] // sub_k) // WINDOW
        else:
            w_first = ids_ref[gbase] // WINDOW
            w_last = ids_ref[gbase + 7] // WINDOW

        @pl.when(w_first == w_last)
        def _one_window():
            # the whole group lands in one window (sorted ids): one
            # flush check for all 8 lanes
            @pl.when(w_first != carry_ref[0])
            def _switch():
                @pl.when(carry_ref[0] >= 0)
                def _():
                    flush(carry_ref[0])
                acc_ref[:] = jnp.zeros_like(acc_ref)
                carry_ref[0] = w_first

            for j in range(8):
                place(G, j, (ids_ref[gbase + j] // sub_k) % WINDOW)

        @pl.when(w_first != w_last)
        def _boundary_group():
            # window boundary inside the group: place lanes one at a
            # time with flush checks (rare — at most once per window)
            for j in range(8):
                phys_j = ids_ref[gbase + j] // sub_k
                w_j = phys_j // WINDOW

                @pl.when(w_j != carry_ref[0])
                def _switch(w_j=w_j):
                    @pl.when(carry_ref[0] >= 0)
                    def _():
                        flush(carry_ref[0])
                    acc_ref[:] = jnp.zeros_like(acc_ref)
                    carry_ref[0] = w_j

                place(G, j, phys_j % WINDOW)

        return 0

    jax.lax.fori_loop(0, chunk // 8, group, 0)

    @pl.when(c == num_chunks - 1)
    def _final():
        @pl.when(carry_ref[0] >= 0)
        def _():
            flush(carry_ref[0])


def sorted_scatter_add_pallas(
    table: Array, sorted_ids: Array, sorted_deltas: Array, *,
    chunk: int = 512, interpret: bool = False,
    sub_k: int = 1, sub_width: int = 0,
) -> Array:
    """Core kernel call: ids MUST be sorted ascending and in-range;
    dropped lanes must carry zero deltas (they may alias any row).

    ``sub_k > 1``: the table is lane-PACKED (ops/packed.py) — ids are
    LOGICAL, ``sorted_deltas`` stay at the logical ``sub_width``, and
    the kernel shifts them to their lane slice in-register (the HBM
    delta buffer never pays the 128-lane expansion).

    ``input_output_aliases`` makes the kernel update the table buffer in
    place.  Under an enclosing jit that is donation-aware and safe; on an
    *eager* call the caller's concrete buffer would be invalidated, so we
    copy it first (eager pushes are the cold path — tests, notebooks)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, dim = sorted_deltas.shape
    capacity = table.shape[0]
    if sub_k > 1:
        if sub_width != dim:
            raise ValueError(
                f"packed deltas width {dim} != sub_width {sub_width}"
            )
        if sub_k * sub_width > table.shape[1]:
            raise ValueError(
                f"sub_k {sub_k} x sub_width {sub_width} exceeds table "
                f"width {table.shape[1]}"
            )
        if sub_k > MAX_INKERNEL_SUB_K:
            raise ValueError(
                f"sub_k {sub_k} > {MAX_INKERNEL_SUB_K}: the in-kernel "
                f"shift unrolls sub_k rolls per group — pre-shift with "
                f"ops.packed.lane_shift_deltas and scatter at physical "
                f"ids instead (ShardedParamStore.push does this "
                f"automatically)"
            )
    if capacity % WINDOW != 0:
        # structural for the windowed DMA in EVERY mode: the last window
        # would overrun (interpret clamps the slice => silent corruption)
        raise ValueError(
            f"pallas scatter kernel needs capacity % {WINDOW} == 0 (the "
            f"table is read/written in {WINDOW}-row windows); got "
            f"{capacity}. Use scatter_add(), which pads, or align the "
            f"table (ShardedParamStore does)."
        )
    # The Mosaic lane constraint applies to the PHYSICAL table width (the
    # HBM DMA extent) — with sub_k > 1 the deltas stay at the narrow
    # logical width by design (shifted in-register), so gate on the table.
    hbm_width = table.shape[1] if sub_k > 1 else dim
    if not interpret and not supports_shape(capacity, hbm_width):
        raise ValueError(
            f"pallas scatter kernel needs the physical row width to be a "
            f"multiple of 128 on real Mosaic (lane alignment); got table "
            f"({capacity}, {table.shape[1]}), deltas width {dim}. Callers "
            f"should gate on supports_shape() and use the XLA scatter "
            f"path instead."
        )
    if chunk % 8 != 0:
        raise ValueError(f"chunk must be a multiple of 8, got {chunk}")

    if not isinstance(table, jax.core.Tracer):
        table = jnp.copy(table)

    n_pad = ((n + chunk - 1) // chunk) * chunk
    if n_pad != n:
        # pad with zero-deltas onto the last (logical) row (largest id
        # keeps the lanes sorted; zero delta makes them no-ops)
        last_id = capacity * sub_k - 1 if sub_k > 1 else capacity - 1
        sorted_ids = jnp.concatenate(
            [sorted_ids, jnp.full((n_pad - n,), last_id, jnp.int32)]
        )
        sorted_deltas = jnp.concatenate(
            [sorted_deltas, jnp.zeros((n_pad - n, dim), sorted_deltas.dtype)]
        )

    grid = (n_pad // chunk,)
    kernel = functools.partial(
        _kernel, chunk=chunk, sub_k=sub_k, sub_width=sub_width
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (chunk, dim), lambda c, ids: (c, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),  # table stays in HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM(
                (WINDOW, table.shape[1]),
                jnp.float32
                if jnp.issubdtype(table.dtype, jnp.floating)
                else table.dtype,
            ),  # acc
            pltpu.VMEM((WINDOW, table.shape[1]), table.dtype),  # RMW window
            pltpu.SMEM((1,), jnp.int32),  # carry window index
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        grid_spec=grid_spec,
        input_output_aliases={2: 0},  # (ids, deltas, table) -> table
        interpret=interpret,
    )(sorted_ids, sorted_deltas.astype(table.dtype), table)


def scatter_add(
    table: Array,
    ids: Array,
    deltas: Array,
    mask: Optional[Array] = None,
    *,
    chunk: int = 512,
    interpret: Optional[bool] = None,
    sub_k: int = 1,
    sub_width: int = 0,
) -> Array:
    """Duplicate-compressing scatter-add: ``table[ids] += deltas``.

    Drop-in replacement for the XLA ``.at[].add`` path in
    :func:`..core.store.push` (OOB/masked lanes dropped).  Sorts by id,
    then one 8-row-window HBM read-modify-write per unique window.

    ``sub_k > 1``: ``table`` is lane-PACKED physical rows (ops/packed.py),
    ``ids`` are LOGICAL and ``deltas`` are (n, sub_width) logical rows —
    the kernel lane-shifts them in-register.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if sub_k > 1:
        capacity, dim = table.shape[0], sub_width
        logical_cap = capacity * sub_k
    else:
        capacity, dim = table.shape[0], int(np.prod(table.shape[1:]))
        logical_cap = capacity
    cap8 = ((capacity + WINDOW - 1) // WINDOW) * WINDOW
    if cap8 != capacity:
        # window-align with a pad copy (correctness path for direct
        # callers; ShardedParamStore aligns capacity at create time so
        # the store's perf path never takes this)
        padded = jnp.pad(
            table.reshape(capacity, -1), ((0, cap8 - capacity), (0, 0))
        )
        out = scatter_add(
            padded, ids, deltas, mask, chunk=chunk, interpret=interpret,
            sub_k=sub_k, sub_width=sub_width,
        )
        return out[:capacity].reshape(table.shape)
    flat_ids = ids.reshape(-1).astype(jnp.int32)
    flat_deltas = deltas.reshape(-1, dim)
    oob = (flat_ids < 0) | (flat_ids >= logical_cap)
    if mask is not None:
        oob = oob | ~mask.reshape(-1)
    # Dropped lanes become zero-deltas on the last row (no sentinel row —
    # avoiding a full-table concatenate+slice copy per push).
    work_ids = jnp.where(oob, logical_cap - 1, flat_ids)
    flat_deltas = jnp.where(
        oob[:, None], jnp.zeros_like(flat_deltas), flat_deltas
    )
    order = jnp.argsort(work_ids)
    sorted_ids = jnp.take(work_ids, order)
    sorted_deltas = jnp.take(flat_deltas, order, axis=0)
    out = sorted_scatter_add_pallas(
        table.reshape(capacity, -1), sorted_ids, sorted_deltas,
        chunk=chunk, interpret=interpret, sub_k=sub_k, sub_width=sub_width,
    )
    return out.reshape(table.shape)


__all__ = ["scatter_add", "sorted_scatter_add_pallas", "supports_shape",
           "WINDOW", "MAX_INKERNEL_SUB_K"]
