"""Pallas TPU kernel: sorted-run scatter-add ("the native component").

Reference parity: SURVEY.md §7 "Hard parts" names sparse scatter-add under
skewed id distributions (Criteo, word2vec) as the rebuild's native-kernel
obligation — the role CUDA kernels would play in a GPU framework.

Algorithm (duplicate-compressing read-modify-write):

  1. XLA-side, sort the (ids, deltas) batch by id — hot ids become
     contiguous *runs*.
  2. The kernel walks the sorted lanes with a sequential TPU grid; the
     per-lane ids sit in SMEM via scalar prefetch.  It accumulates each
     run into a VMEM row register and performs ONE HBM read-modify-write
     per *unique* id (async DMA row in, vector add, DMA row out) — a
     Zipf-hot id touching HBM once per microbatch instead of once per
     occurrence.  XLA's generic scatter serialises every duplicate lane;
     this kernel's HBM traffic is O(unique) instead of O(batch).
  3. Run carry state (current id + partial sum) lives in scratch that
     persists across grid steps (TPU grids execute sequentially), so runs
     spanning chunk boundaries are handled for free.

``scatter_add(...)`` is the public wrapper: turns OOB/masked lanes into
zero-deltas on the last row, sorts, and invokes the kernel with
``input_output_aliases`` (the table is updated in place when the caller's
jit donates it; on an eager call the wrapper copies the table first so the
functional all-mutators-return-new-stores contract holds).  On non-TPU
backends it runs in interpreter mode (slow but exact) so the unit tests
cover the kernel logic on the CPU mesh; ``use_pallas="auto"`` in callers
picks the XLA path off-TPU instead.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _kernel(ids_ref, deltas_ref, table_ref, out_ref, acc_ref, carry_ref,
            row_ref, sem_in, sem_out, *, chunk: int, dim: int, capacity: int):
    """One grid step = one chunk of sorted lanes.

    ids_ref: (N,) int32 in SMEM (scalar-prefetched, whole batch).
    deltas_ref: (chunk, dim) VMEM block for this grid step.
    table_ref/out_ref: aliased (capacity, dim) HBM table (dropped lanes
      arrive as zero-deltas on the last row, so no sentinel is needed).
    acc_ref: (1, dim) VMEM — the current run's partial sum.
    carry_ref: (1,) int32 SMEM — the current run's id (-1 = none).
    row_ref: (1, dim) VMEM — staging row for the HBM read-modify-write.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    c = pl.program_id(0)
    num_chunks = pl.num_programs(0)
    base = c * chunk
    n_total = ids_ref.shape[0]

    @pl.when(c == 0)
    def _init():
        carry_ref[0] = -1
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def flush(row_id):
        """table[row_id] += acc (one RMW round trip)."""
        dma_in = pltpu.make_async_copy(
            table_ref.at[pl.ds(row_id, 1)], row_ref, sem_in
        )
        dma_in.start()
        dma_in.wait()
        row_ref[:] = row_ref[:] + acc_ref[:]
        dma_out = pltpu.make_async_copy(
            row_ref, out_ref.at[pl.ds(row_id, 1)], sem_out
        )
        dma_out.start()
        dma_out.wait()

    def lane(i, _):
        idx = base + i
        lane_id = ids_ref[idx]
        cur = carry_ref[0]

        @pl.when(jnp.logical_and(cur != lane_id, cur >= 0))
        def _boundary():
            flush(cur)

        @pl.when(cur != lane_id)
        def _new_run():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            carry_ref[0] = lane_id

        acc_ref[:] = acc_ref[:] + deltas_ref[pl.ds(i, 1), :]
        return 0

    n_here = jnp.minimum(chunk, n_total - base)
    jax.lax.fori_loop(0, n_here, lane, 0)

    @pl.when(c == num_chunks - 1)
    def _final():
        @pl.when(carry_ref[0] >= 0)
        def _():
            flush(carry_ref[0])


def sorted_scatter_add_pallas(
    table: Array, sorted_ids: Array, sorted_deltas: Array, *,
    chunk: int = 512, interpret: bool = False,
) -> Array:
    """Core kernel call: ids MUST be sorted ascending and in-range;
    dropped lanes must carry zero deltas (they may alias any row).

    ``input_output_aliases`` makes the kernel update the table buffer in
    place.  Under an enclosing jit that is donation-aware and safe; on an
    *eager* call the caller's concrete buffer would be invalidated, so we
    copy it first (eager pushes are the cold path — tests, notebooks)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not isinstance(table, jax.core.Tracer):
        table = jnp.copy(table)

    n, dim = sorted_deltas.shape
    capacity = table.shape[0]
    n_pad = ((n + chunk - 1) // chunk) * chunk
    if n_pad != n:
        # pad with zero-deltas onto the last row (largest id keeps the
        # lanes sorted; zero delta makes them no-ops)
        sorted_ids = jnp.concatenate(
            [sorted_ids, jnp.full((n_pad - n,), capacity - 1, jnp.int32)]
        )
        sorted_deltas = jnp.concatenate(
            [sorted_deltas, jnp.zeros((n_pad - n, dim), sorted_deltas.dtype)]
        )

    grid = (n_pad // chunk,)
    kernel = functools.partial(
        _kernel, chunk=chunk, dim=dim, capacity=capacity
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (chunk, dim), lambda c, ids: (c, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),  # table stays in HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((1, dim), table.dtype),  # acc
            pltpu.SMEM((1,), jnp.int32),  # carry id
            pltpu.VMEM((1, dim), table.dtype),  # RMW staging row
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        grid_spec=grid_spec,
        input_output_aliases={2: 0},  # (ids, deltas, table) -> table
        interpret=interpret,
    )(sorted_ids, sorted_deltas.astype(table.dtype), table)


def scatter_add(
    table: Array,
    ids: Array,
    deltas: Array,
    mask: Optional[Array] = None,
    *,
    chunk: int = 512,
    interpret: Optional[bool] = None,
) -> Array:
    """Duplicate-compressing scatter-add: ``table[ids] += deltas``.

    Drop-in replacement for the XLA ``.at[].add`` path in
    :func:`..core.store.push` (OOB/masked lanes dropped).  Sorts by id,
    then one HBM read-modify-write per unique id.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    capacity, dim = table.shape[0], int(np.prod(table.shape[1:]))
    flat_ids = ids.reshape(-1).astype(jnp.int32)
    flat_deltas = deltas.reshape(-1, dim)
    oob = (flat_ids < 0) | (flat_ids >= capacity)
    if mask is not None:
        oob = oob | ~mask.reshape(-1)
    # Dropped lanes become zero-deltas on the last row (no sentinel row —
    # avoiding a full-table concatenate+slice copy per push).
    work_ids = jnp.where(oob, capacity - 1, flat_ids)
    flat_deltas = jnp.where(oob[:, None], 0.0, flat_deltas)
    order = jnp.argsort(work_ids)
    sorted_ids = jnp.take(work_ids, order)
    sorted_deltas = jnp.take(flat_deltas, order, axis=0)
    out = sorted_scatter_add_pallas(
        table.reshape(capacity, dim), sorted_ids, sorted_deltas,
        chunk=chunk, interpret=interpret,
    )
    return out.reshape(table.shape)


__all__ = ["scatter_add", "sorted_scatter_add_pallas"]
