"""Fused MF-SGD Pallas kernel: pull + SGD + push in one pass (item side).

The compiled MF step (core/transform.make_train_step) is three XLA ops on
the item table: gather ``pulled = table[items]`` (B rows of HBM read),
SGD math, scatter-add of ``item_deltas`` (B-row read-modify-write) — plus
the ``pulled``/``deltas`` (B, d) intermediates living in HBM between
them.  For the gather/scatter-bound MF workload (SURVEY.md §6-§7: the
headline metric is bandwidth-limited), that is ~4 B-row traversals plus
2 B-row intermediates per step.

This kernel fuses the item side into ONE sorted pass (the same
sorted-run structure as ops/pallas_scatter.py): lanes arrive sorted by
item id; each *unique* item row is DMA'd in once, every lane of its run
computes ``err = r - p·q`` against that pulled snapshot and accumulates
the item delta in VMEM, and the updated row is DMA'd out once.  Per-lane
user rows stay OUTSIDE the kernel as a pre-gathered VMEM-blocked input
and the per-lane user deltas as a blocked output (XLA's vectorized
gather/scatter is the right tool for the unsorted user side — fusing it
would serialize on per-row DMA latency).  Item-side HBM traffic drops
from O(B) reads + O(B) RMW + 2 intermediates to **O(unique) RMW, no
intermediates** — under Zipf skew unique << B.

Semantics match the batched step's (same pulled snapshot per microbatch,
duplicate deltas summed, masked lanes contribute nothing, masked-lane
predictions computed against the real item row) — verified lane-for-lane
against make_train_step in tests.  Two documented divergences, both on
*invalid* lanes only: an out-of-range item id yields a prediction against
the last table row (the unfused path predicts against a clipped row), and
its lane updates no user row (the unfused path still applies the user
delta from the clipped pull).

Real-Mosaic layout (measured on a v5e with benchmarks/mosaic_probe.py —
sub-8-row dynamic VMEM slices and non-128-multiple minor dims are
rejected by the hardware compiler, which interpreter mode cannot see):
lanes are processed in GROUPS OF 8 at 8-aligned offsets, the item table
is read/written in aligned 8-row WINDOWS (item row ``r`` = window
``r // 8``, slot ``r % 8``), per-lane rows are extracted/placed with
iota masks and static value slices (never per-lane ref slicing), and
each group's outputs are written as one aligned (8, d) store.  The
compiled path requires ``d % 128 == 0`` and ``capacity % 8 == 0``
(:func:`supports_shape`); callers fall back to the unfused XLA step
otherwise.  A unique window costs ONE 8-row DMA round trip per
microbatch, so item-side HBM traffic is O(unique windows) — under Zipf
skew far below the O(batch) row traversals of the unfused step.

Status: logic-verified in interpreter mode on CPU; chunk size and the
on-chip win await a live TPU (benchmarks/microbench.py mf_fused).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

# One measured Mosaic rule, one home: the scatter kernel module owns the
# window size and shape gate; this kernel shares them.
from .pallas_scatter import WINDOW, supports_shape  # noqa: E402


def _kernel(ids_ref, p_ref, r_ref, m_ref, table_ref,
            out_table_ref, udelta_ref, pred_ref,
            win_ref, acc_ref, carry_ref, sem_in, sem_out,
            *, chunk: int, lr: float, reg: float,
            sub_k: int = 1, sub_width: int = 0):
    """One grid step = one chunk of lanes sorted by item id (chunk % 8 == 0).

    ids_ref: (N,) int32 SMEM (scalar-prefetched) — sorted LOGICAL item
      ids.  With the packed layout (``sub_k`` > 1, ops/packed.py), item
      ``i`` lives in physical row ``i // sub_k`` at lane offset
      ``(i % sub_k) * sub_width``; the kernel windows over PHYSICAL rows
      and masks per-lane math to the item's lane slice.  ``sub_k == 1``
      is the dense layout (slice == the whole row).
    p_ref: (chunk, d) VMEM — pre-gathered user rows (f32; lane-SHIFTED
      to the item's slice when packed).
    r_ref / m_ref: (chunk, 1) VMEM — ratings / mask (f32).
    table_ref/out_table_ref: aliased (phys_capacity, d) HBM item table.
    udelta_ref: (chunk, d) VMEM out — per-lane user deltas (f32;
      lane-shifted when packed — caller unshifts).
    pred_ref: (chunk, 1) VMEM out — per-lane predictions (f32).
    win_ref: (8, d) VMEM — the current window's PULLED snapshot (table
      dtype; all lanes of a window compute against it).
    acc_ref: (8, d) f32 VMEM — the current window's item-delta sums.
    carry_ref: (1,) int32 SMEM — current window index (-1 = none).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    c = pl.program_id(0)
    num_chunks = pl.num_programs(0)
    base = c * chunk

    @pl.when(c == 0)
    def _init():
        carry_ref[0] = -1
        acc_ref[:] = jnp.zeros_like(acc_ref)
        win_ref[:] = jnp.zeros_like(win_ref)

    def flush(w):
        """item_table[w*8 : w*8+8] = win + acc (one RMW per window)."""
        win_ref[:] = (
            win_ref[:].astype(jnp.float32) + acc_ref[:]
        ).astype(win_ref.dtype)
        dma = pltpu.make_async_copy(
            win_ref, out_table_ref.at[pl.ds(w * WINDOW, WINDOW)], sem_out
        )
        dma.start()
        dma.wait()

    def load(w):
        """Pull window w's snapshot (before any of this batch's deltas)."""
        dma = pltpu.make_async_copy(
            table_ref.at[pl.ds(w * WINDOW, WINDOW)], win_ref, sem_in
        )
        dma.start()
        dma.wait()

    slot_iota = jax.lax.broadcasted_iota(jnp.int32, (WINDOW, 1), 0)
    if sub_k > 1:
        lane128 = jax.lax.broadcasted_iota(
            jnp.int32, (1, win_ref.shape[1]), 1
        )

    def switch_window(w):
        @pl.when(w != carry_ref[0])
        def _():
            @pl.when(carry_ref[0] >= 0)
            def _():
                flush(carry_ref[0])
            load(w)
            acc_ref[:] = jnp.zeros_like(acc_ref)
            carry_ref[0] = w

    def lane_math(W, P, j, id_j, r_j, m_j):
        """SGD math for one lane against window snapshot W.

        Returns (pred_row, udelta_row) as (1, 1)/(1, d) values; the item
        delta is accumulated into acc at the lane's physical slot (and,
        when packed, only within its lane slice — the other sub-rows of
        the slot belong to other items).
        """
        phys = id_j // sub_k
        sel = (slot_iota == phys % WINDOW).astype(jnp.float32)  # (8, 1)
        q = jnp.sum(sel * W, axis=0, keepdims=True)   # (1, d) win[slot]
        p = P[j:j + 1, :]                             # static value slice
        # packed: p is lane-shifted to the item's slice (zero elsewhere),
        # so the dot never sees other sub-rows' lanes
        pred = jnp.sum(p * q, axis=1, keepdims=True)  # (1, 1)
        e = (m_j * lr) * (r_j - pred)                 # (1, 1)
        ud = e * q - (m_j * lr * reg) * p             # (1, d)
        idlt = e * p - (m_j * lr * reg) * q           # (1, d)
        if sub_k > 1:
            # e*q / reg*q leak outside the item's slice — mask them off
            sl = (lane128 // sub_width == id_j % sub_k).astype(jnp.float32)
            ud = sl * ud
            idlt = sl * idlt
        acc_ref[:] = acc_ref[:] + sel * idlt
        return pred, ud

    def group(g, _):
        gbase = base + g * 8
        P = p_ref[pl.ds(g * 8, 8), :]
        r_col = r_ref[pl.ds(g * 8, 8), :]
        m_col = m_ref[pl.ds(g * 8, 8), :]
        w_first = (ids_ref[gbase] // sub_k) // WINDOW
        w_last = (ids_ref[gbase + 7] // sub_k) // WINDOW
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0)

        @pl.when(w_first == w_last)
        def _one_window():
            # whole group in one window (sorted ids): one flush check,
            # then all 8 lanes against the same snapshot
            switch_window(w_first)
            W = win_ref[:].astype(jnp.float32)
            UD = jnp.zeros_like(acc_ref[:])
            PRED = jnp.zeros((8, 1), jnp.float32)
            for j in range(8):
                lane_sel = (lane_iota == j).astype(jnp.float32)
                pred, ud = lane_math(
                    W, P, j, ids_ref[gbase + j],
                    r_col[j:j + 1, :], m_col[j:j + 1, :],
                )
                UD = UD + lane_sel * ud
                PRED = PRED + lane_sel * pred
            udelta_ref[pl.ds(g * 8, 8), :] = UD
            pred_ref[pl.ds(g * 8, 8), :] = PRED

        @pl.when(w_first != w_last)
        def _boundary_group():
            # window boundary inside the group: per-lane flush checks;
            # W re-read per lane because the window can change under us
            UD = jnp.zeros_like(acc_ref[:])
            PRED = jnp.zeros((8, 1), jnp.float32)
            for j in range(8):
                id_j = ids_ref[gbase + j]
                switch_window((id_j // sub_k) // WINDOW)
                lane_sel = (lane_iota == j).astype(jnp.float32)
                pred, ud = lane_math(
                    win_ref[:].astype(jnp.float32), P, j, id_j,
                    r_col[j:j + 1, :], m_col[j:j + 1, :],
                )
                UD = UD + lane_sel * ud
                PRED = PRED + lane_sel * pred
            udelta_ref[pl.ds(g * 8, 8), :] = UD
            pred_ref[pl.ds(g * 8, 8), :] = PRED

        return 0

    jax.lax.fori_loop(0, chunk // 8, group, 0)

    @pl.when(c == num_chunks - 1)
    def _final():
        @pl.when(carry_ref[0] >= 0)
        def _():
            flush(carry_ref[0])


def _sorted_fused_call(
    item_table: Array,
    s_items: Array,
    s_p: Array,
    s_r: Array,
    s_m: Array,
    *,
    learning_rate: float,
    regularization: float,
    chunk: int,
    interpret: bool,
    sub_k: int = 1,
    sub_width: int = 0,
) -> Tuple[Array, Array, Array]:
    """Kernel invocation on pre-sorted, chunk-padded lanes.

    Returns ``(new_item_table, udeltas, preds)`` in sorted lane order —
    the composable core shared by the single-shard wrapper and the
    ps-sharded shard_map wrapper."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    capacity, dim = item_table.shape
    n_pad = s_items.shape[0]
    if capacity % WINDOW != 0:
        # structural for the windowed DMA in EVERY mode: the last window
        # would overrun (interpret clamps the slice => silent corruption)
        raise ValueError(
            f"fused MF pallas kernel needs capacity % {WINDOW} == 0 (the "
            f"item table is read/written in {WINDOW}-row windows); got "
            f"{capacity}. Use fused_mf_sgd(), which pads, or align the "
            f"table (ShardedParamStore does)."
        )
    if not interpret and not supports_shape(capacity, dim):
        raise ValueError(
            f"fused MF pallas kernel needs dim % 128 == 0 on real Mosaic "
            f"(lane alignment); got item table ({capacity}, {dim}). "
            f"Callers should gate on supports_shape() and use the unfused "
            f"XLA step instead."
        )
    if chunk % 8 != 0:
        raise ValueError(f"chunk must be a multiple of 8, got {chunk}")

    if not isinstance(item_table, jax.core.Tracer):
        # eager call: aliasing would invalidate the caller's buffer
        item_table = jnp.copy(item_table)

    grid = (n_pad // chunk,)
    kernel = functools.partial(
        _kernel, chunk=chunk,
        lr=float(learning_rate), reg=float(regularization),
        sub_k=sub_k, sub_width=sub_width,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk, dim), lambda c, ids: (c, 0),
                         memory_space=pltpu.VMEM),  # p
            pl.BlockSpec((chunk, 1), lambda c, ids: (c, 0),
                         memory_space=pltpu.VMEM),  # r
            pl.BlockSpec((chunk, 1), lambda c, ids: (c, 0),
                         memory_space=pltpu.VMEM),  # m
            pl.BlockSpec(memory_space=pl.ANY),  # item table (HBM)
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # item table out (aliased)
            pl.BlockSpec((chunk, dim), lambda c, ids: (c, 0),
                         memory_space=pltpu.VMEM),  # user deltas
            pl.BlockSpec((chunk, 1), lambda c, ids: (c, 0),
                         memory_space=pltpu.VMEM),  # predictions
        ],
        scratch_shapes=[
            pltpu.VMEM((8, dim), item_table.dtype),  # window snapshot
            pltpu.VMEM((8, dim), jnp.float32),  # acc (window deltas)
            pltpu.SMEM((1,), jnp.int32),  # carry window index
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    s_r2 = s_r.reshape(-1, 1)
    s_m2 = s_m.reshape(-1, 1)
    return pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct(item_table.shape, item_table.dtype),
            jax.ShapeDtypeStruct((n_pad, dim), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        grid_spec=grid_spec,
        input_output_aliases={4: 0},  # (ids, p, r, m, table) -> table
        interpret=interpret,
    )(s_items, s_p, s_r2, s_m2, item_table)


def _sort_pad_lanes(
    capacity: int,
    user_table: Array,
    users: Array,
    items: Array,
    ratings: Array,
    mask: Optional[Array],
    chunk: int,
):
    """Sort lanes by item id and pad to a chunk multiple.

    Only lanes with INVALID ids are routed to the last row (they have no
    real row to read); masked-but-valid lanes keep their id so their
    returned prediction is computed against the real item row, exactly
    like the unfused path.  Deltas are zeroed via the mask either way."""
    n = items.shape[0]
    dim = user_table.shape[1]
    items = items.astype(jnp.int32)
    users = users.astype(jnp.int32)
    valid = (items >= 0) & (items < capacity)
    m = valid if mask is None else (mask & valid)
    work_items = jnp.where(valid, items, capacity - 1)

    order = jnp.argsort(work_items)
    s_items = jnp.take(work_items, order)
    s_users = jnp.take(users, order)
    s_r = jnp.take(ratings.astype(jnp.float32), order)
    s_m = jnp.take(m, order).astype(jnp.float32)
    # vectorized XLA gather for the unsorted user side (f32 compute)
    s_p = jnp.take(
        user_table, jnp.clip(s_users, 0, user_table.shape[0] - 1), axis=0
    ).astype(jnp.float32)

    n_pad = ((n + chunk - 1) // chunk) * chunk
    if n_pad != n:
        pad = n_pad - n
        s_items = jnp.concatenate(
            [s_items, jnp.full((pad,), capacity - 1, jnp.int32)]
        )
        s_users = jnp.concatenate([s_users, jnp.zeros((pad,), jnp.int32)])
        s_r = jnp.concatenate([s_r, jnp.zeros((pad,), jnp.float32)])
        s_m = jnp.concatenate([s_m, jnp.zeros((pad,), jnp.float32)])
        s_p = jnp.concatenate([s_p, jnp.zeros((pad, dim), jnp.float32)])
    return order, s_items, s_users, s_r, s_m, s_p


def fused_mf_sgd(
    user_table: Array,
    item_table: Array,
    users: Array,
    items: Array,
    ratings: Array,
    mask: Optional[Array] = None,
    *,
    learning_rate: float = 0.01,
    regularization: float = 0.0,
    chunk: int = 1024,
    interpret: Optional[bool] = None,
) -> Tuple[Array, Array, Array]:
    """One fused MF-SGD microbatch step (single shard).

    Returns ``(new_user_table, new_item_table, predictions)`` with
    predictions in the original lane order — semantically identical to
    the unfused gather→SGD→scatter step (same snapshot, sum-combined
    duplicates, masked lanes inert; see module docstring for the two
    invalid-lane divergences).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = items.shape[0]
    capacity = item_table.shape[0]
    cap8 = ((capacity + WINDOW - 1) // WINDOW) * WINDOW
    if cap8 != capacity:
        # window-align with a pad copy (correctness path for direct
        # callers; stores align capacity at create time).  Invalid lanes
        # are routed against the REAL last row before padding, so the
        # documented invalid-lane prediction semantics are unchanged.
        valid = (items >= 0) & (items < capacity)
        routed = jnp.where(valid, items, capacity - 1)
        padded = jnp.pad(item_table, ((0, cap8 - capacity), (0, 0)))
        new_users, new_items, pred = fused_mf_sgd(
            user_table, padded, users, routed, ratings,
            valid if mask is None else (mask & valid),
            learning_rate=learning_rate, regularization=regularization,
            chunk=chunk, interpret=interpret,
        )
        return new_users, new_items[:capacity], pred
    order, s_items, s_users, s_r, s_m, s_p = _sort_pad_lanes(
        capacity, user_table, users, items, ratings, mask, chunk
    )
    new_item_table, udeltas, preds = _sorted_fused_call(
        item_table, s_items, s_p, s_r, s_m,
        learning_rate=learning_rate, regularization=regularization,
        chunk=chunk, interpret=interpret,
    )
    # user side: vectorized XLA scatter-add of the per-lane deltas
    # (padding lanes carry zero deltas onto user row 0 — inert)
    new_user_table = user_table.at[s_users].add(
        udeltas.astype(user_table.dtype), mode="drop"
    )
    # un-permute predictions to the original lane order (scatter-based
    # inverse permutation — no second argsort)
    pred = jnp.zeros((n,), jnp.float32).at[order[:n]].set(preds[:n, 0])
    return new_user_table, new_item_table, pred


def fused_mf_sgd_packed(
    user_table: Array,
    packed_item_table: Array,
    users: Array,
    items: Array,
    ratings: Array,
    mask: Optional[Array] = None,
    *,
    capacity: int,
    dim: int,
    learning_rate: float = 0.01,
    regularization: float = 0.0,
    chunk: int = 1024,
    interpret: Optional[bool] = None,
) -> Tuple[Array, Array, Array]:
    """The fused step over a lane-PACKED item table (ops/packed.py) —
    the reference's native narrow dims (MF 64, FM 17) on the compiled
    kernel, which needs 128-wide rows on real Mosaic.

    ``packed_item_table``: (phys_capacity, 128·m) as built by
    ``ShardedParamStore(layout="packed")`` / ``ops.packed.pack_table``.
    ``capacity``/``dim``: the LOGICAL item count and row width.

    XLA side does the lane plumbing (both batch-sized gathers): user
    rows are pre-shifted to their item's lane slice, and the kernel's
    lane-shifted user deltas are unshifted before the user scatter.  The
    kernel itself windows over physical rows and masks its math to the
    item's slice — semantics identical to :func:`fused_mf_sgd` on the
    equivalent dense table (asserted by tests/test_pallas_mf.py).

    Returns ``(new_user_table, new_packed_item_table, predictions)``.
    """
    from .packed import lane_shift_deltas, lane_unshift, pack_k

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k = pack_k(dim)
    nphys = packed_item_table.shape[0]
    if capacity > nphys * k:
        # a mismatched capacity would route lanes past the physical
        # table — interpret mode clamps the window DMA and silently
        # corrupts, so fail loudly here, and BEFORE window-align padding
        # (padding grows the table, which would let an over-capacity
        # claim slip past this guard into the zero-filled pad rows)
        raise ValueError(
            f"capacity {capacity} exceeds the packed table's "
            f"{nphys} physical rows x k={k} = {nphys * k} logical rows"
        )
    nphys8 = ((nphys + WINDOW - 1) // WINDOW) * WINDOW
    if nphys8 != nphys:
        # window-align with a pad copy, like fused_mf_sgd does for dense
        # tables (pack_table's default phys row count is NOT 8-aligned;
        # stores align at create time)
        padded = jnp.pad(packed_item_table, ((0, nphys8 - nphys), (0, 0)))
        new_users, new_packed, pred = fused_mf_sgd_packed(
            user_table, padded, users, items, ratings, mask,
            capacity=capacity, dim=dim,
            learning_rate=learning_rate, regularization=regularization,
            chunk=chunk, interpret=interpret,
        )
        return new_users, new_packed[:nphys], pred
    n = items.shape[0]
    order, s_items, s_users, s_r, s_m, s_p = _sort_pad_lanes(
        capacity, user_table, users, items, ratings, mask, chunk
    )
    s_p_shifted = lane_shift_deltas(s_p, s_items, dim)
    new_packed, udeltas, preds = _sorted_fused_call(
        packed_item_table, s_items, s_p_shifted, s_r, s_m,
        learning_rate=learning_rate, regularization=regularization,
        chunk=chunk, interpret=interpret, sub_k=k, sub_width=dim,
    )
    # unshift the lane-shifted user deltas back to logical width
    ud = lane_unshift(udeltas, s_items, dim)
    new_user_table = user_table.at[s_users].add(
        ud.astype(user_table.dtype), mode="drop"
    )
    pred = jnp.zeros((n,), jnp.float32).at[order[:n]].set(preds[:n, 0])
    return new_user_table, new_packed, pred


def fused_mf_sgd_sharded(
    user_table: Array,
    item_table: Array,
    users: Array,
    items: Array,
    ratings: Array,
    mask: Optional[Array] = None,
    *,
    mesh,
    ps_axis: str = "ps",
    learning_rate: float = 0.01,
    regularization: float = 0.0,
    chunk: int = 1024,
    interpret: Optional[bool] = None,
) -> Tuple[Array, Array, Array]:
    """The fused step over a ps-sharded item table (the giant-table
    layout: table row-blocked over ``ps``, batch + user table replicated).

    Each ps shard runs the fused kernel on its local block with lanes
    outside its row range masked off; since a lane's item row lives on
    exactly one shard, per-lane user deltas and predictions are disjoint
    across shards and ONE ``psum`` over ``ps`` assembles them — there is
    no separate pull round-trip at all.  The reference's whole
    pull/push message plane for this step becomes that single collective
    (SURVEY.md §2 "TPU-native equivalent").

    dp-sharding the batch is NOT supported here: item blocks would be
    replicated over dp and the in-kernel writes would diverge across dp
    rows (the unfused/locality paths handle that case).

    Divergence from the single-shard fused step, on *invalid* lanes
    only: a globally out-of-range item id yields prediction 0.0 (no
    shard owns it), where the single-shard step predicts against the
    routed last row.  Valid lanes — masked included — are identical.
    """
    from ..utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    ps = mesh.shape[ps_axis]
    for ax, sz in mesh.shape.items():
        if ax != ps_axis and sz != 1:
            raise ValueError(
                f"fused sharded step supports ps-only meshes (item blocks "
                f"would be replicated over axis {ax!r} (size {sz}) and the "
                f"in-kernel writes would diverge)"
            )
    capacity, dim = item_table.shape
    if capacity % ps != 0:
        raise ValueError(
            f"item table capacity {capacity} must divide evenly over "
            f"ps={ps} shards (pad the table — ShardedParamStore does "
            f"this automatically)"
        )
    rows = capacity // ps
    n = items.shape[0]
    lr, reg = learning_rate, regularization

    def body(local_table, u_table, b_users, b_items, b_ratings, b_mask):
        ps_idx = jax.lax.axis_index(ps_axis)
        lo = ps_idx * rows
        rel = b_items.astype(jnp.int32) - lo
        hit = (rel >= 0) & (rel < rows)
        m = hit if b_mask is None else (hit & b_mask)
        order, s_items, s_users, s_r, s_m, s_p = _sort_pad_lanes(
            rows, u_table, b_users, jnp.where(hit, rel, -1), b_ratings,
            m, chunk,
        )
        rows8 = ((rows + WINDOW - 1) // WINDOW) * WINDOW
        block = (
            local_table if rows8 == rows
            else jnp.pad(local_table, ((0, rows8 - rows), (0, 0)))
        )
        new_block, udeltas, preds = _sorted_fused_call(
            block, s_items, s_p, s_r, s_m,
            learning_rate=lr, regularization=reg,
            chunk=chunk, interpret=interpret,
        )
        new_block = new_block[:rows]
        # un-permute to lane order, then assemble across shards: each
        # lane was computed on exactly its item's owning shard (zero
        # elsewhere), so one psum yields the full per-lane values
        lane_udelta = (
            jnp.zeros((n, udeltas.shape[1]), jnp.float32)
            .at[order[:n]]
            .set(udeltas[:n])
        )
        lane_pred = (
            jnp.zeros((n,), jnp.float32).at[order[:n]].set(preds[:n, 0])
        )
        # a non-owning shard computed its (routed-row) pred for foreign
        # lanes — only the owner contributes (udeltas are already zeroed
        # by the kernel mask, which includes ``hit``)
        lane_pred = jnp.where(hit, lane_pred, 0.0)
        lane_udelta = jax.lax.psum(lane_udelta, ps_axis)
        lane_pred = jax.lax.psum(lane_pred, ps_axis)
        # user table is replicated over ps; every shard applies the same
        # psum'd deltas, so it stays replicated
        new_users = u_table.at[b_users.astype(jnp.int32)].add(
            lane_udelta.astype(u_table.dtype), mode="drop"
        )
        return new_block, new_users, lane_pred

    rep = P()
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(ps_axis, None), rep, rep, rep, rep, rep),
        out_specs=(P(ps_axis, None), rep, rep),
        check_vma=False,
    )
    mask_in = (
        jnp.ones(n, bool) if mask is None else mask
    )
    new_item_table, new_user_table, pred = fn(
        item_table, user_table, users, items, ratings, mask_in
    )
    return new_user_table, new_item_table, pred


def make_fused_mf_train_step(
    *,
    learning_rate: float = 0.01,
    regularization: float = 0.0,
    chunk: int = 1024,
    interpret: Optional[bool] = None,
    layout: str = "dense",
    capacity: Optional[int] = None,
    dim: Optional[int] = None,
):
    """A drop-in alternative to ``make_train_step(OnlineMatrixFactorization,
    spec)`` for the MF flagship: same ``(table, state, batch) -> (table,
    state, out)`` signature (state = user factor table), fused item side.

    ``layout="packed"`` (with the LOGICAL ``capacity`` and ``dim``) runs
    the fused kernel on a lane-packed item table — pass the table from a
    ``ShardedParamStore(layout="packed")``."""
    if layout not in ("dense", "packed"):
        # 'auto' is a STORE-construction convenience; here the layout
        # must match the concrete table being passed — silently treating
        # an unknown value as dense would read a packed table as dense
        # rows and train garbage
        raise ValueError(
            f"layout must be 'dense' or 'packed' (matching the item "
            f"table's actual layout), got {layout!r}"
        )
    if layout == "packed" and (capacity is None or dim is None):
        raise ValueError("layout='packed' needs capacity= and dim=")

    if layout == "packed":
        fused_fn = fused_mf_sgd_packed
        layout_kwargs = {"capacity": capacity, "dim": dim}
    else:
        fused_fn = fused_mf_sgd
        layout_kwargs = {}

    def step(item_table, user_table, batch):
        mask = batch.get("mask")
        new_users, new_items, pred = fused_fn(
            user_table,
            item_table,
            batch["user"],
            batch["item"],
            batch["rating"],
            mask,
            learning_rate=learning_rate,
            regularization=regularization,
            chunk=chunk,
            interpret=interpret,
            **layout_kwargs,
        )
        m = (
            jnp.ones_like(pred)
            if mask is None
            else mask.astype(jnp.float32)
        )
        out = {
            "prediction": pred,
            "error": (batch["rating"].astype(jnp.float32) - pred) * m,
        }
        return new_items, new_users, out

    return step


__all__ = [
    "fused_mf_sgd",
    "fused_mf_sgd_packed",
    "fused_mf_sgd_sharded",
    "make_fused_mf_train_step",
    "supports_shape",
    "WINDOW",
]
