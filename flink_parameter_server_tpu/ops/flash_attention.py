"""Flash attention for the dense transformer path (TPU splash kernel).

Reference parity: the reference has nothing sequence-related (SURVEY.md
§2 "Sequence/context parallelism": absent) — this is a beyond-reference
TPU-native component backing BASELINE config 5 (transformer-LM) and the
long-context story.  The O(T²) scores matrix of
:func:`..parallel.ring_attention.reference_attention` never touches HBM:
the splash kernel (JAX's production TPU flash attention,
``jax.experimental.pallas.ops.tpu.splash_attention``) streams K/V blocks
through VMEM with an online softmax, skipping fully-masked blocks of the
causal mask entirely (~2× fewer FLOPs at long T), with a custom VJP for
training.

Integration contract (matching ``reference_attention``):

  * layout ``(B, T, H, D)`` in, ``(B, T, H, D)`` out (the kernel's
    native layout is ``(H, T, D)``; batch is vmapped),
  * causal masking, ``1/sqrt(D)`` scaling applied to q (the kernel does
    NOT scale internally),
  * fp32 softmax accumulation regardless of input dtype (kernel-internal).

``supports_shape`` gates the compiled path conservatively (T a multiple
of 128 sublane-tiles, D a multiple of 64 lanes); the on-chip constraint
set is re-measured by ``benchmarks/kernel_smoke.py`` whenever a TPU is
live.  Off-TPU the caller should prefer ``reference_attention`` —
interpret mode exists for parity tests, not perf.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def supports_shape(seq_len: int, head_dim: int) -> bool:
    """True if the compiled splash kernel supports (T, D)."""
    return seq_len % 128 == 0 and head_dim % 64 == 0 and seq_len >= 128


def eligible(seq_len: int, head_dim: int, mesh=None) -> bool:
    """The 'auto' gate: compiled flash is used iff this holds.  ONE
    home for the predicate — the transformer's attention dispatch and
    the benchmarks' run-labeling both call it (a drifted copy would
    mislabel A/B rows)."""
    return (
        mesh is None
        and jax.default_backend() == "tpu"
        and supports_shape(seq_len, head_dim)
    )


def _dp_only_mesh(mesh, dp_axis: str) -> bool:
    return (
        mesh is not None
        and dp_axis in mesh.axis_names
        and all(
            size == 1
            for name, size in mesh.shape.items()
            if name != dp_axis
        )
    )


def eligible_dp(
    seq_len: int, head_dim: int, batch: int, mesh, dp_axis: str = "dp"
) -> bool:
    """The 'auto' gate for DATA-PARALLEL meshes: flash runs per dp shard
    under shard_map (attention is batch-elementwise, so a dp-only mesh
    needs no cross-shard traffic).  sp/tp/pp meshes stay on their ring /
    reference paths."""
    return (
        _dp_only_mesh(mesh, dp_axis)
        and jax.default_backend() == "tpu"
        and supports_shape(seq_len, head_dim)
        and batch % mesh.shape[dp_axis] == 0
    )


def flash_mha_dp(
    q: Array,
    k: Array,
    v: Array,
    *,
    mesh,
    dp_axis: str = "dp",
    interpret: Optional[bool] = None,
) -> Array:
    """Causal flash attention with the batch dim sharded over ``dp``:
    one kernel invocation per shard, no collectives (attention never
    mixes batch rows).  Inside a jit whose activations are already
    dp-sharded this is a sharding-preserving no-op wrapper around the
    kernel — the multi-chip deployment of BASELINE config 5."""
    from ..utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    B = q.shape[0]
    dp = mesh.shape[dp_axis]
    if B % dp != 0:
        raise ValueError(
            f"flash_mha_dp needs batch {B} divisible by dp={dp}"
        )
    spec = P(dp_axis, None, None, None)
    fn = shard_map(
        lambda a, b, c: flash_mha(a, b, c, interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


@functools.lru_cache(maxsize=32)
def _make_kernel(seq_len: int, num_heads: int, interpret: bool):
    """Kernel construction is Python-side work (mask metadata build) —
    cache per static shape so repeated traces reuse it.

    ``ensure_compile_time_eval``: the splash builder materialises small
    mask arrays; when the first call happens inside a jit trace those
    would be tracers, and caching a tracer-carrying kernel poisons every
    later trace (UnexpectedTracerError).  Forcing compile-time eval makes
    the cached kernel concrete regardless of caller context."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    with jax.ensure_compile_time_eval():
        mask = sm.MultiHeadMask(
            [sm.CausalMask((seq_len, seq_len)) for _ in range(num_heads)]
        )
        return sk.make_splash_mha_single_device(
            mask=mask, interpret=interpret
        )


def flash_mha(
    q: Array,
    k: Array,
    v: Array,
    *,
    interpret: Optional[bool] = None,
) -> Array:
    """Causal flash attention on ``(B, T, H, D)`` tensors.

    Drop-in for ``reference_attention(q, k, v)`` (causal=True) — parity
    asserted to kernel-accumulation tolerance in
    tests/test_flash_attention.py, gradients included.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, T, H, D = q.shape
    if not supports_shape(T, D):
        raise ValueError(
            f"flash_mha needs T % 128 == 0 and D % 64 == 0; got T={T}, "
            f"D={D}. Callers should gate on supports_shape() and fall "
            f"back to reference_attention."
        )
    kernel = _make_kernel(T, H, interpret)
    # scale q in f32 (a bf16 pre-scale would round before the kernel's
    # f32 accumulation even starts)
    scale = 1.0 / (D**0.5)
    q_scaled = (q.astype(jnp.float32) * scale).astype(q.dtype)

    def one(qb, kb, vb):
        out = kernel(
            qb.transpose(1, 0, 2),  # (H, T, D)
            kb.transpose(1, 0, 2),
            vb.transpose(1, 0, 2),
        )
        return out.transpose(1, 0, 2)

    return jax.vmap(one)(q_scaled, k, v).astype(v.dtype)


__all__ = [
    "flash_mha",
    "flash_mha_dp",
    "supports_shape",
    "eligible",
    "eligible_dp",
]
