"""Intra-batch duplicate-id handling — the "combination sender" layer.

Reference parity (SURVEY.md §2 #6, §7 step 4): the reference's batching
("combination") senders buffer pull/push messages and flush them combined
on count/timer triggers.  In the batched TPU model the *microbatch itself*
is the combination buffer; what remains of the concern is how duplicate
ids inside one microbatch combine.

By default deltas for the same id SUM (exact minibatch SGD — every
gradient was computed at the same pulled snapshot).  Under Zipf-hot id
distributions (word2vec, Criteo) a hot id can appear hundreds of times per
batch, making its effective step ~count × lr and destabilising training at
learning rates that are fine sequentially.  ``occurrence_scale`` gives the
mean-combining alternative: scale each lane's delta by 1/count(id) so a
hot id takes one averaged step per batch — bounded regardless of skew.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def occurrence_counts(
    ids: Array, capacity: int, mask: Optional[Array] = None
) -> Array:
    """Per-lane occurrence count of each lane's id within the batch.

    ``ids``: any-shape int array; returns same-shape float32 counts
    (≥ 1 for valid lanes).  O(capacity) scratch — intended for id spaces
    that fit a dense counter (vocab/feature tables), not 2^30 hash spaces.
    """
    flat = ids.reshape(-1).astype(jnp.int32)
    flat = jnp.where(flat < 0, capacity, flat)  # OOB sentinel, drops
    ones = jnp.ones(flat.shape, jnp.float32)
    if mask is not None:
        ones = jnp.where(mask.reshape(-1), ones, 0.0)
    table = jnp.zeros((capacity,), jnp.float32).at[flat].add(ones, mode="drop")
    counts = jnp.take(table, jnp.clip(flat, 0, capacity - 1), axis=0)
    return jnp.maximum(counts, 1.0).reshape(ids.shape)


def occurrence_scale(
    ids: Array, capacity: int, mask: Optional[Array] = None
) -> Array:
    """1/count(id) per lane: turns duplicate-id delta *sums* into *means*."""
    return 1.0 / occurrence_counts(ids, capacity, mask)


# -- host-side coalescing (the cluster client's request combiner) -----------
# The wire-protocol analogue of the combination senders: before a
# microbatch's pulls/pushes go to the network, duplicate ids collapse to
# ONE request per id (a Zipf-hot item can appear hundreds of times per
# batch — sending it hundreds of times would pay the line protocol per
# lane).  These run on the HOST (numpy): the cluster client formats
# text frames from the result, so there is no device round trip to save.


def coalesce_ids(
    ids: np.ndarray, mask: Optional[np.ndarray] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """``(unique_ids, inverse)``: each valid lane's id appears once in
    ``unique_ids`` (sorted ascending); ``inverse`` maps every input
    lane to its unique slot so pulled values scatter back with
    ``values[inverse]``.  Masked-out lanes map to slot 0 — callers must
    treat those lanes as padding (the store contract already does)."""
    flat = np.asarray(ids).reshape(-1).astype(np.int64)
    if mask is not None:
        m = np.asarray(mask).reshape(-1).astype(bool)
        # padding lanes piggyback on the first valid id (or id 0 for an
        # all-padding batch) so unique_ids never carries a pad-only id
        fill = flat[m][0] if m.any() else np.int64(0)
        flat = np.where(m, flat, fill)
    unique, inverse = np.unique(flat, return_inverse=True)
    return unique.astype(np.int64), inverse.reshape(np.asarray(ids).shape)


def aggregate_deltas(
    ids: np.ndarray,
    deltas: np.ndarray,
    mask: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """``(unique_ids, summed)``: duplicate-id deltas SUMMED per id —
    exactly the store's duplicate semantics (intra-batch duplicates
    combine additively), applied before the bytes hit the wire.  Masked
    lanes contribute nothing.  ``deltas`` is ``(n, *value_shape)`` (or
    ``(n,)`` for scalar stores); the result rows align with
    ``unique_ids``."""
    ids_arr = np.asarray(ids)
    flat_ids = ids_arr.reshape(-1).astype(np.int64)
    d = np.asarray(deltas)
    flat_d = d.reshape((ids_arr.size,) + d.shape[ids_arr.ndim:])
    if mask is not None:
        m = np.asarray(mask).reshape(-1).astype(bool)
        flat_ids = flat_ids[m]
        flat_d = flat_d[m]
    unique, inverse = np.unique(flat_ids, return_inverse=True)
    out = np.zeros((unique.shape[0],) + flat_d.shape[1:], np.float64)
    np.add.at(out, inverse, flat_d.astype(np.float64))
    return unique.astype(np.int64), out.astype(flat_d.dtype)


def aggregate_delta_batches(batches) -> Tuple[np.ndarray, np.ndarray]:
    """:func:`aggregate_deltas` across SEVERAL workers' batches — the
    aggregation tree's combine step (compression/aggregator.py): each
    element of ``batches`` is ``(ids, deltas)`` or ``(ids, deltas,
    mask)``; the result is one ``(unique_ids, summed)`` pair equal to
    aggregating the concatenation (per-id sums are associative — the
    f64 accumulator below makes the combine order immaterial).  Empty
    or ``None`` entries are skipped, so a worker with nothing to push
    this round costs nothing."""
    flat_ids = []
    flat_deltas = []
    for entry in batches:
        if entry is None:
            continue
        ids, deltas = entry[0], entry[1]
        mask = entry[2] if len(entry) > 2 else None
        ids_arr = np.asarray(ids).reshape(-1).astype(np.int64)
        if ids_arr.size == 0:
            continue
        d = np.asarray(deltas)
        d = d.reshape((ids_arr.size,) + d.shape[np.asarray(ids).ndim:])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            ids_arr, d = ids_arr[m], d[m]
            if ids_arr.size == 0:
                continue
        flat_ids.append(ids_arr)
        flat_deltas.append(d)
    if not flat_ids:
        return np.empty(0, np.int64), np.empty(0, np.float32)
    all_ids = np.concatenate(flat_ids)
    all_deltas = np.concatenate(flat_deltas)
    return aggregate_deltas(all_ids, all_deltas)


__all__ = [
    "occurrence_counts",
    "occurrence_scale",
    "coalesce_ids",
    "aggregate_deltas",
    "aggregate_delta_batches",
]
