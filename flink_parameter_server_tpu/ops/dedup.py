"""Intra-batch duplicate-id handling — the "combination sender" layer.

Reference parity (SURVEY.md §2 #6, §7 step 4): the reference's batching
("combination") senders buffer pull/push messages and flush them combined
on count/timer triggers.  In the batched TPU model the *microbatch itself*
is the combination buffer; what remains of the concern is how duplicate
ids inside one microbatch combine.

By default deltas for the same id SUM (exact minibatch SGD — every
gradient was computed at the same pulled snapshot).  Under Zipf-hot id
distributions (word2vec, Criteo) a hot id can appear hundreds of times per
batch, making its effective step ~count × lr and destabilising training at
learning rates that are fine sequentially.  ``occurrence_scale`` gives the
mean-combining alternative: scale each lane's delta by 1/count(id) so a
hot id takes one averaged step per batch — bounded regardless of skew.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


def occurrence_counts(
    ids: Array, capacity: int, mask: Optional[Array] = None
) -> Array:
    """Per-lane occurrence count of each lane's id within the batch.

    ``ids``: any-shape int array; returns same-shape float32 counts
    (≥ 1 for valid lanes).  O(capacity) scratch — intended for id spaces
    that fit a dense counter (vocab/feature tables), not 2^30 hash spaces.
    """
    flat = ids.reshape(-1).astype(jnp.int32)
    flat = jnp.where(flat < 0, capacity, flat)  # OOB sentinel, drops
    ones = jnp.ones(flat.shape, jnp.float32)
    if mask is not None:
        ones = jnp.where(mask.reshape(-1), ones, 0.0)
    table = jnp.zeros((capacity,), jnp.float32).at[flat].add(ones, mode="drop")
    counts = jnp.take(table, jnp.clip(flat, 0, capacity - 1), axis=0)
    return jnp.maximum(counts, 1.0).reshape(ids.shape)


def occurrence_scale(
    ids: Array, capacity: int, mask: Optional[Array] = None
) -> Array:
    """1/count(id) per lane: turns duplicate-id delta *sums* into *means*."""
    return 1.0 / occurrence_counts(ids, capacity, mask)


__all__ = ["occurrence_counts", "occurrence_scale"]
