"""Request admission + microbatch coalescing for the serve path.

The jitted query kernels want fixed-shape microbatches for exactly the
reason the ingest side does (``data/streams.py`` — SURVEY.md §7
"Dynamic shapes"): one compiled program per shape, padding + masks for
ragged reality.  This batcher is the serve-side mirror of that
discipline:

  * concurrent ``submit()`` calls land in ONE bounded queue; when the
    queue is full the request is REJECTED (``QueueFull``), never
    blocked — serving latency must stay bounded under overload, and the
    caller (TCP front end) turns the rejection into a protocol error
    the client can back off on;
  * the dispatch thread coalesces whatever is queued into a microbatch:
    flush fires when ``max_batch`` requests accumulate OR the oldest
    queued request has waited ``max_delay_ms`` (deadline-based flush —
    single stragglers never wait for a full batch);
  * batch shapes are padded UP to a bucket (powers of two up to
    ``max_batch``) so the query kernels compile once per bucket, not
    once per occupancy.
"""
from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, List, Optional, Sequence, Tuple


class QueueFull(RuntimeError):
    """Admission queue at capacity: the request was rejected, not queued."""


class DeadlineExceeded(RuntimeError):
    """The request waited in the admission queue past its deadline:
    answering it now would hand the client a result it has already
    given up on, so it is failed instead of served — the queue drains
    at the cost of badput, not of growing latency for everyone
    (``err deadline`` on the serving wire)."""


def pow2_bucket(n: int, cap: int) -> int:
    """Smallest power of two >= n, capped at ``cap``."""
    b = 1
    while b < n and b < cap:
        b <<= 1
    return min(b, cap)


@dataclasses.dataclass
class PendingRequest:
    """One admitted request: opaque payload + the future its answer
    lands in + its admission timestamp (latency accounting)."""

    payload: Any
    future: Future
    t_submit: float


class RequestBatcher:
    """Bounded admission queue with deadline-flush coalescing.

    Producer side (any thread): :meth:`submit` — O(1), raises
    :class:`QueueFull` at capacity.  Consumer side (the serving dispatch
    thread): :meth:`next_batch` — blocks until a batch is due and
    returns up to ``max_batch`` admitted requests.
    """

    def __init__(
        self,
        *,
        max_batch: int = 64,
        max_delay_ms: float = 2.0,
        max_queue: int = 256,
        deadline_ms: Optional[float] = None,
        buckets: Optional[Sequence[int]] = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch={max_batch}: must be >= 1")
        if max_queue < 1:
            raise ValueError(f"max_queue={max_queue}: must be >= 1")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms={deadline_ms}: must be > 0")
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1e3
        self.max_queue = int(max_queue)
        # per-request queue-wait deadline (seconds); the dispatch loop
        # fails expired requests with DeadlineExceeded instead of
        # serving answers nobody is waiting for.  None = no deadline.
        self.deadline_s = (
            None if deadline_ms is None else float(deadline_ms) / 1e3
        )
        if buckets is None:
            buckets = []
            b = 1
            while b < self.max_batch:
                buckets.append(b)
                b <<= 1
            buckets.append(self.max_batch)
        self.buckets: Tuple[int, ...] = tuple(sorted(set(int(b) for b in buckets)))
        if self.buckets[-1] != self.max_batch:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} != max_batch "
                f"{self.max_batch}"
            )
        self._queue: "collections.deque[PendingRequest]" = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        self.submitted = 0
        self.rejected = 0

    # -- producer side -----------------------------------------------------
    def submit(self, payload: Any) -> Future:
        """Admit one request; returns the Future its answer resolves.

        Raises :class:`QueueFull` when ``max_queue`` requests are already
        waiting — overload sheds load instead of growing latency."""
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._queue) >= self.max_queue:
                self.rejected += 1
                raise QueueFull(
                    f"serving queue at capacity ({self.max_queue}); retry "
                    f"with backoff"
                )
            fut: Future = Future()
            self._queue.append(
                PendingRequest(payload, fut, time.monotonic())
            )
            self.submitted += 1
            self._cond.notify_all()
            return fut

    # -- consumer side -----------------------------------------------------
    def next_batch(
        self, timeout: Optional[float] = None
    ) -> Optional[List[PendingRequest]]:
        """Block until a batch is due (full, or the oldest request hit
        its deadline), then pop up to ``max_batch`` requests.  Returns
        ``None`` on ``timeout`` with nothing queued, or when closed and
        drained."""
        t_end = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                if t_end is not None:
                    remaining = t_end - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._cond.wait(remaining)
                else:
                    self._cond.wait(0.1)
            # one request is in: flush when full OR at its deadline
            flush_at = self._queue[0].t_submit + self.max_delay
            while len(self._queue) < self.max_batch and not self._closed:
                now = time.monotonic()
                if now >= flush_at:
                    break
                self._cond.wait(flush_at - now)
            n = min(len(self._queue), self.max_batch)
            batch = [self._queue.popleft() for _ in range(n)]
            self._cond.notify_all()
            return batch

    # -- introspection / lifecycle -----------------------------------------
    @property
    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket >= n (pad-to-bucket shape)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def close(self) -> None:
        """Stop admitting; wake consumers.  Queued requests that were
        never served get a ``RuntimeError`` set so waiters unblock."""
        with self._cond:
            self._closed = True
            while self._queue:
                p = self._queue.popleft()
                if not p.future.done():
                    p.future.set_exception(
                        RuntimeError("serving batcher closed")
                    )
            self._cond.notify_all()

    def reopen(self) -> None:
        """Resume admission after :meth:`close` — the supervised-restart
        path (``ServingService.start`` on a service that was stopped):
        a restarted trainer re-attaching its serving plane must not
        inherit a permanently-closed admission queue."""
        with self._cond:
            self._closed = False
            self._cond.notify_all()


__all__ = [
    "DeadlineExceeded",
    "QueueFull",
    "RequestBatcher",
    "PendingRequest",
    "pow2_bucket",
]
