"""Serving-side observability — the query-plane sibling of
``training/metrics.StepMetrics``.

Same machinery, same contract: a rolling-window tracker with a
``snapshot()`` dict and a JSON-lines ``emit(sink)``, so the driver's
``metrics_sink`` receives interleaved training and serving lines from
one stream.  Tracked: QPS, request latency percentiles (admission →
answer), batch-fill ratio (occupancy / padded bucket — how much of
each compiled program is real work), queue depth, rejection count, and
snapshot staleness (trainer steps the served table lags the live one).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..telemetry.registry import json_line


class ServingMetrics:
    """Rolling QPS/latency/fill tracker for the serve path.

    Thread-safe: the dispatch thread records batches while any thread
    snapshots.  ``queue_depth_fn`` / ``staleness_fn`` are live probes
    wired in by the :class:`~.server.ServingService` so emission reads
    the CURRENT queue/staleness, not a stale recorded value.

    With a :class:`~..telemetry.MetricsRegistry` attached
    (``registry=``, or :meth:`bind_registry` after construction), the
    admission counters (requests / batches / rejects), the
    admission→answer latency histogram, and live probe gauges (QPS,
    fill, queue depth, staleness) publish through the unified plane
    under ``component=serving``.
    """

    def __init__(self, window: int = 1024, registry=None):
        self.window = int(window)
        self._lock = threading.Lock()
        self._latencies: List[float] = []  # seconds, admission -> answer
        self._fills: List[float] = []  # per batch: n / bucket
        self._done_times: List[float] = []  # per request completion
        self.total_requests = 0
        self.total_batches = 0
        self.total_rejected = 0
        self.started_at = time.perf_counter()
        self.queue_depth_fn: Optional[Callable[[], int]] = None
        self.staleness_fn: Optional[Callable[[], Optional[int]]] = None
        self.registry = None
        self._c_requests = self._c_batches = None
        self._c_rejected = self._h_latency = None
        self._c_reject_reason: Dict[str, Any] = {}
        if registry is not None:
            self.bind_registry(registry)

    def bind_registry(self, registry) -> "ServingMetrics":
        """Publish through the unified plane.  Called by
        :class:`~.server.ServingService` AFTER the queue/staleness
        probes are wired, so the gauges read live values from day one."""
        self.registry = registry
        self._c_requests = registry.counter(
            "serving_requests_total", component="serving"
        )
        self._c_batches = registry.counter(
            "serving_batches_total", component="serving"
        )
        self._c_rejected = registry.counter(
            "serving_rejected_total", component="serving"
        )
        # per-cause admission rejects (the soak's badput attribution,
        # docs/loadgen.md): queue_full = hard capacity, deadline =
        # queue wait blew the request deadline, shed = deliberate
        # overload shedding below the hard line.  Pre-registered so
        # /metrics shows zeros from the first scrape.
        self._c_reject_reason = {
            r: registry.counter(
                "serving_rejected_total", component="serving", reason=r
            )
            for r in ("queue_full", "deadline", "shed")
        }
        self._h_latency = registry.histogram(
            "serving_latency_seconds", component="serving"
        )
        registry.gauge("serving_qps", component="serving", fn=self.qps)
        registry.gauge(
            "serving_batch_fill", component="serving", fn=self.batch_fill
        )
        registry.gauge(
            "serving_queue_depth", component="serving",
            fn=lambda: (
                None if self.queue_depth_fn is None
                else self.queue_depth_fn()
            ),
        )
        registry.gauge(
            "snapshot_staleness_steps", component="serving",
            fn=lambda: (
                None if self.staleness_fn is None else self.staleness_fn()
            ),
        )
        return self

    # -- recording ---------------------------------------------------------
    def record_batch(
        self, n: int, bucket: int, latencies_s: List[float]
    ) -> None:
        now = time.perf_counter()
        with self._lock:
            self.total_batches += 1
            self.total_requests += n
            self._fills.append(n / max(1, bucket))
            self._latencies.extend(latencies_s)
            self._done_times.extend([now] * n)
            for buf in (self._latencies, self._fills, self._done_times):
                if len(buf) > self.window:
                    del buf[: len(buf) - self.window]
        if self._c_requests is not None:
            self._c_requests.inc(n)
            self._c_batches.inc()
            for lat in latencies_s:
                self._h_latency.observe(lat)

    def record_reject(self, n: int = 1, reason: str = "queue_full") -> None:
        with self._lock:
            self.total_rejected += n
        if self._c_rejected is not None:
            self._c_rejected.inc(n)
            counter = self._c_reject_reason.get(reason)
            if counter is not None:
                counter.inc(n)

    # -- reporting ---------------------------------------------------------
    def qps(self) -> float:
        """Windowed queries/sec: completions in the window over the span
        from the first windowed completion to now (robust to bursts)."""
        with self._lock:
            if not self._done_times:
                return 0.0
            span = time.perf_counter() - self._done_times[0]
            n = len(self._done_times)
        return n / span if span > 0 else 0.0

    def latency_percentiles(self) -> Dict[str, float]:
        with self._lock:
            lat = list(self._latencies)
        if not lat:
            return {"p50": 0.0, "p90": 0.0, "p99": 0.0}
        d = np.array(lat)
        return {
            "p50": float(np.percentile(d, 50)),
            "p90": float(np.percentile(d, 90)),
            "p99": float(np.percentile(d, 99)),
        }

    def batch_fill(self) -> float:
        with self._lock:
            return float(np.mean(self._fills)) if self._fills else 0.0

    def snapshot(self) -> Dict[str, Any]:
        lat = self.latency_percentiles()
        out = {
            "serving_requests": self.total_requests,
            "serving_rejected": self.total_rejected,
            "serving_qps": round(self.qps(), 1),
            "serving_p50_ms": round(lat["p50"] * 1e3, 3),
            "serving_p90_ms": round(lat["p90"] * 1e3, 3),
            "serving_p99_ms": round(lat["p99"] * 1e3, 3),
            "batch_fill": round(self.batch_fill(), 3),
            "wall_s": round(time.perf_counter() - self.started_at, 3),
        }
        if self.queue_depth_fn is not None:
            out["queue_depth"] = int(self.queue_depth_fn())
        if self.staleness_fn is not None:
            s = self.staleness_fn()
            out["snapshot_staleness_steps"] = None if s is None else int(s)
        return out

    def emit(self, sink=None) -> str:
        """One single-line JSON sample (shared ``ts``/``run_id`` stamped
        by the unified plane; guaranteed to round-trip ``json.loads``)."""
        return json_line(
            self.snapshot(), sink,
            run_id=self.registry.run_id if self.registry else None,
        )


__all__ = ["ServingMetrics"]
