"""The serve front end: dispatch loop, in-process client, TCP server.

Symmetry with the ingest edge (``data/socket.py``): the training side
reads newline-delimited records from a TCP socket; the serving side
answers newline-delimited queries over one.  Same host-side discipline
— sockets and parsing stay on the host, the device only ever sees the
fixed-shape microbatches the :class:`~.batcher.RequestBatcher`
coalesces.

Line protocol (one request per line, one response line per request, in
order, per connection)::

    topk <user_id> <k>[ <ex1,ex2,...>]      # top-k items for user,
                                            # optionally excluding ids
    pull <id1,id2,...>                      # raw embedding rows

    ok v=<version> step=<train_step> stale=<staleness> <payload>
    err <reason>                            # bad-request | overloaded |
                                            # deadline | no-snapshot |
                                            # internal

``topk`` payload: ``<item_id>:<score>`` space-separated (k entries;
lanes with no real candidate are ``-1:-inf``).  ``pull`` payload: one
``;``-separated row per id, each row ``,``-separated floats.

Concurrency model: each connection is handled synchronously (a client
pipelining N connections gets N-way admission concurrency); batching
across connections happens in the shared :class:`RequestBatcher`.
Overload answers ``err overloaded`` immediately — reject, never block.
"""
from __future__ import annotations

import dataclasses
import socket
import threading
from concurrent.futures import Future
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..core.store import ShardedParamStore, StoreSpec
from ..utils.net import LineServer
from .batcher import (
    DeadlineExceeded,
    PendingRequest,
    QueueFull,
    RequestBatcher,
    pow2_bucket,
)
from .engine import LookupResult, NoSnapshotError, QueryEngine, TopKResult
from .metrics import ServingMetrics
from .snapshot import SnapshotManager

import time


@dataclasses.dataclass(frozen=True)
class _TopKQuery:
    user: int
    k: int
    exclude: Tuple[int, ...] = ()


@dataclasses.dataclass(frozen=True)
class _LookupQuery:
    ids: Tuple[int, ...]


class ServingService:
    """snapshots + engine + batcher + ONE dispatch thread.

    The dispatch thread drains the admission queue, pads each batch to
    a bucket shape, runs the jitted query kernels, and resolves the
    per-request futures.  Publishing happens on the TRAINING thread via
    :meth:`on_dispatch` (the driver's ``serve_with`` hook) — the service
    itself never touches live training buffers.
    """

    def __init__(
        self,
        engine: QueryEngine,
        batcher: Optional[RequestBatcher] = None,
        metrics: Optional[ServingMetrics] = None,
        registry=None,
        hotkeys=None,
        shedder=None,
    ):
        self.engine = engine
        self.snapshots = engine.snapshots
        # overload-plane admission (loadgen/overload.LoadShedder):
        # with a shedder attached, requests are shed in the submit
        # path once the queue passes the shedder's depth fraction —
        # BELOW the hard QueueFull line, so rejection is cheap and
        # early (counted reason="shed" vs the hard "queue_full")
        self.shedder = shedder
        # hot-key analytics (telemetry/hotkeys.py): with a sketch
        # attached, every served lookup's requested ids are observed —
        # the serving-side half of the Zipf-skew measurement (register
        # the sketch with the aggregator to fold it into /metrics)
        self.hotkeys = hotkeys
        self.batcher = batcher if batcher is not None else RequestBatcher()
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.metrics.queue_depth_fn = lambda: self.batcher.depth
        self.metrics.staleness_fn = self.snapshots.staleness
        # unified plane (telemetry/): admission counters, the latency
        # histogram, and live depth/fill/staleness probe gauges publish
        # under component=serving — bound AFTER the probes above so the
        # gauges are live from the first scrape.  Default: the
        # process-wide registry (one /metrics endpoint sees the whole
        # train-while-serve stack).
        from ..telemetry import get_registry

        if registry is not None:
            self.metrics.bind_registry(registry)
        elif self.metrics.registry is None:
            self.metrics.bind_registry(get_registry())
        self.dispatch_errors = 0  # batches failed wholesale (loop survived)
        self._health = None  # optional resilience/health.HealthMonitor
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def attach_health(self, monitor) -> "ServingService":
        """Beat ``serving_dispatch`` on ``monitor`` from the dispatch
        loop (resilience/health.py stall watchdog wiring)."""
        self._health = monitor
        return self

    @classmethod
    def for_spec(
        cls,
        spec: StoreSpec,
        *,
        publish_every: int = 1,
        user_vectors=None,
        max_batch: int = 64,
        max_delay_ms: float = 2.0,
        max_queue: int = 256,
        buckets: Optional[Sequence[int]] = None,
    ) -> "ServingService":
        """One-stop construction from a store spec (what
        ``StreamingDriver.serve_with`` calls)."""
        snaps = SnapshotManager(spec, publish_every=publish_every)
        engine = QueryEngine(snaps, user_vectors=user_vectors)
        batcher = RequestBatcher(
            max_batch=max_batch, max_delay_ms=max_delay_ms,
            max_queue=max_queue, buckets=buckets,
        )
        return cls(engine, batcher)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ServingService":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            # restart path: a previous stop() closed the admission
            # queue; a restarted trainer re-attaching serving (the
            # supervisor's resume, or an explicit stop/start cycle)
            # gets a live one again
            self.batcher.reopen()
            self._thread = threading.Thread(
                target=self._loop, name="serving-dispatch", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.batcher.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- training-side hooks (called on the trainer thread) ----------------
    def on_train_start(self, store: ShardedParamStore, step: int, state=None):
        """Publish the pre-training table (serving is live from step 0)
        and start the dispatch thread."""
        self.snapshots.publish(store.table, step, aux=state)
        self.start()

    def on_dispatch(self, table, state, step: int, *, force: bool = False):
        """Per-dispatch publish offer (the ``publish_every`` cadence
        decides); ``force`` for the close-time final publish."""
        if force:
            self.snapshots.publish(table, step, aux=state)
        else:
            self.snapshots.maybe_publish(table, step, aux=state)

    def wait_for_snapshot(
        self, timeout: Optional[float] = None, *, min_version: int = 1
    ) -> bool:
        """Block until a snapshot with version >= ``min_version`` is
        published (warm-up gate for clients; version 1 is the
        pre-training table, version 2 the first mid-training publish —
        the first one carrying worker state)."""
        if not self.snapshots.wait_for_snapshot(timeout):
            return False
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            snap = self.snapshots.latest()
            if snap is not None and snap.version >= min_version:
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.005)

    # -- admission ---------------------------------------------------------
    def _admit_shed(self) -> None:
        """The shed gate (loadgen/overload.py): deliberate rejection
        below the hard capacity line once the queue is deep enough —
        raised as :class:`QueueFull` so every existing caller's
        backoff path applies unchanged, counted as its own cause."""
        if self.shedder is not None and not self.shedder.admit(
            self.batcher.depth, self.batcher.max_queue
        ):
            self.metrics.record_reject(reason="shed")
            raise QueueFull(
                "serving admission shed under overload pressure; "
                "retry with backoff or degrade"
            )

    def submit_topk(
        self, user: int, k: int = 10, exclude: Sequence[int] = ()
    ) -> Future:
        self._admit_shed()
        try:
            return self.batcher.submit(
                _TopKQuery(int(user), int(k), tuple(int(e) for e in exclude))
            )
        except QueueFull:
            self.metrics.record_reject(reason="queue_full")
            raise

    def submit_lookup(self, ids: Sequence[int]) -> Future:
        self._admit_shed()
        try:
            return self.batcher.submit(
                _LookupQuery(tuple(int(i) for i in ids))
            )
        except QueueFull:
            self.metrics.record_reject(reason="queue_full")
            raise

    def client(self) -> "ServingClient":
        return ServingClient(self)

    # -- the dispatch loop -------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self.batcher.next_batch(timeout=0.1)
            if self._health is not None:
                self._health.beat("serving_dispatch")
            if not batch:
                continue
            try:
                self._serve_batch(batch)
            except BaseException as e:
                # One poisoned batch must not kill the dispatch thread —
                # with it dead, every later query hangs to its timeout
                # while the trainer keeps publishing to nobody.  Fail
                # the batch's futures, count it, keep serving.
                # fpsanalyze: allow[S001] the ONE dispatch thread is the sole writer; readers are monitoring-only
                self.dispatch_errors += 1
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(e)

    def _serve_batch(self, batch: List[PendingRequest]) -> None:
        dl = self.batcher.deadline_s
        if dl is not None:
            # fail requests whose queue wait already blew the deadline
            # — serving them would return answers nobody is waiting
            # for while fresher requests queue behind them
            now = time.monotonic()
            expired = [p for p in batch if now - p.t_submit > dl]
            if expired:
                batch = [p for p in batch if now - p.t_submit <= dl]
                self.metrics.record_reject(len(expired), reason="deadline")
                for p in expired:
                    if not p.future.done():
                        p.future.set_exception(DeadlineExceeded(
                            f"queued {now - p.t_submit:.3f}s > deadline "
                            f"{dl:.3f}s"
                        ))
        topks = [p for p in batch if isinstance(p.payload, _TopKQuery)]
        lookups = [p for p in batch if isinstance(p.payload, _LookupQuery)]
        others = [
            p for p in batch if not isinstance(p.payload, (_TopKQuery,
                                                           _LookupQuery))
        ]
        for p in others:
            if not p.future.done():
                p.future.set_exception(
                    TypeError(f"unknown request payload {type(p.payload)}")
                )
        if topks:
            self._serve_topks(topks)
        if lookups:
            self._serve_lookups(lookups)

    def _serve_topks(self, pending: List[PendingRequest]) -> None:
        n = len(pending)
        bucket = self.batcher.bucket_for(n)
        k_max = max(p.payload.k for p in pending)
        e_max = max(len(p.payload.exclude) for p in pending)
        users = np.zeros(bucket, np.int32)
        for i, p in enumerate(pending):
            users[i] = p.payload.user
        exclude = None
        if e_max:
            e_pad = pow2_bucket(e_max, 1 << 20)
            exclude = np.full((bucket, e_pad), -1, np.int32)
            for i, p in enumerate(pending):
                ex = p.payload.exclude
                exclude[i, : len(ex)] = ex
        try:
            res = self.engine.top_k(users, k_max, exclude=exclude)
        except Exception as e:  # NoSnapshot / bad shapes: per-request error
            for p in pending:
                if not p.future.done():
                    p.future.set_exception(e)
            return
        now = time.monotonic()
        lats = []
        for i, p in enumerate(pending):
            k = p.payload.k
            answer = TopKResult(
                scores=res.scores[i, :k],
                item_ids=res.item_ids[i, :k],
                version=res.version,
                train_step=res.train_step,
                staleness=res.staleness,
            )
            lats.append(now - p.t_submit)
            if not p.future.done():
                p.future.set_result(answer)
        self.metrics.record_batch(n, bucket, lats)

    def _serve_lookups(self, pending: List[PendingRequest]) -> None:
        n = len(pending)
        bucket = self.batcher.bucket_for(n)
        w_max = max(len(p.payload.ids) for p in pending)
        w_pad = pow2_bucket(max(1, w_max), 1 << 20)
        ids = np.zeros((bucket, w_pad), np.int32)
        for i, p in enumerate(pending):
            ids[i, : len(p.payload.ids)] = p.payload.ids
        if self.hotkeys is not None:
            self.hotkeys.observe(np.concatenate([
                np.asarray(p.payload.ids, np.int64) for p in pending
            ]))
        try:
            res = self.engine.lookup(ids)
        except Exception as e:
            for p in pending:
                if not p.future.done():
                    p.future.set_exception(e)
            return
        now = time.monotonic()
        lats = []
        for i, p in enumerate(pending):
            w = len(p.payload.ids)
            answer = LookupResult(
                values=res.values[i, :w],
                version=res.version,
                train_step=res.train_step,
                staleness=res.staleness,
            )
            lats.append(now - p.t_submit)
            if not p.future.done():
                p.future.set_result(answer)
        self.metrics.record_batch(n, bucket, lats)


class ServingClient:
    """In-process client — the test/benchmark surface.

    Each call admits one request and blocks on its future; use
    :meth:`top_k_many` to keep many requests in flight (that is what
    exercises the coalescing path)."""

    def __init__(self, service: ServingService):
        self._service = service

    def top_k(
        self, user: int, k: int = 10, exclude: Sequence[int] = (),
        timeout: float = 30.0,
    ) -> TopKResult:
        return self._service.submit_topk(user, k, exclude).result(timeout)

    def lookup(self, ids: Sequence[int], timeout: float = 30.0) -> LookupResult:
        return self._service.submit_lookup(ids).result(timeout)

    def top_k_many(
        self, users: Sequence[int], k: int = 10, timeout: float = 60.0
    ) -> List[TopKResult]:
        futs = [self._service.submit_topk(u, k) for u in users]
        return [f.result(timeout) for f in futs]


# -- the TCP line protocol ---------------------------------------------------


def format_response(res) -> str:
    head = f"ok v={res.version} step={res.train_step} stale={res.staleness}"
    if isinstance(res, TopKResult):
        body = " ".join(
            f"{int(i)}:{float(s):.6g}"
            for i, s in zip(res.item_ids, res.scores)
        )
        return f"{head} {body}"
    vals = np.asarray(res.values, np.float64)
    # one ';'-row per id: scalar stores give (W,), vector stores (W, d)
    vals = vals.reshape(-1, 1) if vals.ndim <= 1 else vals.reshape(
        vals.shape[0], -1
    )
    body = ";".join(",".join(f"{v:.6g}" for v in row) for row in vals)
    return f"{head} {body}"


def parse_response(line: str) -> dict:
    """Parse one response line into a dict (client/test helper)."""
    parts = line.strip().split()
    if not parts:
        raise ValueError("empty response")
    if parts[0] == "err":
        return {"ok": False, "error": " ".join(parts[1:])}
    if parts[0] != "ok":
        raise ValueError(f"malformed response {line!r}")
    meta = {}
    i = 1
    while i < len(parts) and "=" in parts[i]:
        key, _, val = parts[i].partition("=")
        meta[key] = int(val)
        i += 1
    out = {
        "ok": True,
        "version": meta.get("v"),
        "train_step": meta.get("step"),
        "staleness": meta.get("stale"),
    }
    rest = parts[i:]
    if rest and ":" in rest[0]:
        items, scores = [], []
        for tok in rest:
            iid, _, sc = tok.partition(":")
            items.append(int(iid))
            scores.append(float(sc))
        out["item_ids"] = items
        out["scores"] = scores
    elif rest:
        out["values"] = [
            [float(v) for v in row.split(",") if v]
            for row in " ".join(rest).split(";")
        ]
    return out


class ServingServer(LineServer):
    """Line-protocol TCP front end over a :class:`ServingService`.

    ``port=0`` binds an ephemeral port (read it back from ``.port``).
    The socket plumbing (the selectors event loop, per-connection read
    buffers + dispatchers, the line reassembly + overflow guard,
    shutdown) lives in :class:`~..utils.net.LineServer`; this class is
    the protocol — :meth:`respond` answers one request line with one
    response line.  The serving plane deliberately stays on the line
    protocol: its answers are id lists and scores, not row payloads,
    so binary framing buys nothing here — a cluster-style ``hello``
    handshake lands in the unknown-command branch (``err
    bad-request``), which is exactly the downgrade answer a
    negotiating client expects (docs/cluster.md "Binary framing").
    """

    def __init__(
        self,
        service: ServingService,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        request_timeout: float = 30.0,
        max_line_bytes: int = 1 << 20,
        profiler=None,
    ):
        super().__init__(
            host, port, name="serving", max_line_bytes=max_line_bytes
        )
        self.service = service
        self.request_timeout = float(request_timeout)
        # latency-budget phases (telemetry/profiler.py): request parse
        # + admission, dispatch wait, response serialize — verb-scoped
        # as serving_<cmd> so the serve path has its own budget next to
        # the cluster pull/push one
        from ..telemetry.profiler import resolve_profiler

        self.profiler = resolve_profiler(profiler)

    def start(self) -> "ServingServer":
        self.service.start()
        super().start()
        return self

    # -- the protocol ------------------------------------------------------
    def respond(self, line: str) -> str:
        verb = "serving_" + (
            line.split(None, 1)[0].lower() if line.strip() else "empty"
        )
        prof = self.profiler
        try:
            with prof.timer(verb, "server_parse"):
                fut = self._admit(line)
        except QueueFull:
            return "err overloaded"
        except ValueError as e:
            return f"err bad-request: {e}"
        try:
            with prof.timer(verb, "server_queue_wait"):
                # admission → batched dispatch → future resolution: the
                # serve path's queue-wait analogue
                res = fut.result(self.request_timeout)
        except NoSnapshotError:
            return "err no-snapshot"
        except DeadlineExceeded:
            # the request outlived its queue-wait deadline: a typed
            # overload outcome the client can count as badput
            return "err deadline"
        except Exception as e:
            return f"err internal: {type(e).__name__}: {e}"
        with prof.timer(verb, "response_serialize"):
            return format_response(res)

    def _admit(self, line: str) -> Future:
        parts = line.split()
        cmd = parts[0].lower()
        if cmd == "topk":
            if len(parts) not in (3, 4):
                raise ValueError("usage: topk <user> <k> [ex1,ex2,...]")
            user, k = int(parts[1]), int(parts[2])
            if k < 1:
                raise ValueError("k must be >= 1")
            exclude: Tuple[int, ...] = ()
            if len(parts) == 4:
                exclude = tuple(
                    int(t) for t in parts[3].split(",") if t.strip()
                )
            return self.service.submit_topk(user, k, exclude)
        if cmd == "pull":
            if len(parts) != 2:
                raise ValueError("usage: pull <id1,id2,...>")
            ids = tuple(int(t) for t in parts[1].split(",") if t.strip())
            if not ids:
                raise ValueError("pull needs at least one id")
            return self.service.submit_lookup(ids)
        raise ValueError(f"unknown command {cmd!r} (topk|pull)")


def tcp_request(host: str, port: int, line: str, timeout: float = 30.0) -> dict:
    """One-shot TCP query (test/benchmark helper): send one request
    line, read one response line, parse it."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.sendall(line.strip().encode("utf-8") + b"\n")
        buf = b""
        while b"\n" not in buf:
            chunk = s.recv(1 << 16)
            if not chunk:
                break
            buf += chunk
    return parse_response(buf.split(b"\n")[0].decode("utf-8", "replace"))


__all__ = [
    "ServingService",
    "ServingClient",
    "ServingServer",
    "format_response",
    "parse_response",
    "tcp_request",
]
