"""serving/ — the online inference subsystem.

Reference parity: the reference system's whole point is *online*
learning — the model is useful while it trains — yet its only read
path is the close()-time model dump.  This package is the missing
query plane: versioned table snapshots decouple readers from the
scatter-update step, an admission batcher coalesces concurrent
requests into the fixed-shape microbatches the jitted query kernels
need, and a line-protocol TCP server answers top-K recommendation
queries against the live :class:`~..core.store.ShardedParamStore`
while the :class:`~..training.driver.StreamingDriver` keeps training.

Module map::

  snapshot.py   TableSnapshot / SnapshotManager — donated-buffer
                copy-on-publish with a publish_every cadence and
                staleness metadata (steps behind the trainer)
  batcher.py    RequestBatcher — bounded admission queue, pad-to-bucket
                coalescing, deadline flush, reject-on-overload
  engine.py     QueryEngine — jitted snapshot-read kernels: embedding
                lookup, MF dot-product scoring, exact top-K with
                exclusion masks (reuses ops/topk.sharded_topk)
  server.py     ServingService (batcher + engine + dispatch thread),
                ServingClient (in-process), ServingServer (TCP line
                protocol, symmetric to data/socket.py's ingest edge)
  metrics.py    ServingMetrics — QPS, batch-fill ratio, queue depth,
                p50/p99 request latency, snapshot staleness
  follower.py   FollowerLookupService — serving lookups routed across
                replica chains (replication/): reads survive a dead
                primary and a mid-flight failover

Train-while-serve is one call::

    driver = StreamingDriver(logic, store)
    service = driver.serve_with(publish_every=4)
    client = service.client()
    ...                       # driver.run(batches) in one thread,
    client.top_k(user, k=10)  # queries answered concurrently
"""
from .batcher import QueueFull, RequestBatcher
from .engine import LookupResult, NoSnapshotError, QueryEngine, TopKResult
from .follower import ChainLookupResult, FollowerLookupService
from .metrics import ServingMetrics
from .server import ServingClient, ServingServer, ServingService
from .snapshot import SnapshotManager, TableSnapshot

__all__ = [
    "ChainLookupResult",
    "FollowerLookupService",
    "QueueFull",
    "RequestBatcher",
    "NoSnapshotError",
    "QueryEngine",
    "TopKResult",
    "LookupResult",
    "ServingMetrics",
    "ServingService",
    "ServingClient",
    "ServingServer",
    "SnapshotManager",
    "TableSnapshot",
]
