"""Follower lookups — the serving plane's read path over replica chains.

The in-process serving stack (snapshot.py/engine.py) reads versioned
snapshots inside the TRAINING process; this module is the other
serving topology: a lookup service that reads the live cluster table
**through the replica chains** (replication/, docs/elastic.md), so
serving traffic keeps flowing while a primary is dead and being failed
over — the "millions of users read from followers" story.

It is a thin façade over a read-routed
:class:`~..cluster.client.ClusterClient`: lookups load-balance across
each shard's chain, honor the follower staleness contract (a lagging
follower's ``err lagging`` falls back to the primary inside the
client), and survive a promotion as a membership refresh — latency,
never an error.  The chaos failover e2e test and
``benchmarks/failover_time.py`` drive their "zero serving errors
during failover" window through this service.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class ChainLookupResult:
    """One answered lookup batch + its routing provenance."""

    values: np.ndarray  # (B, *value_shape) float32
    epoch: Optional[int]  # membership epoch the routing used


class FollowerLookupService:
    """Serving lookups against a replica-chained cluster.

    Built from a ``membership`` view (the usual case — promotions and
    resizes are then just refreshes) or handed an existing read-routed
    client.  Timeouts default TIGHT: a serving read is latency-bound,
    and the chain gives it somewhere else to go.
    """

    def __init__(
        self,
        membership=None,
        value_shape: Sequence[int] = (),
        *,
        client=None,
        registry=None,
        timeout: float = 5.0,
        connect_timeout: float = 2.0,
        retry_timeout: float = 10.0,
    ):
        if client is None:
            if membership is None:
                raise ValueError(
                    "FollowerLookupService needs membership= (or a "
                    "pre-built read-routed client=)"
                )
            from ..cluster.client import ClusterClient

            client = ClusterClient(
                value_shape=value_shape,
                membership=membership,
                read_replicas=True,
                timeout=timeout,
                connect_timeout=connect_timeout,
                retry_timeout=retry_timeout,
                registry=registry if registry is not None else None,
                worker="serving",
            )
            self._owns_client = True
        else:
            self._owns_client = False
        self._client = client
        self.lookups_served = 0
        self.lookup_errors = 0
        if registry is not False:
            from ..telemetry.registry import get_registry

            reg = registry if registry is not None else get_registry()
            self._c_lookups = reg.counter(
                "replication_serving_lookups_total",
                component="replication",
            )
        else:
            self._c_lookups = None

    def lookup(self, ids) -> ChainLookupResult:
        """Pull the rows for ``ids`` through the chain-routed client;
        every retry/fallback/refresh happens inside — a raised error
        here means the whole chain (followers AND primary) was
        unreachable past the retry budget."""
        ids = np.asarray(ids, np.int64)
        try:
            values = self._client.pull_batch(ids)
        except Exception:
            self.lookup_errors += 1
            raise
        self.lookups_served += 1
        if self._c_lookups is not None:
            self._c_lookups.inc()
        return ChainLookupResult(
            values=values, epoch=self._client._epoch
        )

    def close(self) -> None:
        if self._owns_client:
            self._client.close()


__all__ = ["ChainLookupResult", "FollowerLookupService"]
