"""Jitted query kernels over a published snapshot.

Three read ops, all compiled once per (shape, k) and cached by jit:

  * ``lookup(ids)`` — embedding pull: the store's sharded gather
    (:func:`..core.store.pull`) against the snapshot table;
  * ``score(user_ids, item_ids)`` — MF dot-product scoring of explicit
    (user, item) pairs;
  * ``top_k(user_ids, k, exclude=...)`` — exact top-K recommendation
    reusing :func:`..ops.topk.sharded_topk` through
    :func:`..models.topk_recommender.query_topk` (per-shard MXU matmul
    + hierarchical ``top_k``, over-fetch + mask for excluded/seen
    items) — the reference's top-K worker, answered from a snapshot.

The engine reads the snapshot pointer ONCE per call, so every answer is
internally consistent (table + user vectors + version from the same
publish) and carries its staleness as metadata.  User vectors come from
the snapshot's ``aux`` (the driver publishes worker state — MF user
factors) or from a static array passed at construction.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import store as store_mod
from ..core.store import ShardedParamStore
from ..models.topk_recommender import query_topk
from .snapshot import SnapshotManager, TableSnapshot

Array = jax.Array


class NoSnapshotError(RuntimeError):
    """Query arrived before the first snapshot publish."""


@dataclasses.dataclass(frozen=True)
class TopKResult:
    """One batch of top-K answers + the snapshot provenance they came
    from.  ``item_ids`` lanes with no real candidate (catalogue smaller
    than k, or excluded) are -1 with ``-inf`` scores — the ops-level
    padding convention."""

    scores: np.ndarray  # (B, k) float
    item_ids: np.ndarray  # (B, k) int
    version: int
    train_step: int
    staleness: int


@dataclasses.dataclass(frozen=True)
class LookupResult:
    values: np.ndarray  # (B, *value_shape)
    version: int
    train_step: int
    staleness: int


class QueryEngine:
    """Snapshot-read kernels with jit-cached programs.

    One engine serves many concurrent callers: jax dispatch is
    thread-safe, snapshots are immutable, and the only mutable state
    here is the jit-function cache (guarded by the GIL — worst case a
    duplicate trace, never a wrong answer).
    """

    def __init__(
        self,
        snapshots: SnapshotManager,
        *,
        user_vectors: Optional[Array] = None,
    ):
        self.snapshots = snapshots
        self._static_user_vectors = user_vectors
        self._fns: Dict[Any, Any] = {}

    # -- snapshot plumbing -------------------------------------------------
    def _snap(self) -> TableSnapshot:
        snap = self.snapshots.latest()
        if snap is None:
            raise NoSnapshotError(
                "no snapshot published yet (is the trainer running / did "
                "serve_with publish the initial table?)"
            )
        return snap

    def _user_vectors(self, snap: TableSnapshot) -> Array:
        aux = snap.aux
        if aux is not None and hasattr(aux, "ndim") and aux.ndim == 2:
            return aux
        if self._static_user_vectors is not None:
            return self._static_user_vectors
        raise ValueError(
            "top-K needs user vectors: publish the worker state as the "
            "snapshot aux (StreamingDriver.serve_with does) or pass "
            "user_vectors= to the QueryEngine"
        )

    # -- compiled read ops -------------------------------------------------
    def _lookup_fn(self):
        key = "lookup"
        if key not in self._fns:
            spec = self.snapshots.spec

            def fn(table, ids):
                return store_mod.pull(spec, table, ids)

            self._fns[key] = jax.jit(fn)
        return self._fns[key]

    def _score_fn(self):
        key = "score"
        if key not in self._fns:
            spec = self.snapshots.spec

            def fn(table, user_vecs, user_ids, item_ids):
                q = jnp.take(user_vecs, user_ids.astype(jnp.int32), axis=0)
                v = store_mod.pull(spec, table, item_ids)
                return jnp.sum(q * v, axis=-1)

            self._fns[key] = jax.jit(fn)
        return self._fns[key]

    def _topk_fn(self, k: int, has_exclude: bool):
        key = ("topk", int(k), bool(has_exclude))
        if key not in self._fns:
            spec = self.snapshots.spec

            if has_exclude:

                def fn(table, user_vecs, user_ids, exclude):
                    return query_topk(
                        ShardedParamStore(spec, table),
                        user_vecs, user_ids, k, exclude=exclude,
                    )

            else:

                def fn(table, user_vecs, user_ids):
                    return query_topk(
                        ShardedParamStore(spec, table),
                        user_vecs, user_ids, k,
                    )

            self._fns[key] = jax.jit(fn)
        return self._fns[key]

    # -- public query surface ----------------------------------------------
    def lookup(self, ids) -> LookupResult:
        """Batched embedding pull against the latest snapshot."""
        snap = self._snap()
        ids = jnp.asarray(np.asarray(ids, dtype=np.int32))
        vals = self._lookup_fn()(snap.table, ids)
        return LookupResult(
            values=np.asarray(vals),
            version=snap.version,
            train_step=snap.train_step,
            staleness=self.snapshots.staleness_of(snap),
        )

    def score(self, user_ids, item_ids) -> LookupResult:
        """MF dot-product scores for aligned (user, item) id pairs."""
        snap = self._snap()
        uv = self._user_vectors(snap)
        scores = self._score_fn()(
            snap.table, uv,
            jnp.asarray(np.asarray(user_ids, np.int32)),
            jnp.asarray(np.asarray(item_ids, np.int32)),
        )
        return LookupResult(
            values=np.asarray(scores),
            version=snap.version,
            train_step=snap.train_step,
            staleness=self.snapshots.staleness_of(snap),
        )

    def top_k(
        self, user_ids, k: int, *, exclude=None
    ) -> TopKResult:
        """Exact top-K items for ``user_ids`` (B,), excluding the
        (B, E) ``exclude`` ids (pad unused lanes with -1)."""
        if k < 1:
            raise ValueError(f"k={k}: must be >= 1")
        snap = self._snap()
        uv = self._user_vectors(snap)
        uids = jnp.asarray(np.asarray(user_ids, np.int32))
        if exclude is not None:
            excl = jnp.asarray(np.asarray(exclude, np.int32))
            scores, ids = self._topk_fn(k, True)(snap.table, uv, uids, excl)
        else:
            scores, ids = self._topk_fn(k, False)(snap.table, uv, uids)
        return TopKResult(
            scores=np.asarray(scores),
            item_ids=np.asarray(ids),
            version=snap.version,
            train_step=snap.train_step,
            staleness=self.snapshots.staleness_of(snap),
        )


__all__ = ["QueryEngine", "TopKResult", "LookupResult", "NoSnapshotError"]
