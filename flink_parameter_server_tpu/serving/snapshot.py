"""Versioned table snapshots — the read path's isolation boundary.

The training loop donates its table buffer into every jitted step
(``transform_batched`` jits with ``donate_argnums``), so a reader
holding the live array would race the scatter-update — or worse, read a
deleted buffer.  The snapshot discipline (the straggler-study split:
serving must never block the update loop): at a configurable
``publish_every`` dispatch cadence the trainer *copies* the live table
(donated-buffer copy-on-publish — ``jnp.copy`` preserves sharding) and
swaps an immutable, versioned :class:`TableSnapshot` behind a lock.
Readers grab the latest snapshot pointer once per query and see a
bit-identical table until the next publish; staleness (trainer steps
behind) is carried as metadata on every answer instead of being hidden.

All publishes happen on the TRAINING thread (the driver's dispatch
callback), so the copy is sequenced before the next donation without
any cross-thread buffer juggling; readers only ever swap pointers.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..core.store import ShardedParamStore, StoreSpec

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class TableSnapshot:
    """An immutable published view of the parameter table.

    ``aux`` carries whatever the trainer published alongside the table —
    the driver publishes the worker state (e.g. MF user factors), which
    is what the query engine scores with.  ``train_step`` is the trainer
    step the snapshot was taken at; staleness at read time is computed
    against the manager's live step counter."""

    spec: StoreSpec
    table: Array
    aux: Any
    version: int
    train_step: int
    published_at: float

    def store(self) -> ShardedParamStore:
        """The snapshot as a read-only store (pull/top-K compose)."""
        return ShardedParamStore(self.spec, self.table)


class SnapshotManager:
    """Publish-side cadence + read-side pointer swap, thread-safe.

    ``publish_every`` is measured in trainer steps: ``maybe_publish``
    republishes only once the trainer has advanced that far past the
    last published snapshot (the first offer always publishes).  Every
    ``note_step``/``maybe_publish`` call also advances the live step
    counter that :meth:`staleness` measures against.
    """

    def __init__(self, spec: StoreSpec, *, publish_every: int = 1):
        if publish_every < 1:
            raise ValueError(f"publish_every={publish_every}: must be >= 1")
        self.spec = spec
        self.publish_every = int(publish_every)
        self._lock = threading.Lock()
        self._latest: Optional[TableSnapshot] = None
        self._current_step = 0
        self._published = threading.Event()

    # -- publish side (training thread) -----------------------------------
    def publish(self, table: Array, step: int, aux: Any = None) -> TableSnapshot:
        """Copy-on-publish: snapshot the live (donated-next-dispatch)
        buffers and swap the latest pointer.  Blocks until the copy is
        device-complete so the source buffer is free to be donated the
        moment this returns."""
        copied = jnp.copy(table)
        aux_copied = jax.tree.map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, aux
        )
        jax.block_until_ready(copied)
        if aux_copied is not None:
            jax.block_until_ready(aux_copied)
        with self._lock:
            version = (self._latest.version + 1) if self._latest else 1
            snap = TableSnapshot(
                spec=self.spec,
                table=copied,
                aux=aux_copied,
                version=version,
                train_step=int(step),
                published_at=time.time(),
            )
            self._latest = snap
            self._current_step = max(self._current_step, int(step))
        self._published.set()
        return snap

    def maybe_publish(
        self, table: Array, step: int, aux: Any = None
    ) -> Optional[TableSnapshot]:
        """Publish iff the cadence is due; always advances the live step
        counter (so staleness keeps ticking between publishes)."""
        self.note_step(step)
        with self._lock:
            due = (
                self._latest is None
                or int(step) - self._latest.train_step >= self.publish_every
            )
        if due:
            return self.publish(table, step, aux)
        return None

    def note_step(self, step: int) -> None:
        """Record trainer progress without publishing (staleness input)."""
        with self._lock:
            self._current_step = max(self._current_step, int(step))

    # -- read side (serving threads) ---------------------------------------
    def latest(self) -> Optional[TableSnapshot]:
        with self._lock:
            return self._latest

    @property
    def current_step(self) -> int:
        with self._lock:
            return self._current_step

    def staleness_of(self, snap: TableSnapshot) -> int:
        """Trainer steps the snapshot lags the live table (>= 0)."""
        return max(0, self.current_step - snap.train_step)

    def staleness(self) -> Optional[int]:
        snap = self.latest()
        return None if snap is None else self.staleness_of(snap)

    def wait_for_snapshot(self, timeout: Optional[float] = None) -> bool:
        """Block until the first publish (serving warm-up gate)."""
        return self._published.wait(timeout)


__all__ = ["TableSnapshot", "SnapshotManager"]
