"""The overload-control plane: shed, budget, break, brown out.

Under sustained offered load past capacity a queueing system has
exactly two futures: degrade gracefully for everyone, or collapse for
everyone — queues grow without bound, retries amplify the offered
load, and p99 explodes for *every* request, not just the excess.  This
module is the repo's graceful-degradation toolkit, four mechanisms
that compose (each is independently attachable; the soak A/B in
``benchmarks/soak_capacity.py`` measures what they buy together):

  * :class:`OverloadGuard` — **priority-aware load shedding at the
    shard edge**.  Attached to a :class:`~..cluster.shard.ShardServer`,
    it answers ``err overloaded`` to sheddable traffic (serving/lease
    reads first, then plain reads) once the live request depth passes
    a threshold, BEFORE the request pays parse/lock/apply costs.
    Training pushes are never shed by default — a shed push is a lost
    update; a shed read is one stale-or-retried lookup.
  * :class:`LoadShedder` — the same policy at the **serving admission
    edge** (:class:`~..serving.server.ServingService`): shed at a
    depth fraction below the hard ``QueueFull`` line so rejection is
    cheap and early, counted per reason.
  * :class:`RetryBudget` — a **client-side token bucket**: every retry
    spends a token, successes slowly refill.  An exhausted budget
    fails fast (:class:`RetryBudgetExhausted`) instead of feeding the
    retry storm — the complement of PR 10's decorrelated jitter: jitter
    spreads the herd in time, the budget caps its total size.
  * :class:`CircuitBreaker` / :class:`BreakerBoard` — a **per-shard
    error-rate breaker**: a window of failures opens the circuit
    (requests fail fast locally), a cooldown later one half-open probe
    tests the water, success closes it.  The board keys one breaker
    per shard inside :class:`~..cluster.client.ClusterClient`.
  * :class:`BrownoutController` — **degrade instead of erroring**:
    under shed pressure, widen the staleness bound of the PR-11
    hot-row caches (:meth:`~..hotcache.cache.HotRowCache.set_widen`)
    so hot reads are served stale-but-bounded at the edge rather than
    rejected; pressure gone, the bound snaps back.  The
    ``lease_staleness`` invariant checker still runs — at the widened
    bound, which stays a real bound.

Wire contract: the shard's shed answer is the typed ``err overloaded``
reply (docs/cluster.md), which
:class:`~..cluster.client.ClusterClient` raises as
:class:`OverloadedError` — a typed failure the caller can count as
badput and fail fast on, never a retry loop.  Frames may carry a
``pr=<n>`` option (0 = critical/write-class, 1 = normal read, 2 =
sheddable serving read); old servers parse and ignore it, the PR-6
trailing-token contract.

Instruments (``component=loadgen``; catalogued in docs/loadgen.md):
``overload_shed_total{edge,verb}``, ``retry_budget_tokens``,
``retry_budget_exhausted_total``, ``overload_breaker_open``,
``overload_breaker_transitions_total{state}``, ``brownout_active``,
``overload_brownouts_total``.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, Optional

# priority vocabulary for the pr= frame option
PRIORITY_CRITICAL = 0   # write-class: never shed by default
PRIORITY_NORMAL = 1     # plain reads
PRIORITY_SHEDDABLE = 2  # serving/lease reads: shed first

_WRITE_VERBS = frozenset({"push", "load", "repl", "flush"})


class OverloadedError(RuntimeError):
    """The request was SHED (``err overloaded`` on the wire, or a
    local admission/budget decision): typed so callers can fail fast
    and count badput instead of retrying into the storm."""


class RetryBudgetExhausted(OverloadedError):
    """The client's retry token bucket ran dry: this request fails
    fast instead of adding another replay to the herd."""


def _reg(registry):
    if registry is False:
        return None
    from ..telemetry.registry import get_registry

    return registry if registry is not None else get_registry()


class RetryBudget:
    """Token bucket over retries: ``try_spend()`` per retry,
    ``on_success()`` refills ``refill_per_success`` (capped).  Starts
    full.  Thread-safe — one budget may back every connection of one
    client (the per-connection granularity the soak uses is one budget
    per client, which IS per connection-owner here)."""

    def __init__(
        self,
        capacity: float = 10.0,
        *,
        refill_per_success: float = 0.25,
        registry=None,
        worker: Optional[str] = None,
    ):
        if capacity <= 0:
            raise ValueError(f"capacity={capacity}: must be > 0")
        if refill_per_success < 0:
            raise ValueError("refill_per_success must be >= 0")
        self.capacity = float(capacity)
        self.refill_per_success = float(refill_per_success)
        self._tokens = float(capacity)
        self._lock = threading.Lock()
        self.spent = 0
        self.exhausted = 0
        reg = _reg(registry)
        if reg is not None:
            labels = {"worker": worker} if worker is not None else {}
            reg.gauge(
                "retry_budget_tokens", component="loadgen",
                fn=self.tokens, **labels,
            )
            self._c_exhausted = reg.counter(
                "retry_budget_exhausted_total", component="loadgen",
                **labels,
            )
        else:
            self._c_exhausted = None

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def try_spend(self, n: float = 1.0) -> bool:
        """Spend ``n`` tokens for a retry; False (and counted) when the
        bucket cannot cover it — the caller must fail fast."""
        with self._lock:
            if self._tokens < n:
                self.exhausted += 1
                exhausted = True
            else:
                self._tokens -= n
                self.spent += 1
                exhausted = False
        if exhausted and self._c_exhausted is not None:
            self._c_exhausted.inc()
        return not exhausted

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(
                self.capacity, self._tokens + self.refill_per_success
            )


class CircuitBreaker:
    """Error-rate window → open → half-open probe → closed.

    ``fail()`` / ``ok()`` feed a trailing ``window_s`` event window;
    when it holds ≥ ``min_failures`` failures AND the failure fraction
    ≥ ``failure_rate``, the breaker OPENS for ``cooldown_s`` (every
    ``allow()`` answers False — callers fail fast without touching the
    wire).  After the cooldown one probe is allowed through
    (half-open); its ``ok()`` closes the breaker, its ``fail()``
    reopens it for another cooldown.
    """

    def __init__(
        self,
        *,
        window_s: float = 1.0,
        min_failures: int = 5,
        failure_rate: float = 0.5,
        cooldown_s: float = 0.25,
        clock=time.monotonic,
    ):
        if window_s <= 0 or cooldown_s <= 0:
            raise ValueError("window_s and cooldown_s must be > 0")
        if min_failures < 1:
            raise ValueError("min_failures must be >= 1")
        if not 0.0 < failure_rate <= 1.0:
            raise ValueError("failure_rate in (0, 1]")
        self.window_s = float(window_s)
        self.min_failures = int(min_failures)
        self.failure_rate = float(failure_rate)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._events = []  # (t, ok) inside the window
        self.state = "closed"  # closed | open | half_open
        self._opened_at = 0.0
        self._probe_inflight = False
        self.transitions: Dict[str, int] = {
            "open": 0, "half_open": 0, "closed": 0,
        }

    def _trim(self, now: float) -> None:
        cutoff = now - self.window_s
        self._events = [e for e in self._events if e[0] >= cutoff]

    def _to(self, state: str) -> None:
        self.state = state
        self.transitions[state] += 1

    def allow(self) -> bool:
        """May a request go out now?  Closed: yes.  Open: no, until
        the cooldown elapses — then one half-open probe slot."""
        now = self._clock()
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if now - self._opened_at < self.cooldown_s:
                    return False
                self._to("half_open")
                self._probe_inflight = True
                return True
            # half_open: one probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def ok(self) -> None:
        now = self._clock()
        with self._lock:
            self._trim(now)
            self._events.append((now, True))
            if self.state in ("half_open", "open"):
                self._probe_inflight = False
                self._events = []
                self._to("closed")

    def fail(self) -> None:
        now = self._clock()
        with self._lock:
            self._trim(now)
            self._events.append((now, False))
            if self.state == "half_open":
                self._probe_inflight = False
                self._opened_at = now
                self._to("open")
                return
            if self.state == "open":
                return
            fails = sum(1 for _t, okay in self._events if not okay)
            total = len(self._events)
            if (
                fails >= self.min_failures
                and fails / total >= self.failure_rate
            ):
                self._opened_at = now
                self._to("open")


class BreakerBoard:
    """One :class:`CircuitBreaker` per shard, created lazily, plus the
    registry surface (open-breaker gauge, transition counters) — what
    :class:`~..cluster.client.ClusterClient` consults per request."""

    def __init__(
        self,
        *,
        window_s: float = 1.0,
        min_failures: int = 5,
        failure_rate: float = 0.5,
        cooldown_s: float = 0.25,
        registry=None,
        worker: Optional[str] = None,
        clock=time.monotonic,
    ):
        self._kwargs = dict(
            window_s=window_s, min_failures=min_failures,
            failure_rate=failure_rate, cooldown_s=cooldown_s,
            clock=clock,
        )
        self._lock = threading.Lock()
        self._breakers: Dict[int, CircuitBreaker] = {}
        reg = _reg(registry)
        if reg is not None:
            labels = {"worker": worker} if worker is not None else {}
            reg.gauge(
                "overload_breaker_open", component="loadgen",
                fn=self.open_count, **labels,
            )
            self._c_trans = {
                s: reg.counter(
                    "overload_breaker_transitions_total",
                    component="loadgen", state=s, **labels,
                )
                for s in ("open", "half_open", "closed")
            }
        else:
            self._c_trans = None
        self._last_trans: Dict[int, Dict[str, int]] = {}

    def _get(self, shard: int) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(shard)
            if br is None:
                br = CircuitBreaker(**self._kwargs)
                self._breakers[shard] = br
                self._last_trans[shard] = {
                    "open": 0, "half_open": 0, "closed": 0,
                }
            return br

    def _publish(self, shard: int) -> None:
        if self._c_trans is None:
            return
        br = self._breakers[shard]
        last = self._last_trans[shard]
        for s, c in br.transitions.items():
            if c > last[s]:
                self._c_trans[s].inc(c - last[s])
                last[s] = c

    def allow(self, shard: int) -> bool:
        ok = self._get(shard).allow()
        self._publish(shard)
        return ok

    def ok(self, shard: int) -> None:
        self._get(shard).ok()
        self._publish(shard)

    def fail(self, shard: int) -> None:
        self._get(shard).fail()
        self._publish(shard)

    def state(self, shard: int) -> str:
        return self._get(shard).state

    def open_count(self) -> int:
        with self._lock:
            return sum(
                1 for b in self._breakers.values() if b.state == "open"
            )


class OverloadGuard:
    """Shard-edge admission: shed by (verb class, frame priority) at
    live-depth thresholds.  ``admit`` runs BEFORE the request is
    parsed — shedding must be the cheapest thing the server does.
    Over the line protocol that means before the id/payload split;
    over the binary framing (utils/frames.py) it is cheaper still:
    the verb id and priority are single header BYTES, so a shed
    request costs one 24-byte header peek — no TLV, id, or payload
    work at all (``ShardServer.respond_frame``).

    Effective threshold per request: write-class verbs (push / load /
    repl / flush) and ``pr=0`` frames use ``write_depth`` (None =
    never shed — a shed write is a lost update); ``pr=2`` (sheddable,
    the serving tier's tag) and ``lease`` frames use
    ``sheddable_depth``; everything else (plain reads) uses
    ``read_depth``.  A request is shed when the CURRENT depth
    (including itself) exceeds its threshold.
    """

    def __init__(
        self,
        *,
        sheddable_depth: int = 8,
        read_depth: int = 32,
        write_depth: Optional[int] = None,
        registry=None,
        shard: Optional[int] = None,
    ):
        if sheddable_depth < 1 or read_depth < 1:
            raise ValueError("depth thresholds must be >= 1")
        self.sheddable_depth = int(sheddable_depth)
        self.read_depth = int(read_depth)
        self.write_depth = (
            None if write_depth is None else int(write_depth)
        )
        self.sheds = 0
        self._lock = threading.Lock()
        reg = _reg(registry)
        if reg is not None:
            labels = {"shard": str(shard)} if shard is not None else {}
            self._counters = {
                verb: reg.counter(
                    "overload_shed_total", component="loadgen",
                    edge="shard", verb=verb, **labels,
                )
                for verb in ("pull", "lease", "push", "other")
            }
        else:
            self._counters = None

    def _threshold(self, verb: str, priority: Optional[int]):
        if verb in _WRITE_VERBS or priority == PRIORITY_CRITICAL:
            return self.write_depth
        if verb == "lease" or (
            priority is not None and priority >= PRIORITY_SHEDDABLE
        ):
            return self.sheddable_depth
        return self.read_depth

    def admit(
        self, verb: str, priority: Optional[int], depth: int
    ) -> bool:
        thr = self._threshold(verb, priority)
        if thr is None or depth <= thr:
            return True
        with self._lock:
            self.sheds += 1
        if self._counters is not None:
            key = verb if verb in ("pull", "lease", "push") else "other"
            self._counters[key].inc()
        return False


class LoadShedder:
    """Serving-admission shedding, below the hard ``QueueFull`` line:
    shed sheddable requests once the queue passes ``shed_at`` of
    capacity (normal-priority at ``normal_at``), so rejection happens
    in the submit path — microseconds — instead of after a queue
    wait."""

    def __init__(
        self,
        *,
        shed_at: float = 0.5,
        normal_at: float = 0.85,
        registry=None,
    ):
        if not 0.0 < shed_at <= normal_at <= 1.0:
            raise ValueError(
                f"need 0 < shed_at ({shed_at}) <= normal_at "
                f"({normal_at}) <= 1"
            )
        self.shed_at = float(shed_at)
        self.normal_at = float(normal_at)
        self.sheds = 0
        self._lock = threading.Lock()
        reg = _reg(registry)
        self._c_shed = (
            reg.counter(
                "overload_shed_total", component="loadgen",
                edge="serving", verb="submit",
            )
            if reg is not None else None
        )

    def admit(
        self, depth: int, max_queue: int,
        priority: int = PRIORITY_SHEDDABLE,
    ) -> bool:
        frac = depth / max(1, max_queue)
        threshold = (
            self.shed_at if priority >= PRIORITY_SHEDDABLE
            else self.normal_at
        )
        if priority <= PRIORITY_CRITICAL or frac < threshold:
            return True
        with self._lock:
            self.sheds += 1
        if self._c_shed is not None:
            self._c_shed.inc()
        return False


class BrownoutController:
    """Degrade-not-error: shed pressure widens the hot-row caches'
    staleness bound by ``widen_factor`` (served entries stay inside
    ``bound × widen_factor`` ticks — a REAL bound the lease_staleness
    checker enforces); a quiet period restores it.

    Pressure model: ``note_shed()`` events inside a trailing
    ``window_s`` window; ≥ ``enter_sheds`` of them enters brownout.
    Exit when ``exit_quiet_s`` passes without a shed (evaluated on the
    ``note_ok`` path — a dead-quiet system with no traffic stays
    browned out until traffic proves recovery, which is the
    conservative direction).
    """

    def __init__(
        self,
        caches: Iterable = (),
        *,
        widen_factor: float = 4.0,
        enter_sheds: int = 8,
        window_s: float = 1.0,
        exit_quiet_s: float = 1.0,
        registry=None,
        clock=time.monotonic,
    ):
        if widen_factor < 1.0:
            raise ValueError(
                f"widen_factor={widen_factor}: must be >= 1"
            )
        if enter_sheds < 1:
            raise ValueError("enter_sheds must be >= 1")
        self.caches = list(caches)
        self.widen_factor = float(widen_factor)
        self.enter_sheds = int(enter_sheds)
        self.window_s = float(window_s)
        self.exit_quiet_s = float(exit_quiet_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._shed_times = []
        self._last_shed = 0.0
        self.active = False
        self.entries = 0  # brownout episodes entered
        reg = _reg(registry)
        if reg is not None:
            reg.gauge(
                "brownout_active", component="loadgen",
                fn=lambda: 1.0 if self.active else 0.0,
            )
            self._c_entries = reg.counter(
                "overload_brownouts_total", component="loadgen"
            )
        else:
            self._c_entries = None

    def attach(self, cache) -> None:
        with self._lock:
            self.caches.append(cache)
            if self.active:
                cache.set_widen(self.widen_factor)

    def _enter(self) -> None:
        # caller holds the lock
        self.active = True
        self.entries += 1
        for c in self.caches:
            c.set_widen(self.widen_factor)

    def _exit(self) -> None:
        self.active = False
        for c in self.caches:
            c.set_widen(1.0)

    def note_shed(self) -> None:
        now = self._clock()
        entered = False
        with self._lock:
            cutoff = now - self.window_s
            self._shed_times = [
                t for t in self._shed_times if t >= cutoff
            ]
            self._shed_times.append(now)
            self._last_shed = now
            if not self.active and len(
                self._shed_times
            ) >= self.enter_sheds:
                self._enter()
                entered = True
        if entered and self._c_entries is not None:
            self._c_entries.inc()

    def note_ok(self) -> None:
        now = self._clock()
        with self._lock:
            if self.active and now - self._last_shed >= self.exit_quiet_s:
                self._exit()


__all__ = [
    "BreakerBoard",
    "BrownoutController",
    "CircuitBreaker",
    "LoadShedder",
    "OverloadGuard",
    "OverloadedError",
    "PRIORITY_CRITICAL",
    "PRIORITY_NORMAL",
    "PRIORITY_SHEDDABLE",
    "RetryBudget",
    "RetryBudgetExhausted",
]
