"""Open-loop arrival schedules — the honest half of a load test.

A closed-loop load generator couples arrivals to completions: when the
system stalls, the generator politely stops offering load, and the
recorded latencies hide exactly the stall being measured (coordinated
omission — the same lie PR 7's honest residuals exist to prevent in
the latency budget).  Everything here is OPEN loop: arrival times are
drawn up front from a seeded stochastic process, independent of any
response, and the soak harness (:mod:`.soak`) measures every request's
latency against its *scheduled arrival*, so a backlog shows up as tail
latency instead of silently thinning the offered load.

Three composable pieces:

  * **rate curves** — plain ``rate(t) -> requests/sec`` callables:
    :func:`constant_rate`, :func:`diurnal_rate` (a raised-cosine
    day/night swing — the morning-ramp/overnight-idle shape the
    elastic controller must track), :func:`ramp_rate` (linear sweep,
    the capacity-probe shape) and :func:`flash_crowds` (multiplicative
    spikes layered on any base curve — the celebrity-event shape);
  * **the process** — :func:`poisson_arrivals` draws a non-homogeneous
    Poisson arrival vector from any rate curve by thinning (Lewis &
    Shedler): memoryless inter-arrivals, seeded, so the same seed
    yields the same schedule on any host;
  * **the split** — :func:`split_slots` deals a schedule round-robin
    to N generator threads while every request keeps its ABSOLUTE
    arrival time (the per-thread view of one global schedule, not N
    independent schedules).

Everything is stdlib + numpy; nothing here touches the cluster.
"""
from __future__ import annotations

import math
from typing import Callable, List, Sequence, Tuple

import numpy as np

RateFn = Callable[[float], float]


def constant_rate(rps: float) -> Tuple[RateFn, float]:
    """``(rate_fn, rate_max)`` for a flat offered load."""
    if rps <= 0:
        raise ValueError(f"rps={rps}: must be > 0")
    r = float(rps)
    return (lambda t: r), r


def diurnal_rate(
    low_rps: float, high_rps: float, period_s: float, *,
    phase: float = 0.0,
) -> Tuple[RateFn, float]:
    """Raised-cosine day/night curve: ``low`` at t=0 (+phase), peaking
    at ``high`` half a period later — the morning ramp the autoscaler
    is scored against, compressed to whatever period the soak runs."""
    if not 0 < low_rps <= high_rps:
        raise ValueError(
            f"need 0 < low ({low_rps}) <= high ({high_rps})"
        )
    if period_s <= 0:
        raise ValueError(f"period_s={period_s}: must be > 0")
    lo, hi, p = float(low_rps), float(high_rps), float(period_s)

    def rate(t: float) -> float:
        return lo + (hi - lo) * 0.5 * (
            1.0 - math.cos(2.0 * math.pi * (t / p + phase))
        )

    return rate, hi


def ramp_rate(
    start_rps: float, end_rps: float, duration_s: float
) -> Tuple[RateFn, float]:
    """Linear sweep from ``start`` to ``end`` over ``duration`` (held
    at ``end`` past it) — the capacity-probe shape."""
    if start_rps <= 0 or end_rps <= 0:
        raise ValueError("rates must be > 0")
    s, e, d = float(start_rps), float(end_rps), float(duration_s)

    def rate(t: float) -> float:
        if t >= d:
            return e
        return s + (e - s) * (t / d)

    return rate, max(s, e)


def flash_crowds(
    base: RateFn, base_max: float,
    spikes: Sequence[Tuple[float, float, float]],
) -> Tuple[RateFn, float]:
    """Layer ``(at_s, duration_s, multiplier)`` spikes onto any base
    curve — the flash-crowd shape (a linked celebrity, a market open).
    Overlapping spikes multiply."""
    sp = [(float(a), float(d), float(m)) for a, d, m in spikes]
    for a, d, m in sp:
        if d <= 0 or m <= 0:
            raise ValueError(f"spike ({a}, {d}, {m}): need d > 0, m > 0")

    def rate(t: float) -> float:
        r = base(t)
        for a, d, m in sp:
            if a <= t < a + d:
                r *= m
        return r

    worst = base_max
    for _a, _d, m in sp:
        worst = max(worst, base_max * m)
    return rate, worst


def poisson_arrivals(
    rate_fn: RateFn, rate_max: float, duration_s: float, *, seed: int = 0
) -> np.ndarray:
    """Non-homogeneous Poisson arrival offsets in ``[0, duration_s)``,
    by thinning: draw a homogeneous process at ``rate_max``, keep each
    point with probability ``rate(t) / rate_max``.  Seeded and
    host-independent — the schedule IS the experiment's identity."""
    if rate_max <= 0 or duration_s <= 0:
        raise ValueError("rate_max and duration_s must be > 0")
    rng = np.random.default_rng(seed)
    out: List[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / rate_max))
        if t >= duration_s:
            break
        r = rate_fn(t)
        if r > rate_max * (1.0 + 1e-9):
            raise ValueError(
                f"rate_fn({t:.3f}) = {r} exceeds rate_max={rate_max}; "
                f"thinning needs a true upper bound"
            )
        if rng.random() < r / rate_max:
            out.append(t)
    return np.asarray(out, np.float64)


def split_slots(arrivals: np.ndarray, n: int) -> List[np.ndarray]:
    """Deal one global arrival schedule to ``n`` generator threads
    round-robin; every request keeps its absolute arrival offset."""
    if n < 1:
        raise ValueError(f"n={n}: must be >= 1")
    return [np.asarray(arrivals[t::n], np.float64) for t in range(n)]


__all__ = [
    "RateFn",
    "constant_rate",
    "diurnal_rate",
    "flash_crowds",
    "poisson_arrivals",
    "ramp_rate",
    "split_slots",
]
