"""loadgen — open-loop million-user traffic + the overload-control plane.

The harness ROADMAP item 4 asked for (docs/loadgen.md):

  * :mod:`.arrivals` — seeded open-loop arrival schedules (Poisson
    thinning over diurnal / ramp / flash-crowd rate curves), so
    coordinated omission cannot hide stalls;
  * :mod:`.population` — a Zipf user/item population with regional
    train/serve traffic mixes;
  * :mod:`.overload` — the graceful-degradation toolkit: shard- and
    serving-edge load shedding (``err overloaded``), client retry
    budgets, per-shard circuit breakers, and brownout (widened
    hot-cache staleness instead of errors);
  * :mod:`.soak` — the :class:`~.soak.SoakRunner` driving the full
    replicated+elastic stack with the PR-10 nemesis mesh underneath,
    plus the goodput ledger and the autoscaler-quality score.

``soak`` pulls in the whole cluster stack; it is imported lazily so
``from ..loadgen.overload import OverloadedError`` stays cheap inside
``cluster/client.py`` (no import cycle through the package).
"""
from .arrivals import (
    constant_rate,
    diurnal_rate,
    flash_crowds,
    poisson_arrivals,
    ramp_rate,
    split_slots,
)
from .overload import (
    BreakerBoard,
    BrownoutController,
    CircuitBreaker,
    LoadShedder,
    OverloadGuard,
    OverloadedError,
    RetryBudget,
    RetryBudgetExhausted,
)
from .population import Region, Request, UserPopulation

_LAZY = {
    "GoodputLedger", "SoakConfig", "SoakReport", "SoakRunner",
    "autoscaler_score", "run_soak",
}


def __getattr__(name):
    if name in _LAZY:
        from . import soak

        return getattr(soak, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "BreakerBoard",
    "BrownoutController",
    "CircuitBreaker",
    "GoodputLedger",
    "LoadShedder",
    "OverloadGuard",
    "OverloadedError",
    "Region",
    "Request",
    "RetryBudget",
    "RetryBudgetExhausted",
    "SoakConfig",
    "SoakReport",
    "SoakRunner",
    "UserPopulation",
    "autoscaler_score",
    "constant_rate",
    "diurnal_rate",
    "flash_crowds",
    "poisson_arrivals",
    "ramp_rate",
    "run_soak",
    "split_slots",
]
