"""A synthetic user population — who arrives, from where, wanting what.

The "millions of users" claim is a statement about *traffic shape*,
not just volume: real request streams are Zipf-skewed (a celebrity
head on a uniform tail — the distribution the hotcache tier and the
PR-6 sketches are built for), and they mix read and write traffic
unevenly by region (a serving-heavy consumer region next to a
training-heavy ingest region).  This module samples that shape
deterministically:

  * **key popularity** — item ranks follow a truncated Zipf(``s``)
    law; rank → id through a seeded permutation so the hot head is not
    trivially ``[0..k)``;
  * **regions** — each :class:`Region` carries a traffic ``weight``
    and a ``serve_frac`` (the read share of its traffic); a sampled
    request is a serving lookup or a training push according to its
    region's mix;
  * **users** — Zipf-ranked too (heavy users exist), routed stably so
    one user's pushes land on one logical writer.

Requests come out of :meth:`UserPopulation.sample` one at a time from
a caller-owned ``numpy`` Generator — the soak runner hands each
generator thread its own seeded stream, so the composed experiment is
reproducible from ``(population seed, per-thread seeds)`` alone.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Region:
    """One traffic region: relative ``weight`` of all arrivals, and
    the fraction of its traffic that is serving reads (the rest is
    training pushes)."""

    name: str
    weight: float = 1.0
    serve_frac: float = 0.9

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"region {self.name}: weight must be > 0")
        if not 0.0 <= self.serve_frac <= 1.0:
            raise ValueError(
                f"region {self.name}: serve_frac in [0, 1]"
            )


DEFAULT_REGIONS: Tuple[Region, ...] = (
    Region("us", weight=0.5, serve_frac=0.95),
    Region("eu", weight=0.3, serve_frac=0.9),
    Region("ingest", weight=0.2, serve_frac=0.4),
)


@dataclasses.dataclass(frozen=True)
class Request:
    """One sampled request: a serving lookup (``kind="serve"``) over
    ``ids`` or a training push (``kind="train"``) of deltas to
    ``ids``."""

    kind: str            # "serve" | "train"
    region: str
    user: int
    ids: np.ndarray      # item ids touched (int64)


def _zipf_pmf(n: int, s: float) -> np.ndarray:
    ranks = np.arange(1, n + 1, dtype=np.float64)
    w = ranks ** (-float(s))
    return w / w.sum()


class UserPopulation:
    """Seeded Zipf population with regional train/serve mixes."""

    def __init__(
        self,
        num_users: int,
        num_items: int,
        *,
        zipf_s: float = 1.1,
        batch_ids: int = 4,
        regions: Optional[Sequence[Region]] = None,
        seed: int = 0,
    ):
        if num_users < 1 or num_items < 1:
            raise ValueError("need num_users >= 1 and num_items >= 1")
        if batch_ids < 1:
            raise ValueError(f"batch_ids={batch_ids}: must be >= 1")
        if zipf_s <= 0:
            raise ValueError(f"zipf_s={zipf_s}: must be > 0")
        self.num_users = int(num_users)
        self.num_items = int(num_items)
        self.batch_ids = int(batch_ids)
        self.zipf_s = float(zipf_s)
        self.regions: Tuple[Region, ...] = tuple(
            regions if regions is not None else DEFAULT_REGIONS
        )
        w = np.asarray([r.weight for r in self.regions], np.float64)
        self._region_p = w / w.sum()
        rng = np.random.default_rng(seed)
        # rank -> id permutations: the hot head is a seeded secret, not
        # the first k ids (a cache keyed on "small ids are hot" would
        # pass a dishonest version of this test)
        self._item_by_rank = rng.permutation(self.num_items).astype(
            np.int64
        )
        self._user_by_rank = rng.permutation(self.num_users).astype(
            np.int64
        )
        self._item_pmf = _zipf_pmf(self.num_items, self.zipf_s)
        self._user_pmf = _zipf_pmf(self.num_users, self.zipf_s)

    # -- introspection -------------------------------------------------------
    def hot_items(self, top_n: int) -> np.ndarray:
        """The ``top_n`` most popular item ids (by construction) — what
        a static lease policy or a cache-size budget keys on."""
        return self._item_by_rank[: max(0, int(top_n))].copy()

    def head_share(self, top_n: int) -> float:
        """Probability mass carried by the ``top_n`` hottest items —
        the skew figure a storm headline quotes ("1% of keys take
        90%")."""
        return float(self._item_pmf[: max(0, int(top_n))].sum())

    # -- sampling ------------------------------------------------------------
    def sample(self, rng: np.random.Generator) -> Request:
        """One request from the caller's stream: region → kind by the
        region's mix → Zipf user + Zipf item batch."""
        ridx = int(rng.choice(len(self.regions), p=self._region_p))
        region = self.regions[ridx]
        kind = "serve" if rng.random() < region.serve_frac else "train"
        user = int(
            self._user_by_rank[
                int(rng.choice(self.num_users, p=self._user_pmf))
            ]
        )
        ranks = rng.choice(
            self.num_items, size=self.batch_ids, p=self._item_pmf
        )
        return Request(
            kind=kind, region=region.name, user=user,
            ids=self._item_by_rank[ranks].astype(np.int64),
        )

    def request_stream(
        self, n: int, *, seed: int = 0
    ) -> List[Request]:
        """``n`` requests from a fresh seeded stream (test helper; the
        soak runner samples lazily per generator thread instead)."""
        rng = np.random.default_rng(seed)
        return [self.sample(rng) for _ in range(int(n))]


__all__ = [
    "DEFAULT_REGIONS",
    "Region",
    "Request",
    "UserPopulation",
]
