"""SoakRunner — minutes-long open-loop soaks over the full stack.

This is ROADMAP item 4 made runnable: a seeded user population
(:mod:`.population`) arriving on a seeded open-loop schedule
(:mod:`.arrivals`) against the full replicated+elastic cluster — with
the PR-10 nemesis mesh underneath (every shard front door is a
:class:`~..nemesis.proxy.ChaosProxy`, byte-for-byte the same splice
``nemesis/runner.py`` uses) and the overload-control plane
(:mod:`.overload`) switchable per arm, which is what makes the
capacity A/B in ``benchmarks/soak_capacity.py`` an experiment instead
of a demo.

Execution model:

  * the **driver** is a :class:`~..replication.driver
    .ReplicatedClusterDriver` behind the nemesis mesh; an optional
    :class:`~..elastic.controller.ElasticController` polls the local
    registry (replace/promote dead shards, track the load curve);
  * **generator threads** split one global arrival schedule
    round-robin; each samples the population per arrival — a serving
    read (priority 2, through a lease-capable hot-row cache, retry
    budget + per-shard breakers attached) or a training push
    (priority 0, plain client, full retry semantics: a shed write
    would be a lost update, so writes are never shed or budgeted);
  * **latency is arrival-anchored**: every request's latency is
    ``completion − scheduled arrival``, so a backlog shows up as tail
    latency instead of thinning the offered load (no coordinated
    omission);
  * a **nemesis thread** fires ``(at_s, NemesisOp)`` entries through
    :func:`~..nemesis.runner._execute_op` — the same op vocabulary,
    executed on a wall-clock schedule instead of a round counter
    (a soak has no training rounds to key on);
  * the **goodput ledger** classifies every arrival exactly once:
    ``ok`` (answered within the SLO deadline), ``late`` (answered,
    too slow), ``shed`` (typed overload rejection — fast badput),
    ``error`` (anything else), bucketed per second for the timeline
    artifacts.

After teardown the PR-10 invariant checkers run: exactly-once ledger
(writer-acked rows == shard-applied rows), lease staleness at the
WIDENED bound (brownout may have stretched it — the checker enforces
the stretched value), serving error budget, zero leaked threads.

:func:`autoscaler_score` turns a timeline into the controller-quality
figure: SLO-seconds burned vs an ideal controller on the same trace
(ideal = burns only where the offered load exceeds what the LARGEST
configuration can serve at all).

The runner is WORKLOAD-GENERIC (``SoakConfig.workload`` →
workloads/registry.py): the table shape, train-push synthesis and
read-id mapping come from the registered workload, and the push path
can run the q8 codec (``wire_format="q8"``, bypassed for increment
workloads) and the aggregation tree (``push_aggregate=True`` — one
combined uplink push per train drain round, exactly-once on the
uplink).  docs/workloads.md; the arms are recorded in
``results/cpu/soak_capacity.md`` and the workload battery.
"""
from __future__ import annotations

import dataclasses
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .arrivals import RateFn, constant_rate, poisson_arrivals, split_slots
from .overload import (
    PRIORITY_CRITICAL,
    PRIORITY_SHEDDABLE,
    BreakerBoard,
    BrownoutController,
    OverloadGuard,
    OverloadedError,
    RetryBudget,
)
from .population import UserPopulation

OUTCOMES = ("ok", "late", "shed", "error")


class GoodputLedger:
    """Every arrival classified exactly once, bucketed per second.

    ``record`` takes the request's SCHEDULED arrival offset (the
    honest timestamp) and its outcome; admitted requests (ok | late)
    also record their arrival-anchored latency.  ``summary`` closes
    the books: totals per outcome, goodput rate, and arrival-anchored
    p50/p99 over admitted requests."""

    def __init__(self, duration_s: float):
        self.duration_s = float(duration_s)
        n = max(1, int(np.ceil(self.duration_s)))
        self._lock = threading.Lock()
        self._buckets = {o: np.zeros(n, np.int64) for o in OUTCOMES}
        self._latencies: List[float] = []  # admitted, arrival-anchored
        self._shed_lat: List[float] = []   # fail-fast turnaround
        self.arrivals = 0

    def record(
        self, arrival_s: float, outcome: str,
        latency_s: Optional[float] = None,
    ) -> None:
        if outcome not in OUTCOMES:
            raise ValueError(f"outcome {outcome!r}: one of {OUTCOMES}")
        b = min(
            len(self._buckets[outcome]) - 1, max(0, int(arrival_s))
        )
        with self._lock:
            self.arrivals += 1
            self._buckets[outcome][b] += 1
            if latency_s is not None:
                if outcome in ("ok", "late"):
                    self._latencies.append(float(latency_s))
                elif outcome == "shed":
                    self._shed_lat.append(float(latency_s))

    def timeline(self) -> List[Dict[str, int]]:
        with self._lock:
            n = len(self._buckets["ok"])
            return [
                {
                    "t": t,
                    **{o: int(self._buckets[o][t]) for o in OUTCOMES},
                }
                for t in range(n)
            ]

    def summary(self) -> Dict[str, object]:
        with self._lock:
            totals = {
                o: int(self._buckets[o].sum()) for o in OUTCOMES
            }
            lats = np.asarray(self._latencies, np.float64)
            shed_lats = np.asarray(self._shed_lat, np.float64)
            arrivals = self.arrivals
        out: Dict[str, object] = {
            "arrivals": arrivals,
            **totals,
            "admitted": totals["ok"] + totals["late"],
            "goodput_rps": round(totals["ok"] / self.duration_s, 1),
            "offered_rps_observed": round(arrivals / self.duration_s, 1),
            # the honesty flag the --soak artifact lint requires: all
            # latency figures below are measured against the SCHEDULED
            # arrival, never the send time
            "latency_anchor": "arrival",
        }
        if lats.size:
            out["p50_ms"] = round(float(np.percentile(lats, 50)) * 1e3, 3)
            out["p99_ms"] = round(float(np.percentile(lats, 99)) * 1e3, 3)
            out["mean_ms"] = round(float(lats.mean()) * 1e3, 3)
        else:
            out["p50_ms"] = out["p99_ms"] = out["mean_ms"] = None
        out["shed_turnaround_p99_ms"] = (
            round(float(np.percentile(shed_lats, 99)) * 1e3, 3)
            if shed_lats.size else None
        )
        return out


@dataclasses.dataclass
class SoakConfig:
    """One soak experiment.  ``overload_control`` is the A/B switch:
    False runs the identical topology and traffic with no guard, no
    budget, no breakers, no brownout — the collapse arm."""

    duration_s: float = 8.0
    offered_rps: float = 120.0
    rate_fn: Optional[RateFn] = None    # None → constant offered_rps
    rate_max: Optional[float] = None    # required with rate_fn
    generators: int = 4                 # open-loop generator threads
    # training pushes run on their OWN worker pool, fed by a queue
    # from the generators: a push stalled behind a partition (writes
    # keep the 5 s durability-grade timeout) must never block the
    # latency-bound serve traffic sharing its arrival stream
    train_workers: int = 2
    # population shape
    num_users: int = 512
    num_items: int = 1024
    batch_ids: int = 4
    zipf_s: float = 1.1
    regions: Optional[Sequence] = None  # None → population default
    # topology
    dim: int = 8
    num_shards: int = 2
    replication_factor: int = 1
    # the registered workload under soak (workloads/registry.py):
    # "mf" (the incumbent) | "pa" | "sketch" — table shape, push
    # synthesis and read-id mapping all come from the workload, so the
    # open-loop harness regresses any learner the registry knows
    workload: str = "mf"
    # train-push payload encoding (compression/, docs/compression.md):
    # "q8" quantizes push deltas with error feedback — the PR-14
    # follow-on arm, bandwidth-sensitive now that proc shards exist.
    # Increment workloads (sketches) bypass it (exactness carve-out).
    wire_format: str = "b64"
    # two-level aggregation tree on the train-push path: the
    # train workers rendezvous per drain round and ONE combined push
    # per round crosses the wire through a combiner uplink client
    # (compression/aggregator.py; the exactly-once ledger balances on
    # the uplink)
    push_aggregate: bool = False
    # straggler-adaptive runtime kill-switch (adaptive/): the soak
    # runs a single uplink worker on an async serve clock, so the
    # dynamic SSP bounds are inert here — but the push hedger rides
    # the train uplink, and flipping this arms it end to end
    adaptive: bool = False
    adaptive_push_hedge_after_s: Optional[float] = None
    link_delay_ms: float = 1.0          # per-request mesh delay (c2s)
    # the goodput deadline: an answer later than this is badput
    slo_ms: float = 100.0
    # overload-control plane (the arm switch + its knobs)
    overload_control: bool = True
    shed_sheddable_depth: int = 6
    shed_read_depth: int = 24
    retry_budget_capacity: float = 6.0
    breaker_min_failures: int = 8
    breaker_cooldown_s: float = 0.25
    brownout_widen: float = 4.0
    brownout_enter_sheds: int = 16
    # client-edge deadline shedding (the third shed point, after the
    # shard and serving edges): a serve request already older than
    # ``client_deadline_frac × slo_ms`` at DISPATCH is dead on
    # arrival — issuing it would return an answer the caller has
    # given up on while delaying every fresher request behind it, so
    # the overload-control arm sheds it client-side in microseconds.
    # The fraction leaves service-time headroom so admitted requests
    # can still finish inside the SLO.  Train pushes are never
    # deadline-shed (a dropped push is a lost update).
    client_deadline_frac: float = 0.5
    # hot-row cache (both arms: the PR-11 tier is part of the stack)
    cache_bound: int = 32
    cache_capacity: int = 512
    hot_top_n: int = 64
    lease_ttl: int = 64
    # elastic controller (None = fixed topology)
    controller_policy: Optional[object] = None
    controller_interval_s: float = 0.5
    # nemesis schedule under the soak: (at_s, NemesisOp) pairs
    nemesis: Sequence[Tuple[float, object]] = ()
    # closed-loop warmup before the schedule arms: dials connections,
    # builds host mirrors, compiles the jax paths — cold-start costs
    # belong to the stack's birth, not to the soak's tail
    warmup_requests: int = 64
    # client plumbing.  Serve clients run on LATENCY-SCALE deadlines:
    # a serving read blocked 5 s behind a partition is worthless, so
    # its socket/read timeout is a small multiple of the healthy p99
    # and its total retry window is short (the budget sheds the rest).
    # Train clients keep the generous timeouts — a push must land.
    request_timeout: float = 5.0
    connect_timeout: float = 2.0
    retry_timeout: float = 8.0
    serve_timeout_s: float = 0.4
    serve_retry_timeout_s: float = 2.0
    serving_error_budget: int = 0
    # two-tier parameter store (tierstore/, docs/tierstore.md): the
    # shard slices run hot-in-RAM / cold-in-mmap at a bounded resident
    # set.  Purely a store swap — same WAL, same wire, same ledger.
    tiered: bool = False
    tier_hot_rows: int = 4096
    seed: int = 0


@dataclasses.dataclass
class SoakReport:
    """One soak's full outcome: ledger summary + timeline + verdicts."""

    summary: Dict[str, object]
    timeline: List[Dict[str, int]]
    verdicts: List[object]           # nemesis/invariants.Verdict
    faults: Dict[str, int]
    cache: Dict[str, object]
    overload: Dict[str, object]
    controller_events: List[dict]
    wall_s: float
    # the metric-series window from an attached TimelineRecorder
    # (telemetry/timeline.py) — distinct from `timeline`, which is the
    # goodput ledger's per-second offered/completed buckets
    metric_timeline: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return all(v.ok for v in self.verdicts)

    def as_dict(self) -> dict:
        return {
            "summary": self.summary,
            "timeline": self.timeline,
            "verdicts": [v.as_dict() for v in self.verdicts],
            "faults": dict(sorted(self.faults.items())),
            "cache": self.cache,
            "overload": self.overload,
            "controller_events": self.controller_events,
            "wall_s": round(self.wall_s, 3),
            "ok": self.ok,
            "metric_timeline": self.metric_timeline,
        }


def _make_driver_class():
    from ..nemesis.runner import _NemesisMeshMixin
    from ..replication.driver import ReplicatedClusterDriver

    class _GuardedShards:
        """Attach the overload guard to every shard server this
        driver ever builds — initial spin-up, scale-out and
        replacement alike (the same chokepoint discipline as the
        nemesis mesh, one layer further in: the guard rides the REAL
        server, the proxy wraps outside it)."""

        guard_factory = None  # set post-construction, pre-start

        def _build_shard(self, shard_id, partitioner=None):
            shard, server = super()._build_shard(shard_id, partitioner)
            if self.guard_factory is not None:
                server.overload = self.guard_factory(int(shard_id))
            return shard, server

    class SoakMeshDriver(
        _NemesisMeshMixin, _GuardedShards, ReplicatedClusterDriver
    ):
        """Replicated cluster, every primary behind the chaos mesh,
        every shard server behind the overload guard."""

    return SoakMeshDriver


class SoakRunner:
    """Build the stack from a :class:`SoakConfig`, run the open-loop
    soak, tear down, audit.  One-shot: construct → :meth:`run`."""

    def __init__(self, config: SoakConfig, *, registry=None,
                 timeline=None):
        self.config = config
        from ..telemetry.registry import MetricsRegistry
        from ..workloads import WorkloadParams, create_workload

        self.registry = (
            registry if registry is not None else MetricsRegistry()
        )
        # optional TimelineRecorder (telemetry/timeline.py) sampling
        # this runner's registry for the duration of the soak; its
        # window lands on the report as `metric_timeline` and its
        # detector firings pressure the elastic controller when one
        # is configured
        self.timeline = timeline
        # num_users=64 keeps the MF logic identical to the pre-registry
        # soak (worker state is never trained here — driver.run() is
        # not called — but the table shape and init must not move under
        # the capacity ledger); num_items/dim size the table
        self.workload = create_workload(config.workload, WorkloadParams(
            num_users=64, num_items=config.num_items, dim=config.dim,
            seed=1,
        ))

    # -- internals -----------------------------------------------------------
    def _build_driver(self, wal_dir: str):
        from ..replication.driver import ReplicatedClusterConfig
        from ..workloads import build_cluster_driver

        cfg = self.config
        cls = _make_driver_class()
        driver = build_cluster_driver(
            self.workload,
            config=ReplicatedClusterConfig(
                store_backend=("tiered" if cfg.tiered else "socket"),
                tier_hot_rows=cfg.tier_hot_rows,
                num_shards=cfg.num_shards,
                num_workers=1,
                staleness_bound=None,  # serve-side async clock
                wal_dir=wal_dir,
                wire_format=cfg.wire_format,
                replication_factor=cfg.replication_factor,
                adaptive=cfg.adaptive,
                adaptive_push_hedge_after_s=cfg.adaptive_push_hedge_after_s,
                request_timeout=cfg.request_timeout,
                connect_timeout=cfg.connect_timeout,
                retry_timeout=cfg.retry_timeout,
            ),
            driver_cls=cls,
            registry=self.registry,
            driver_kwargs={"nemesis_seed": cfg.seed},
        )
        if cfg.overload_control:
            reg = self.registry

            def factory(shard_id: int) -> OverloadGuard:
                return OverloadGuard(
                    sheddable_depth=cfg.shed_sheddable_depth,
                    read_depth=cfg.shed_read_depth,
                    write_depth=None,
                    registry=reg,
                    shard=shard_id,
                )

            driver.guard_factory = factory
        return driver

    def _make_serve_client(self, driver, name: str, policy, brownout):
        from ..cluster.client import ClusterClient
        from ..hotcache.cache import HotRowCache

        cfg = self.config
        cache = HotRowCache(
            cfg.cache_bound, capacity=cfg.cache_capacity,
            registry=self.registry, worker=name,
        )
        if brownout is not None:
            brownout.attach(cache)
        budget = breakers = None
        if cfg.overload_control:
            budget = RetryBudget(
                cfg.retry_budget_capacity,
                registry=self.registry, worker=name,
            )
            breakers = BreakerBoard(
                min_failures=cfg.breaker_min_failures,
                cooldown_s=cfg.breaker_cooldown_s,
                registry=self.registry, worker=name,
            )
        client = ClusterClient(
            value_shape=self.workload.value_shape,
            membership=driver.membership,
            registry=self.registry,
            worker=name,
            timeout=cfg.serve_timeout_s,
            connect_timeout=min(
                cfg.connect_timeout, cfg.serve_timeout_s
            ),
            retry_timeout=cfg.serve_retry_timeout_s,
            retry_budget=budget,
            breakers=breakers,
            priority=(
                PRIORITY_SHEDDABLE if cfg.overload_control else None
            ),
            hotcache=cache,
            lease_policy=policy,
            lease_ttl=cfg.lease_ttl,
        )
        return client, cache

    def _make_train_client(self, driver, name: str):
        from ..cluster.client import ClusterClient

        cfg = self.config
        # the push-path codec rides the TRAIN clients only (pulls are
        # never quantized); increment workloads get the exactness
        # carve-out here, same rule as ClusterDriver._make_client
        wire_format = cfg.wire_format
        if self.workload.push_semantics == "increment" and \
                wire_format in ("q8", "bf16"):
            wire_format = "b64"
        return ClusterClient(
            value_shape=self.workload.value_shape,
            membership=driver.membership,
            registry=self.registry,
            worker=name,
            timeout=cfg.request_timeout,
            connect_timeout=cfg.connect_timeout,
            retry_timeout=cfg.retry_timeout,
            wire_format=wire_format,
            priority=PRIORITY_CRITICAL if cfg.overload_control else None,
        )

    # -- the run -------------------------------------------------------------
    def run(self) -> SoakReport:
        from ..hotcache.policy import StaticHotSet
        from ..nemesis.invariants import (
            ThreadLedger,
            check_exactly_once,
            check_lease_staleness,
            check_serving_budget,
        )
        from ..nemesis.runner import _execute_op

        cfg = self.config
        if cfg.rate_fn is not None:
            if cfg.rate_max is None:
                raise ValueError("rate_fn needs rate_max (thinning bound)")
            rate_fn, rate_max = cfg.rate_fn, float(cfg.rate_max)
        else:
            rate_fn, rate_max = constant_rate(cfg.offered_rps)
        population = UserPopulation(
            cfg.num_users, cfg.num_items,
            zipf_s=cfg.zipf_s, batch_ids=cfg.batch_ids,
            regions=cfg.regions, seed=cfg.seed,
        )
        arrivals = poisson_arrivals(
            rate_fn, rate_max, cfg.duration_s, seed=cfg.seed + 1
        )
        slots = split_slots(arrivals, cfg.generators)
        ledger = GoodputLedger(cfg.duration_s)
        thread_ledger = ThreadLedger()
        policy = StaticHotSet(population.hot_items(cfg.hot_top_n))
        brownout = (
            BrownoutController(
                widen_factor=cfg.brownout_widen,
                enter_sheds=cfg.brownout_enter_sheds,
                registry=self.registry,
            )
            if cfg.overload_control else None
        )
        workload = self.workload
        t_wall0 = time.perf_counter()
        wal_root = tempfile.mkdtemp(prefix="soak-wal-")
        driver = self._build_driver(wal_root)
        driver.start()
        controller = None
        if cfg.controller_policy is not None:
            from ..elastic.controller import ElasticController

            controller = ElasticController(
                driver, policy=cfg.controller_policy,
                registry=self.registry,
                interval_s=cfg.controller_interval_s,
                timeline=self.timeline,
            )
        if self.timeline is not None:
            self.timeline.mark("soak_start", scenario="soak")
            self.timeline.start()
        serve_clients: List = []
        caches: List = []
        train_clients: List = []
        serve_errors = [0]
        served = [0]
        deadline_sheds = [0]
        error_samples: List[str] = []
        err_lock = threading.Lock()
        push_agg = None
        agg_stop = threading.Event()
        try:
            if cfg.link_delay_ms > 0:
                for proxy in driver.mesh.values():
                    # request leg only: one delay per request burst,
                    # the LAN-RTT model hotcache_storm.py established
                    proxy.set_delay(cfg.link_delay_ms, 0.0, "c2s")
            for g in range(cfg.generators):
                sc, cache = self._make_serve_client(
                    driver, f"loadgen-serve-{g}", policy, brownout
                )
                serve_clients.append(sc)
                caches.append(cache)
            # the aggregation-tree arm funnels every train push through
            # ONE combiner uplink client (its own pid space — the
            # exactly-once ledger balances on the uplink); otherwise
            # one client per train worker
            if cfg.push_aggregate and cfg.train_workers > 1:
                from ..compression.aggregator import PushAggregator

                class _StopAwareAggregator(PushAggregator):
                    """Rendezvous combiner whose shutdown is decided AT
                    a barrier round: the action flips ``finished`` when
                    the stop event is set, so every worker observes the
                    flip after the SAME rendezvous and exits in
                    lockstep (no sibling left parked at the barrier)."""

                    finished = False

                    def _combine(self) -> None:
                        super()._combine()
                        if agg_stop.is_set():
                            self.finished = True

                uplink = self._make_train_client(
                    driver, "loadgen-train-uplink"
                )
                push_agg = _StopAwareAggregator(
                    cfg.train_workers, uplink,
                    registry=self.registry, timeout=30.0,
                )
                train_clients.append(uplink)
            else:
                for w in range(cfg.train_workers):
                    train_clients.append(
                        self._make_train_client(
                            driver, f"loadgen-train-{w}"
                        )
                    )

            # warmup (closed loop, unrecorded): every client touches
            # every shard before the open-loop clock starts
            wrng = np.random.default_rng(cfg.seed + 999)
            per_gen = max(1, int(cfg.warmup_requests) // cfg.generators)
            for g in range(cfg.generators):
                for _ in range(per_gen):
                    try:
                        serve_clients[g].pull_batch(
                            workload.soak_read_ids(
                                population.sample(wrng).ids
                            )
                        )
                    except Exception:  # noqa: BLE001 — warmup only
                        pass
            for tc in train_clients:
                for _ in range(4):
                    try:
                        wids, wdeltas = workload.soak_push(
                            wrng, population.sample(wrng).ids
                        )
                        tc.push_batch(wids, wdeltas * 0.0)
                    except Exception:  # noqa: BLE001 — warmup only
                        pass

            t_start = time.perf_counter() + 0.05
            stop = threading.Event()

            deadline_s = (
                cfg.client_deadline_frac * cfg.slo_ms / 1e3
                if cfg.overload_control else None
            )

            def _record_error(req, offset: float, e: BaseException):
                ledger.record(float(offset), "error")
                with err_lock:
                    if req.kind == "serve":
                        serve_errors[0] += 1
                    if len(error_samples) < 12:
                        error_samples.append(
                            f"{req.kind}: {type(e).__name__}: {e}"
                        )

            import queue as _queue

            train_q: "_queue.Queue" = _queue.Queue()

            def _record_pushed(batch, done: float) -> None:
                for offset, target, _req in batch:
                    lat = done - target
                    ledger.record(
                        float(offset),
                        "ok" if lat <= cfg.slo_ms / 1e3 else "late",
                        lat,
                    )

            def train_worker_loop(w: int) -> None:
                rng = np.random.default_rng(cfg.seed + 700 + w)
                client = train_clients[w]
                while True:
                    item = train_q.get()
                    if item is None:
                        return
                    # combination-sender semantics under backlog: drain
                    # whatever else queued and push it as ONE aggregated
                    # batch (duplicate ids sum client-side) — the same
                    # sender-side aggregation the cluster client applies
                    # per frame, lifted to the request queue, which is
                    # what keeps unsheddable write traffic inside its
                    # capacity share under overload
                    batch = [item]
                    while len(batch) < 32:
                        try:
                            nxt = train_q.get_nowait()
                        except _queue.Empty:
                            break
                        if nxt is None:
                            train_q.put(None)  # re-arm shutdown
                            break
                        batch.append(nxt)
                    ids, deltas = workload.soak_push(
                        rng, np.concatenate([b[2].ids for b in batch])
                    )
                    try:
                        client.push_batch(ids, deltas)
                        _record_pushed(batch, time.perf_counter())
                    except Exception as e:  # noqa: BLE001
                        for offset, _target, req in batch:
                            _record_error(req, offset, e)

            def train_worker_agg_loop(w: int) -> None:
                """The aggregation-tree train path: every drain round is
                a rendezvous (possibly with an EMPTY contribution — the
                barrier must see all workers each round), and the
                combiner pushes one merged batch through the uplink.
                Exit is lockstep via the barrier-action stop flag;
                stragglers left in the queue are drained by the main
                thread directly through the uplink."""
                rng = np.random.default_rng(cfg.seed + 700 + w)
                while True:
                    batch = []
                    try:
                        item = train_q.get(timeout=0.05)
                        if item is not None:
                            batch.append(item)
                    except _queue.Empty:
                        pass
                    while batch and len(batch) < 32:
                        try:
                            nxt = train_q.get_nowait()
                        except _queue.Empty:
                            break
                        if nxt is not None:
                            batch.append(nxt)
                    if batch:
                        ids, deltas = workload.soak_push(
                            rng,
                            np.concatenate([b[2].ids for b in batch]),
                        )
                    else:
                        ids = np.empty(0, np.int64)
                        deltas = np.empty(
                            (0,) + tuple(workload.value_shape),
                            np.float32,
                        )
                    try:
                        push_agg.push_batch(w, ids, deltas)
                        if batch:
                            _record_pushed(batch, time.perf_counter())
                    except BaseException as e:  # noqa: BLE001
                        for offset, _target, req in batch:
                            _record_error(req, offset, e)
                        if agg_stop.is_set():
                            return  # barrier broken at teardown
                    if push_agg.finished:
                        return

            def generator_loop(g: int) -> None:
                rng = np.random.default_rng(cfg.seed + 100 + g)
                serve = serve_clients[g]
                for offset in slots[g]:
                    if stop.is_set():
                        # teardown mid-schedule (nemesis wedged the
                        # run): the remainder is recorded as errors —
                        # an arrival we never served is not goodput
                        ledger.record(float(offset), "error")
                        continue
                    target = t_start + float(offset)
                    now = time.perf_counter()
                    if target > now:
                        time.sleep(target - now)
                    req = population.sample(rng)
                    if req.kind == "train":
                        # pushes ride their own worker pool: a write
                        # stalled behind a fault (writes keep the
                        # durability-grade timeout) must never block
                        # this generator's latency-bound serve traffic
                        train_q.put((float(offset), target, req))
                        continue
                    if (
                        deadline_s is not None
                        and time.perf_counter() - target > deadline_s
                    ):
                        with err_lock:
                            deadline_sheds[0] += 1
                        # dead on arrival: the generator is behind
                        # schedule past the deadline budget — shed at
                        # the client edge instead of serving an answer
                        # nobody is waiting for
                        ledger.record(
                            float(offset), "shed",
                            time.perf_counter() - target,
                        )
                        if brownout is not None:
                            brownout.note_shed()
                        continue
                    try:
                        serve.pull_batch(workload.soak_read_ids(req.ids))
                        with err_lock:
                            served[0] += 1
                        lat = time.perf_counter() - target
                        ledger.record(
                            float(offset),
                            "ok" if lat <= cfg.slo_ms / 1e3 else "late",
                            lat,
                        )
                        if brownout is not None:
                            brownout.note_ok()
                    except OverloadedError:
                        ledger.record(
                            float(offset), "shed",
                            time.perf_counter() - target,
                        )
                        if brownout is not None:
                            brownout.note_shed()
                    except Exception as e:  # noqa: BLE001 — budgeted
                        _record_error(req, offset, e)

            def nemesis_loop() -> None:
                for at_s, op in sorted(
                    self.config.nemesis, key=lambda e: e[0]
                ):
                    wait = (t_start + float(at_s)) - time.perf_counter()
                    if wait > 0 and stop.wait(wait):
                        return
                    try:
                        _execute_op(driver, op)
                    except Exception:  # noqa: BLE001 — a failed op is
                        pass  # a no-op fault, not a failed soak

            threads = [
                threading.Thread(
                    target=generator_loop, args=(g,),
                    name=f"loadgen-generator-{g}", daemon=True,
                )
                for g in range(cfg.generators)
            ]
            train_threads = [
                threading.Thread(
                    target=(
                        train_worker_agg_loop if push_agg is not None
                        else train_worker_loop
                    ),
                    args=(w,),
                    name=f"loadgen-train-worker-{w}", daemon=True,
                )
                for w in range(cfg.train_workers)
            ]
            nem = threading.Thread(
                target=nemesis_loop, name="loadgen-nemesis", daemon=True
            )
            if controller is not None:
                controller.start()
            nem.start()
            for t in train_threads:
                t.start()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if push_agg is not None:
                # lockstep shutdown at a rendezvous round, then drain
                # stragglers directly through the uplink
                agg_stop.set()
                for t in train_threads:
                    t.join(timeout=60)
                push_agg.abort()
                drain_rng = np.random.default_rng(cfg.seed + 799)
                uplink_client = train_clients[0]
                while True:
                    try:
                        item = train_q.get_nowait()
                    except _queue.Empty:
                        break
                    if item is None:
                        continue
                    offset, target, req = item
                    try:
                        ids, deltas = workload.soak_push(
                            drain_rng, req.ids
                        )
                        uplink_client.push_batch(ids, deltas)
                        _record_pushed(
                            [item], time.perf_counter()
                        )
                    except Exception as e:  # noqa: BLE001
                        _record_error(req, offset, e)
            else:
                # drain the push queue, then release the workers
                for _ in train_threads:
                    train_q.put(None)
                for t in train_threads:
                    t.join(timeout=60)
            stop.set()
            nem.join(timeout=10)
        finally:
            stop.set()
            if controller is not None:
                controller.stop()
            if self.timeline is not None:
                self.timeline.sample()   # final tick: post-run state
                self.timeline.stop()
                self.timeline.mark("soak_end", scenario="soak")
            for proxy in driver.mesh.values():
                proxy.heal()
                proxy.clear_delay()
                proxy.clear_drip()
            acked = sum(c.rows_pushed for c in train_clients)
            for c in serve_clients + train_clients:
                try:
                    c.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            applied = sum(sh.rows_applied for sh in driver.all_shards)
            faults = driver.faults_injected()
            driver.stop()

        # -- the audit --------------------------------------------------------
        widened_bound = int(np.ceil(
            cfg.cache_bound
            * (cfg.brownout_widen if brownout is not None
               and brownout.entries else 1.0)
        ))
        cache_stats: Dict[str, object] = {}
        _summable = (
            "hits", "misses", "fills", "revocations", "stale_rejects",
            "evictions", "entries",
        )
        for c in caches:
            stats = c.stats()
            for k in _summable:
                cache_stats[k] = cache_stats.get(k, 0) + stats[k]
        cache_stats["bound"] = cfg.cache_bound
        cache_stats["widened_bound"] = widened_bound
        cache_stats["max_served_age"] = max(
            (c.stats()["max_served_age"] for c in caches), default=0
        )
        verdicts = [
            check_exactly_once(acked, applied),
            check_lease_staleness(cache_stats, bound=widened_bound),
            check_serving_budget(
                served[0], serve_errors[0],
                budget=cfg.serving_error_budget,
            ),
            thread_ledger.check(),
        ]
        overload_stats: Dict[str, object] = {
            "control": cfg.overload_control,
            "brownouts": 0 if brownout is None else brownout.entries,
            "widen_factor": (
                cfg.brownout_widen if cfg.overload_control else 1.0
            ),
            "wire_format": cfg.wire_format,
            "push_aggregate": push_agg is not None,
        }
        if push_agg is not None:
            overload_stats["combined_pushes"] = push_agg.rounds_combined
            overload_stats["combined_rows_saved"] = max(
                0, push_agg.rows_in - push_agg.rows_pushed
            )
        # push-path codec effect (compression/): bytes the q8 arm kept
        # off the wire, summed over every train client's compressor
        saved = sum(
            int(inst.value)
            for inst in self.registry.instruments()
            if inst.name == "compression_bytes_saved_total"
        )
        if saved:
            overload_stats["compression_bytes_saved"] = saved
        if cfg.overload_control:
            overload_stats["client_deadline_sheds"] = deadline_sheds[0]
            overload_stats["shard_edge_sheds"] = int(sum(
                inst.value
                for inst in self.registry.instruments()
                if inst.name == "overload_shed_total"
                and inst.labels.get("edge") == "shard"
            ))
            overload_stats["budget_exhausted"] = sum(
                c.retry_budget.exhausted for c in serve_clients
                if c.retry_budget is not None
            )
            overload_stats["breakers_open_transitions"] = sum(
                b.transitions["open"]
                for c in serve_clients
                if c.breakers is not None
                for b in c.breakers._breakers.values()
            )
        summary = ledger.summary()
        summary["error_samples"] = list(error_samples)
        return SoakReport(
            summary=summary,
            timeline=ledger.timeline(),
            verdicts=verdicts,
            faults=faults,
            cache=cache_stats,
            overload=overload_stats,
            controller_events=(
                list(controller.events) if controller is not None else []
            ),
            wall_s=time.perf_counter() - t_wall0,
            metric_timeline=(
                self.timeline.payload() if self.timeline is not None
                else None
            ),
        )


def run_soak(config: SoakConfig, *, registry=None,
             timeline=None) -> SoakReport:
    """One-call form of :class:`SoakRunner`."""
    return SoakRunner(config, registry=registry, timeline=timeline).run()


def closed_loop_capacity(
    config: SoakConfig,
    *,
    requests_per_generator: int = 200,
    registry=None,
) -> Dict[str, float]:
    """CLOSED-loop calibration of one topology: the same population,
    clients and mesh links as the soak, arrivals coupled to
    completions — the sustainable completion rate, which is what the
    open-loop A/B's "2× capacity" is 2× OF.  Overload control is
    forced OFF (a calibration that sheds is measuring the shed
    policy, not the topology) and no nemesis runs.  Returns
    ``capacity_rps`` plus closed-loop p50/p99 (ms) — the curve row is
    a capacity **at the p99 SLO** only when that p99 is under it."""
    from ..hotcache.policy import StaticHotSet

    cfg = dataclasses.replace(
        config, overload_control=False, nemesis=(),
        controller_policy=None,
    )
    runner = SoakRunner(cfg, registry=registry)
    population = UserPopulation(
        cfg.num_users, cfg.num_items,
        zipf_s=cfg.zipf_s, batch_ids=cfg.batch_ids,
        regions=cfg.regions, seed=cfg.seed,
    )
    policy = StaticHotSet(population.hot_items(cfg.hot_top_n))
    wal_root = tempfile.mkdtemp(prefix="soak-calib-wal-")
    driver = runner._build_driver(wal_root)
    driver.start()
    serve_clients: List = []
    train_clients: List = []
    lat: List[List[float]] = [[] for _ in range(cfg.generators)]
    errors: List[BaseException] = []
    try:
        if cfg.link_delay_ms > 0:
            for proxy in driver.mesh.values():
                proxy.set_delay(cfg.link_delay_ms, 0.0, "c2s")
        for g in range(cfg.generators):
            sc, _cache = runner._make_serve_client(
                driver, f"loadgen-calib-serve-{g}", policy, None
            )
            serve_clients.append(sc)
            train_clients.append(
                runner._make_train_client(
                    driver, f"loadgen-calib-train-{g}"
                )
            )
        workload = runner.workload
        wrng = np.random.default_rng(cfg.seed + 999)
        for g in range(cfg.generators):
            for _ in range(12):
                req = population.sample(wrng)
                serve_clients[g].pull_batch(
                    workload.soak_read_ids(req.ids)
                )
                # pushes too: the first push of each padded bucket
                # shape pays a jax scatter compile (~100 ms) that
                # belongs to warmup, not the measured tail
                wids, wdeltas = workload.soak_push(wrng, req.ids)
                train_clients[g].push_batch(wids, wdeltas * 0.0)

        def loop(g: int) -> None:
            rng = np.random.default_rng(cfg.seed + 500 + g)
            try:
                for _ in range(int(requests_per_generator)):
                    req = population.sample(rng)
                    t0 = time.perf_counter()
                    if req.kind == "serve":
                        serve_clients[g].pull_batch(
                            workload.soak_read_ids(req.ids)
                        )
                    else:
                        train_clients[g].push_batch(
                            *workload.soak_push(rng, req.ids)
                        )
                    lat[g].append(time.perf_counter() - t0)
            except BaseException as e:  # noqa: BLE001 — re-raised
                errors.append(e)

        threads = [
            threading.Thread(
                target=loop, args=(g,),
                name=f"loadgen-calib-{g}", daemon=True,
            )
            for g in range(cfg.generators)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errors:
            raise errors[0]
    finally:
        for c in serve_clients + train_clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        driver.stop()
    all_lat = np.asarray([x for sub in lat for x in sub], np.float64)
    total = int(all_lat.size)
    return {
        "capacity_rps": round(total / wall, 1),
        "requests": total,
        "closed_p50_ms": round(
            float(np.percentile(all_lat, 50)) * 1e3, 3
        ),
        "closed_p99_ms": round(
            float(np.percentile(all_lat, 99)) * 1e3, 3
        ),
        "wall_s": round(wall, 3),
    }


def autoscaler_score(
    timeline: Sequence[Dict[str, int]],
    rate_fn: RateFn,
    max_capacity_rps: float,
    *,
    slo_target: float = 0.9,
) -> Dict[str, object]:
    """Controller quality over a soak timeline: SLO-seconds burned vs
    the ideal controller on the SAME trace.

    A second is BURNED when it saw arrivals and delivered less than
    ``slo_target`` of them as goodput (``ok``).  The ideal controller
    — instantly at the right size, never migrating — still burns the
    seconds where the offered rate exceeds what the largest measured
    configuration can serve (``max_capacity_rps``): no controller can
    scale past the hardware.  Score = 1 − excess burned fraction over
    the seconds the ideal keeps clean; 1.0 = as good as ideal, 0.0 =
    burned everything ideal would have saved."""
    burned = []
    ideal_burned = []
    for row in timeline:
        t = row["t"]
        arr = sum(row[o] for o in OUTCOMES)
        if arr == 0:
            continue
        burned.append(row["ok"] < slo_target * arr)
        ideal_burned.append(rate_fn(t + 0.5) > max_capacity_rps)
    total = len(burned)
    n_burn = sum(burned)
    n_ideal = sum(ideal_burned)
    # only seconds the ideal controller keeps clean count against us
    excess = sum(
        1 for b, i in zip(burned, ideal_burned) if b and not i
    )
    saveable = total - n_ideal
    score = 1.0 if saveable <= 0 else max(0.0, 1.0 - excess / saveable)
    return {
        "slo_seconds_burned": int(n_burn),
        "ideal_slo_seconds_burned": int(n_ideal),
        "excess_slo_seconds": int(excess),
        "active_seconds": int(total),
        "score": round(score, 4),
        "slo_target": slo_target,
    }


__all__ = [
    "GoodputLedger",
    "OUTCOMES",
    "SoakConfig",
    "SoakReport",
    "SoakRunner",
    "autoscaler_score",
    "closed_loop_capacity",
    "run_soak",
]
