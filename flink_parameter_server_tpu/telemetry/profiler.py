"""Latency-budget profiler — where do the milliseconds of a round go?

The plane built so far answers *that* a pull took 5 ms (histograms,
spans, SLO burn rates) but not *where*: ROADMAP item 2 (multiprocess
shards + binary framing) is justified by the claim that text/b64 parse
cost and handler serialization dominate the wire path, and until this
module that claim was hypothesis.  The straggler study
(arXiv:2308.15482) diagnoses PS tail latency from exactly this kind of
hidden per-phase imbalance, and MXNET-MPI (arXiv:1801.03855) motivates
its aggregation redesign with per-stage cost breakdowns — so every
cluster round is decomposed here into named phases:

    client_serialize → wire → server_queue_wait → server_parse →
    wal_append → scatter_apply → response_serialize → client_parse

Two measurement styles, one seam:

  * :class:`PhaseProfiler` — fine-grained sub-span accounting.  Call
    sites (``cluster/client.py``, ``cluster/shard.py``,
    ``elastic/hedging.py``, ``serving/server.py``) wrap each phase in
    ``profiler.timer(verb, phase)``; observations land in a registry
    histogram family ``phase_seconds{component="profiler", verb=,
    phase=}`` (live on ``/metrics``) AND in a bounded exact-sample
    reservoir per (verb, phase) — bucket-interpolated percentiles are
    fine for dashboards but too coarse for budget arithmetic, where a
    2.5× bucket straddle would swamp the 10% additivity bound the
    tests pin.  :meth:`PhaseProfiler.budget` assembles the
    per-round budget: measured phases by exact p50, ``wire`` as the
    client-RTT minus server-busy residual, ``server_other`` as the
    server-busy minus attributed-phase residual — so the phases sum to
    the round by construction *of honest residuals*, and the test
    oracle (span-trace p50 of the whole round) checks the measured
    parts actually cover it.
  * :class:`StackSampler` — a low-overhead sampling stack profiler
    (``sys._current_frames()`` on a timer thread): when a phase is
    fat, the folded-stack export says which FUNCTION inside it burns
    the time.  Export formats: collapsed stacks (flamegraph.pl /
    speedscope) and a retroactive :class:`~.spans.SpanTracer` ring
    (:meth:`StackSampler.to_tracer`) so samples ride the existing
    :class:`~.distributed.TraceCollector` lanes next to the span
    timeline.

Both are attribution, not load: a disabled profiler's ``timer()`` is a
shared no-op (two attribute reads), and the sampler costs one frame
walk per interval (default 100 ms — see :class:`StackSampler` for the
measured tax curve on a single-core host) — the overhead A/B
(``benchmarks/telemetry_overhead.py``) runs with both ON and the bar
stays ≤ 3%.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .registry import MetricsRegistry, get_registry, json_line

# Canonical phase order of one cluster round (docs/observability.md).
# ``wire`` and ``server_other`` are residuals derived at budget time;
# everything else is measured at its call site.  The BINARY transport
# (utils/frames.py) reuses these names — frame encode IS
# client_serialize, frame decode IS server_parse — which is what keeps
# the line-vs-binary A/B (results/cpu/transport_ab.md) directly
# comparable.  The vocabulary is pinned in lockstep with
# ``tools/check_metric_lines.KNOWN_BUDGET_PHASES`` (a tier-1 test
# compares the two), so a renamed/added phase must update the lint,
# the docs, and this tuple together.
PHASES: Tuple[str, ...] = (
    "client_serialize",
    "wire",
    "server_queue_wait",
    "server_parse",
    "wal_append",
    "scatter_apply",
    "response_serialize",
    "server_other",
    "client_parse",
)

# Phases measured server-side whose sum is compared against the
# server's whole-request wall (``server_total``) for the
# ``server_other`` residual.
_SERVER_PHASES: Tuple[str, ...] = (
    "server_queue_wait",
    "server_parse",
    "wal_append",
    "scatter_apply",
    "response_serialize",
)

# Phase durations are µs-to-ms scale; the default latency buckets
# (0.5 ms floor) would collapse most phases into one bin.
PROFILE_BUCKETS: Tuple[float, ...] = (
    1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5,
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    0.01, 0.025, 0.05, 0.1, 0.25, 1.0,
)


class _NullTimer:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_TIMER = _NullTimer()


class _NullProfiler:
    """The disabled profiler: every call is a no-op, shared
    process-wide so call sites can keep unconditional `.timer(...)`."""

    __slots__ = ()
    enabled = False

    def timer(self, verb: str, phase: str):
        return _NULL_TIMER

    def observe(self, verb: str, phase: str, seconds: float) -> None:
        pass


NULL_PROFILER = _NullProfiler()


class _PhaseTimer:
    __slots__ = ("prof", "verb", "phase", "t0")

    def __init__(self, prof: "PhaseProfiler", verb: str, phase: str):
        self.prof = prof
        self.verb = verb
        self.phase = phase

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.prof.observe(
            self.verb, self.phase, time.perf_counter() - self.t0
        )
        return False


class PhaseProfiler:
    """Per-phase cost accounting over (verb, phase) keys.

    Observations land twice: a registry histogram
    ``phase_seconds{verb=,phase=}`` (the ``/metrics`` surface, bucketed)
    and an exact bounded reservoir (the budget arithmetic surface —
    exact medians, no bucket interpolation error).  The histogram's
    ``sum``/``count`` are exact too, so means come from there.
    """

    enabled = True

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        *,
        reservoir: int = 4096,
    ):
        self.registry = registry if registry is not None else get_registry()
        self.reservoir = int(reservoir)
        self._lock = threading.Lock()
        # (verb, phase) -> (histogram, deque-of-recent-values)
        self._sites: Dict[Tuple[str, str], Tuple[Any, deque]] = {}

    # -- recording ---------------------------------------------------------
    def _site(self, verb: str, phase: str) -> Tuple[Any, deque]:
        key = (verb, phase)
        site = self._sites.get(key)  # dict reads are GIL-atomic
        if site is None:
            with self._lock:
                site = self._sites.get(key)
                if site is None:
                    h = self.registry.histogram(
                        "phase_seconds", component="profiler",
                        buckets=PROFILE_BUCKETS, verb=verb, phase=phase,
                    )
                    site = (h, deque(maxlen=self.reservoir))
                    self._sites[key] = site
        return site

    def observe(self, verb: str, phase: str, seconds: float) -> None:
        h, ring = self._site(verb, phase)
        h.observe(seconds)
        ring.append(float(seconds))

    def timer(self, verb: str, phase: str):
        """``with profiler.timer("pull", "client_serialize"): ...``"""
        return _PhaseTimer(self, verb, phase)

    # -- reads -------------------------------------------------------------
    def stat(self, verb: str, phase: str) -> Dict[str, float]:
        """Exact ``{count, mean, p50, total}`` seconds for one site
        (zeros when never observed)."""
        site = self._sites.get((verb, phase))
        if site is None:
            return {"count": 0, "mean": 0.0, "p50": 0.0, "total": 0.0}
        h, ring = site
        count = h.count
        if count == 0:
            return {"count": 0, "mean": 0.0, "p50": 0.0, "total": 0.0}
        vals = sorted(ring)
        mid = len(vals) // 2
        p50 = (
            vals[mid] if len(vals) % 2
            else 0.5 * (vals[mid - 1] + vals[mid])
        )
        return {
            "count": count,
            "mean": h.sum / count,
            "p50": p50,
            "total": h.sum,
        }

    def verbs(self) -> List[str]:
        with self._lock:
            return sorted({v for (v, _p) in self._sites})

    def budget(self, verb: str = "pull") -> Dict[str, Any]:
        """The latency budget of one round of ``verb`` traffic.

        Measured phases use their exact reservoir p50; two residuals
        close the books: ``wire`` = p50(rtt) − p50(server_total)
        (client-observed round trip minus server busy time — transport
        + kernel + framing cost) and ``server_other`` =
        p50(server_total) − Σ attributed server phases (dispatch
        overhead the sub-spans don't cover).  Phases therefore sum to
        ``round_ms`` = p50(client_serialize) + p50(rtt) +
        p50(client_parse) by construction; what the span-trace oracle
        test checks is that this round matches the independently
        traced whole-round p50 — i.e. that the instrumented sites
        actually cover the round.  Without server-side data in this
        registry (a future cross-process topology), ``wire`` honestly
        absorbs the whole RTT and ``coverage`` says "client-only".
        """
        rtt = self.stat(verb, "rtt")
        srv = self.stat(verb, "server_total")
        c_ser = self.stat(verb, "client_serialize")
        c_par = self.stat(verb, "client_parse")
        coverage = "full" if srv["count"] else (
            "client-only" if rtt["count"] else "none"
        )
        measured_srv = {p: self.stat(verb, p) for p in _SERVER_PHASES}
        wire_p50 = max(0.0, rtt["p50"] - srv["p50"])
        srv_attr = sum(s["p50"] for s in measured_srv.values())
        other_p50 = max(0.0, srv["p50"] - srv_attr)
        round_s = c_ser["p50"] + rtt["p50"] + c_par["p50"]
        phases: List[Dict[str, Any]] = []

        def add(phase: str, p50: float, count: int, mean: float) -> None:
            phases.append({
                "phase": phase,
                "p50_ms": round(p50 * 1e3, 4),
                "mean_ms": round(mean * 1e3, 4),
                "count": int(count),
                "pct": round(100.0 * p50 / round_s, 1) if round_s else 0.0,
            })

        add("client_serialize", c_ser["p50"], c_ser["count"], c_ser["mean"])
        add("wire", wire_p50, rtt["count"],
            max(0.0, rtt["mean"] - srv["mean"]))
        for p in _SERVER_PHASES:
            s = measured_srv[p]
            add(p, s["p50"], s["count"], s["mean"])
        add("server_other", other_p50, srv["count"],
            max(0.0, srv["mean"] - sum(
                s["mean"] for s in measured_srv.values()
            )))
        add("client_parse", c_par["p50"], c_par["count"], c_par["mean"])
        top = max(phases, key=lambda p: p["pct"]) if round_s else None
        return {
            "verb": verb,
            "round_ms": round(round_s * 1e3, 4),
            "rounds": int(rtt["count"]),
            "coverage": coverage,
            "phases": phases,
            "top_phase": None if top is None else top["phase"],
            "top_pct": None if top is None else top["pct"],
        }

    def budget_report(self) -> Dict[str, Any]:
        """Budgets for every verb with data — the run-report /
        ``psctl budget`` payload."""
        return {
            v: self.budget(v)
            for v in self.verbs()
            if self.stat(v, "rtt")["count"]
            or self.stat(v, "server_total")["count"]
        }

    def write_budget_artifact(self, path: Optional[str] = None) -> str:
        """One JSON artifact (ts/run_id-stamped like every emitter;
        ``tools/check_metric_lines.py --budget`` lints it)."""
        line = json_line(
            {"kind": "latency_budget", "budgets": self.budget_report()},
            run_id=self.registry.run_id,
        )
        if path is not None:
            with open(path, "w") as f:
                f.write(line + "\n")
        return line


# -- sampling stack profiler --------------------------------------------------


# Per-code-object formatted frame names.  A sample tick runs WITH the
# GIL held, so the fold must be near-free: the same code objects recur
# every tick, and formatting (basename + f-string) dominates without
# this cache.  Keyed by the code object itself (not id() — id reuse
# after GC would alias frames); bounded by the program's distinct code
# objects.
_CODE_NAMES: Dict[Any, str] = {}


def _fold_stack(frame, max_depth: int) -> str:
    """``root;...;leaf`` collapsed-stack key for one thread's current
    frame (flamegraph.pl grammar: semicolon-joined, root first)."""
    parts: List[str] = []
    depth = 0
    while frame is not None and depth < max_depth:
        code = frame.f_code
        name = _CODE_NAMES.get(code)
        if name is None:
            name = (
                f"{os.path.basename(code.co_filename)}:{code.co_name}"
            )
            _CODE_NAMES[code] = name
        parts.append(name)
        frame = frame.f_back
        depth += 1
    parts.reverse()
    return ";".join(parts)


class StackSampler:
    """Low-overhead sampling profiler over every live thread.

    A daemon thread wakes every ``interval_s``, snapshots
    ``sys._current_frames()`` (one C-level dict copy — no tracing hooks,
    no per-call cost on the profiled code), and accumulates folded
    stacks.  The default 100 ms interval is chosen by measurement, not
    taste: in-process sampling shares the GIL (and, on a single-core
    box, the core) with the profiled code, so every wakeup steals real
    time — measured on the 1-core CI container, 5 ms sampling cost
    ~6% of driver throughput, 50 ms ~2.6%; 100 ms keeps the whole
    telemetry plane inside its ≤ 3% bar while still collecting 10
    samples/sec (thousands over any window worth flame-graphing — a
    parameter server is a long-running process).  Drop ``interval_s``
    for short windows on multi-core hosts, where the sampling thread
    runs on a spare core and the tax is near zero.  Exports:

      * :meth:`export_folded` — collapsed-stack text
        (``a;b;c <count>`` per line; flamegraph.pl / speedscope load
        it directly);
      * :meth:`to_tracer` — a retroactive :class:`~.spans.SpanTracer`
        ring (one ``interval_s``-wide span per sampled leaf, lane
        ``process="stack-sampler"``) so the samples merge into a
        :class:`~.distributed.TraceCollector` timeline next to the
        phase spans.

    The sampler's own thread is excluded.  The folded table is bounded
    (``max_stacks`` distinct stacks; overflow folds into ``<other>``)
    so a week-long job cannot OOM the host.
    """

    def __init__(
        self,
        interval_s: float = 0.1,
        *,
        max_depth: int = 32,
        max_stacks: int = 10_000,
        keep_samples: int = 65536,
        process: str = "stack-sampler",
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s={interval_s}: must be > 0")
        self.interval_s = float(interval_s)
        self.max_depth = int(max_depth)
        self.max_stacks = int(max_stacks)
        self.process = process
        self.samples = 0  # sampling ticks taken
        self._folded: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (perf_counter_ts, thread_name, leaf_frame) for to_tracer()
        self._recent: deque = deque(maxlen=int(keep_samples))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "StackSampler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="stack-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def __enter__(self) -> "StackSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- sampling ----------------------------------------------------------
    def _loop(self) -> None:
        own = threading.get_ident()
        names: Dict[int, str] = {}
        refresh = 0
        while not self._stop.wait(self.interval_s):
            now = time.perf_counter()
            frames = sys._current_frames()
            if refresh == 0 or any(i not in names for i in frames):
                names = {t.ident: t.name for t in threading.enumerate()}
            refresh = (refresh + 1) % 50
            with self._lock:
                self.samples += 1
                for ident, frame in frames.items():
                    if ident == own:
                        continue
                    name = names.get(ident, f"thread-{ident}")
                    key = name + ";" + _fold_stack(frame, self.max_depth)
                    if (
                        key not in self._folded
                        and len(self._folded) >= self.max_stacks
                    ):
                        key = "<other>"
                    self._folded[key] = self._folded.get(key, 0) + 1
                    leaf = key.rsplit(";", 1)[-1]
                    self._recent.append((now, name, leaf))

    # -- exports -----------------------------------------------------------
    def folded(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._folded)

    def export_folded(self, path: Optional[str] = None) -> str:
        """Collapsed-stack text, heaviest stacks first."""
        items = sorted(
            self.folded().items(), key=lambda kv: (-kv[1], kv[0])
        )
        text = "".join(f"{stack} {n}\n" for stack, n in items)
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    def top(self, n: int = 10) -> List[Tuple[str, int]]:
        """Heaviest LEAF frames (self time, in samples) — the quick
        `psctl`-style answer to "what is the process doing"."""
        leaves: Dict[str, int] = {}
        for stack, count in self.folded().items():
            leaf = stack.rsplit(";", 1)[-1]
            leaves[leaf] = leaves.get(leaf, 0) + count
        return sorted(leaves.items(), key=lambda kv: (-kv[1], kv[0]))[:n]

    def to_tracer(self, capacity: Optional[int] = None):
        """Replay the retained samples into a fresh
        :class:`~.spans.SpanTracer` ring (component ``stack``, one
        ``interval_s``-wide retroactive span per sampled leaf) —
        feed it to ``TraceCollector.add()`` to see the sampled flame
        next to the span lanes."""
        from .spans import SpanTracer

        with self._lock:
            recent = list(self._recent)
        ring = SpanTracer(
            capacity=capacity if capacity is not None else max(
                1, len(recent)
            ),
            process=self.process,
        )
        for ts, name, leaf in recent:
            ring.record(
                f"{name}: {leaf}", ts, ts + self.interval_s,
                component="stack",
            )
        return ring


# -- the process-wide default -------------------------------------------------
_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[PhaseProfiler] = None
_DEFAULT_AUTO = False  # True when get_profiler() created it lazily


def get_profiler() -> PhaseProfiler:
    """The process-wide default profiler (created on first use, over
    the default registry) — what the cluster/serving call sites resolve
    when not handed one explicitly.  An auto-created default follows
    registry swaps (``set_registry``): a test that isolates the
    registry gets a fresh profiler too, instead of one pinned to the
    previous test's instruments."""
    global _DEFAULT, _DEFAULT_AUTO
    with _DEFAULT_LOCK:
        if _DEFAULT is None or (
            _DEFAULT_AUTO and _DEFAULT.registry is not get_registry()
        ):
            _DEFAULT = PhaseProfiler()
            _DEFAULT_AUTO = True
        return _DEFAULT


def set_profiler(profiler: Optional[PhaseProfiler]) -> None:
    """Swap the process default (tests isolate themselves with this;
    None resets to lazy re-creation)."""
    global _DEFAULT, _DEFAULT_AUTO
    with _DEFAULT_LOCK:
        _DEFAULT = profiler
        _DEFAULT_AUTO = False


def resolve_profiler(profiler=None):
    """The call-site convention mirrors ``registry=``: None → process
    default, False → the shared no-op, an instance → itself."""
    if profiler is False:
        return NULL_PROFILER
    if profiler is None:
        return get_profiler()
    return profiler


__all__ = [
    "PHASES",
    "PROFILE_BUCKETS",
    "NULL_PROFILER",
    "PhaseProfiler",
    "StackSampler",
    "get_profiler",
    "set_profiler",
    "resolve_profiler",
]
