"""Timeline plane — continuous metric series over the registry.

Every other telemetry surface is point-in-time: ``snapshot()`` answers
"what is true now", the profiler answers "where did this round go",
the SLO engine answers "is the objective burning".  None of them can
answer "when did shard 0 start getting slow" — the question the
straggler study (arXiv:2308.15482, PAPERS.md) says dominates PS
throughput, and the one ROADMAP item 3 (straggler-adaptive runtime)
needs answered before it can adapt anything.

:class:`TimelineRecorder` is the missing time axis: a background
sampler that polls a :class:`~.registry.MetricsRegistry` on a fixed
cadence into bounded per-instrument ring series —

  * counters become **rates** (value delta / wall delta),
  * gauges become **values** (live probes resolved per sample),
  * histograms become **windowed p50/p99** via bucket-count deltas and
    the same in-bin interpolation ``ElasticController`` already uses
    for its windowed RTT p99 (:func:`percentile_from_counts` is that
    math, hoisted here so both consumers share one implementation).

Because identity is (name, label set, derived field), labelled
instruments fan out into per-entity series for free:
``phase_seconds{verb,phase}`` and ``cluster_shard_rtt_seconds{shard}``
become per-verb / per-shard time series without any instrument
changing its meaning (the MXNET-MPI lesson: new capability layered
under an unchanged task model).

On top ride two consumers fed inline at sample time:

  * :class:`SkewTracker` — windowed per-entity medians over one
    metric's series, published as ``skew_ratio{metric,entity}``
    gauges (``fps_skew_ratio`` on ``/metrics``); the max/median skew
    entity is the ROADMAP-3 straggler attribution primitive.
  * online detectors (:mod:`.detectors`) — EWMA drift + rolling-MAD
    outlier; a firing bumps ``timeline_anomalies_total{metric,kind}``,
    notes the flight recorder (one throttled dump per episode), and
    is visible to :class:`~..elastic.controller.ElasticController` as
    scale/replace pressure alongside SLO breaches.

Surfaces: the TelemetryServer ``timeline`` path serves
:meth:`TimelineRecorder.payload` live (``psctl watch`` /
``psctl timeline``); ``run_scenario``/``SoakRunner`` record timelines
into ``results/<platform>/soak_timeline.{md,json}`` (linted by
``tools/check_metric_lines.py --timeline``); the run report grows a
timeline section.  ``docs/observability.md`` documents the plane.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .flightrec import get_recorder
from .registry import MetricsRegistry, _label_key, get_registry


def percentile_from_counts(bounds, counts, q: float) -> float:
    """The registry histogram's in-bin interpolation
    (:meth:`~.registry.Histogram.percentile`) applied to an arbitrary
    bucket-count vector — typically a DELTA window between two polls.
    ``counts`` is non-cumulative with the overflow bin last; the
    overflow bin clamps to the largest finite boundary."""
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = q / 100.0 * total
    seen = 0.0
    for i, c in enumerate(counts):
        if seen + c >= rank and c > 0:
            if i == len(bounds):
                return bounds[-1]
            lo = 0.0 if i == 0 else bounds[i - 1]
            frac = (rank - seen) / c
            return lo + (bounds[i] - lo) * min(1.0, max(0.0, frac))
        seen += c
    return bounds[-1]


def _median(values: List[float]) -> float:
    s = sorted(values)
    n = len(s)
    if n == 0:
        raise ValueError("median of empty sequence")
    mid = n // 2
    if n % 2:
        return s[mid]
    return 0.5 * (s[mid - 1] + s[mid])


class SkewTracker:
    """Windowed per-entity medians over ONE metric's timeline series —
    the straggler attribution primitive.

    ``observe()`` is fed every appended point by the recorder; points
    whose labels carry ``entity_label`` accumulate into a bounded
    per-entity window.  ``evaluate()`` (once per sample tick) computes
    each entity's median, the median-of-medians baseline, and each
    entity's ratio against it; ratios publish as
    ``skew_ratio{metric=,entity=}`` gauges and the max-ratio entity is
    flagged once past ``ratio_threshold`` — "shard 0 is 8× the fleet
    median" is one gauge read, not a log dive.

    Unlike the drift detectors, this needs NO pre-fault baseline: the
    entities are each other's control group, so a straggler that is
    slow from its very first window still attributes.
    """

    def __init__(
        self,
        metric: str,
        *,
        entity_label: str,
        field: Optional[str] = None,
        window: int = 32,
        min_points: int = 3,
        ratio_threshold: float = 2.0,
        warmup_evals: int = 0,
        registry: Optional[MetricsRegistry] = None,
        history: int = 1024,
    ):
        if window < 1 or min_points < 1:
            raise ValueError(
                f"window={window}, min_points={min_points}: both >= 1"
            )
        if ratio_threshold <= 1.0:
            raise ValueError(
                f"ratio_threshold={ratio_threshold}: must be > 1 (1.0 "
                f"would flag a perfectly balanced fleet)"
            )
        self.metric = metric
        self.entity_label = entity_label
        self.field = field
        self.window = int(window)
        self.min_points = int(min_points)
        self.ratio_threshold = float(ratio_threshold)
        # the first windows after process start measure connection
        # setup, not steady-state service time — suppress flagging
        # (never the published ratios) until this many verdicts passed
        self.warmup_evals = int(warmup_evals)
        self._evals = 0
        self.registry = registry
        self._per_entity: Dict[str, deque] = {}
        self._gauges: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self.history: deque = deque(maxlen=int(history))
        self.last: Optional[Dict[str, Any]] = None

    def observe(self, name: str, labels: Dict[str, str], field: str,
                value: float, ts: float) -> None:
        if name != self.metric:
            return
        if self.field is not None and field != self.field:
            return
        entity = labels.get(self.entity_label)
        if entity is None:
            return
        with self._lock:
            ring = self._per_entity.get(entity)
            if ring is None:
                ring = deque(maxlen=self.window)
                self._per_entity[entity] = ring
            ring.append(float(value))

    def evaluate(self, now: Optional[float] = None) -> Optional[dict]:
        """One attribution pass: per-entity medians → ratios → gauges.
        Returns the verdict dict (also kept as ``.last`` and appended
        to ``.history``), or None when fewer than two entities have
        enough points to compare."""
        with self._lock:
            medians = {
                e: _median(list(ring))
                for e, ring in self._per_entity.items()
                if len(ring) >= self.min_points
            }
        if len(medians) < 2:
            return None
        baseline = _median(list(medians.values()))
        floor = max(abs(baseline), 1e-12)
        ratios = {e: m / floor for e, m in medians.items()}
        if self.registry is not None:
            for e, r in ratios.items():
                g = self._gauges.get(e)
                if g is None:
                    g = self.registry.gauge(
                        "skew_ratio", component="timeline",
                        metric=self.metric, entity=e,
                    )
                    self._gauges[e] = g
                g.set(r)
        top = max(ratios, key=lambda e: ratios[e])
        self._evals += 1
        verdict = {
            "ts": round(now if now is not None else time.time(), 6),
            "metric": self.metric,
            "entity_label": self.entity_label,
            "entity": top,
            "ratio": round(ratios[top], 4),
            "flagged": (
                ratios[top] >= self.ratio_threshold
                and self._evals > self.warmup_evals
            ),
            "medians": {e: round(m, 6) for e, m in medians.items()},
        }
        self.last = verdict
        self.history.append(verdict)
        return verdict

    def snapshot(self) -> dict:
        return {
            "metric": self.metric,
            "entity_label": self.entity_label,
            "field": self.field,
            "ratio_threshold": self.ratio_threshold,
            "warmup_evals": self.warmup_evals,
            "last": self.last,
        }


class TimelineRecorder:
    """Background sampler: registry instruments → bounded ring series.

    ``start()`` launches the poll thread (``interval_s`` cadence);
    ``sample()`` is one synchronous poll (tests and the soak/nemesis
    harnesses drive it directly when they want deterministic ticks).
    ``payload()`` is the JSON-shaped window every surface serves: the
    TelemetryServer ``timeline`` path, the soak artifact, the run
    report.  ``mark(label, **fields)`` stamps an operational event
    (fault injected, arm started) onto the same time axis, which is
    what lets the lint and the A/B harness cross-reference anomaly
    firings against fault onset.

    Per-label-set identity means cardinality is bounded only by the
    registry's; ``max_series`` caps the fan-out (drops counted, never
    silent) so a runaway label can't eat the process.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        *,
        interval_s: float = 0.25,
        capacity: int = 2048,
        max_series: int = 512,
        detectors: Optional[Iterable] = None,
        skew: Optional[Iterable[SkewTracker]] = None,
        include: Optional[Callable[[str], bool]] = None,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s={interval_s}: must be > 0")
        if capacity < 2 or max_series < 1:
            raise ValueError(
                f"capacity={capacity}, max_series={max_series}: need "
                f"capacity >= 2 and max_series >= 1"
            )
        self.registry = registry if registry is not None else get_registry()
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self.max_series = int(max_series)
        self.detectors = list(detectors) if detectors else []
        self.skew = list(skew) if skew else []
        for tracker in self.skew:
            if tracker.registry is None:
                tracker.registry = self.registry
        self._include = include
        self._lock = threading.Lock()
        self._series: Dict[
            Tuple[str, Tuple[Tuple[str, str], ...], str], deque
        ] = {}
        self._prev_counter: Dict[int, Tuple[float, float]] = {}
        self._prev_buckets: Dict[int, List[int]] = {}
        self._anomalies: List[dict] = []
        self._marks: List[dict] = []
        self._samples = 0
        self._dropped_series = 0
        self.started_at: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- one poll ----------------------------------------------------------
    def sample(self) -> int:
        """Poll every instrument once; returns the number of points
        appended this tick.  Fires detectors/skew inline on each new
        point (the detectors see exactly what the rings record)."""
        now = time.time()
        mono = time.monotonic()
        fired: List[dict] = []
        appended = 0
        for inst in self.registry.instruments():
            if self._include is not None and not self._include(inst.name):
                continue
            if inst.kind == "counter":
                v = float(inst.value)
                prev = self._prev_counter.get(id(inst))
                self._prev_counter[id(inst)] = (v, mono)
                if prev is None:
                    continue
                pv, pt = prev
                dt = mono - pt
                if dt <= 0:
                    continue
                appended += self._append(
                    inst, "rate", now, max(0.0, (v - pv) / dt), fired
                )
            elif inst.kind == "gauge":
                v = inst.value
                if v is None:
                    continue  # unreadable probe = gap, not a zero
                appended += self._append(
                    inst, "value", now, float(v), fired
                )
            elif inst.kind == "histogram":
                counts = inst.bucket_counts()
                prev_c = self._prev_buckets.get(
                    id(inst), [0] * len(counts)
                )
                self._prev_buckets[id(inst)] = counts
                delta = [c - p for c, p in zip(counts, prev_c)]
                if sum(delta) <= 0:
                    continue  # no traffic this window = gap
                bounds = inst.bounds
                appended += self._append(
                    inst, "p50", now,
                    percentile_from_counts(bounds, delta, 50.0), fired,
                )
                appended += self._append(
                    inst, "p99", now,
                    percentile_from_counts(bounds, delta, 99.0), fired,
                )
        for tracker in self.skew:
            tracker.evaluate(now)
        self._samples += 1
        for anom in fired:  # file IO (flightrec dump) outside the walk
            self._on_anomaly(anom)
        return appended

    def _append(self, inst, field: str, ts: float, value: float,
                fired: List[dict]) -> int:
        key = (inst.name, _label_key(inst.labels), field)
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                if len(self._series) >= self.max_series:
                    self._dropped_series += 1
                    return 0
                ring = deque(maxlen=self.capacity)
                self._series[key] = ring
            ring.append((round(ts, 6), value))
        for tracker in self.skew:
            tracker.observe(inst.name, inst.labels, field, value, ts)
        for det in self.detectors:
            anom = det.observe(inst.name, inst.labels, field, value, ts)
            if anom is not None:
                fired.append(anom)
        return 1

    def _on_anomaly(self, anom: dict) -> None:
        self._anomalies.append(anom)
        self.registry.counter(
            "timeline_anomalies_total", component="timeline",
            metric=anom["metric"], kind=anom["kind"],
        ).inc()
        rec = get_recorder()
        if rec is not None:
            rec.note(
                "timeline_anomaly", metric=anom["metric"],
                kind=anom["kind"], field=anom.get("field"),
                value=anom.get("value"), score=anom.get("score"),
            )
            # throttled per (kind, metric): a storm of firings on one
            # series produces ONE blackbox artifact per episode, not
            # one per sample (flightrec min_dump_interval_s)
            rec.dump(f"timeline_{anom['kind']}_{anom['metric']}")

    # -- the event axis ----------------------------------------------------
    def mark(self, label: str, **fields: Any) -> dict:
        """Stamp an operational event (fault injected, phase change)
        onto the timeline's own time axis — the cross-reference anchor
        the ``--timeline`` lint and the detection A/B measure against."""
        event = {"ts": round(time.time(), 6), "label": str(label)}
        event.update(fields)
        self._marks.append(event)
        return event

    # -- reads -------------------------------------------------------------
    def anomalies(self) -> List[dict]:
        """Append-only anomaly ledger (the elastic controller keeps a
        cursor into this to turn NEW firings into scale pressure)."""
        return list(self._anomalies)

    def anomalies_since(self, cursor: int) -> Tuple[List[dict], int]:
        """The ledger entries appended since ``cursor`` plus the new
        cursor — the one-liner both the elastic and adaptive
        controllers use so neither re-consumes an old firing."""
        ledger = list(self._anomalies)
        return ledger[cursor:], len(ledger)

    def series(self, metric: Optional[str] = None) -> List[dict]:
        with self._lock:
            items = list(self._series.items())
        out = []
        for (name, labels, field), ring in items:
            if metric is not None and name != metric:
                continue
            out.append({
                "metric": name,
                "labels": dict(labels),
                "field": field,
                "points": [[ts, v] for ts, v in ring],
            })
        out.sort(key=lambda s: (s["metric"], s["field"],
                                sorted(s["labels"].items())))
        return out

    def payload(self, metric: Optional[str] = None) -> dict:
        """The timeline window in its one wire/artifact shape (the
        TelemetryServer ``timeline`` path, the soak artifact's per-arm
        body, the ``--timeline`` lint's subject)."""
        return {
            "kind": "timeline",
            "run_id": self.registry.run_id,
            "interval_s": self.interval_s,
            "capacity": self.capacity,
            "samples": self._samples,
            "started_at": self.started_at,
            "dropped_series": self._dropped_series,
            "series": self.series(metric),
            "marks": list(self._marks),
            "anomalies": list(self._anomalies),
            "skew": [t.snapshot() for t in self.skew],
        }

    def summary(self) -> List[dict]:
        """Per-series min/max/last rows (the run-report section)."""
        rows = []
        for s in self.series():
            vals = [v for _, v in s["points"]]
            if not vals:
                continue
            rows.append({
                "metric": s["metric"],
                "labels": s["labels"],
                "field": s["field"],
                "points": len(vals),
                "min": min(vals),
                "max": max(vals),
                "last": vals[-1],
            })
        return rows

    # -- the loop ----------------------------------------------------------
    def start(self) -> "TimelineRecorder":
        if self._thread is None or not self._thread.is_alive():
            self.started_at = time.time()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="timeline-recorder", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — the sampler must survive
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self) -> "TimelineRecorder":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- the process-wide default -------------------------------------------------
# Like the flight recorder: NOT created lazily.  No recorder installed
# means the `timeline` telemetry path answers null and no thread runs —
# library users opt in, they never discover a background sampler.
_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[TimelineRecorder] = None


def get_timeline() -> Optional[TimelineRecorder]:
    with _DEFAULT_LOCK:
        return _DEFAULT


def set_timeline(
    recorder: Optional[TimelineRecorder],
) -> Optional[TimelineRecorder]:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = recorder
    return recorder


__all__ = [
    "TimelineRecorder",
    "SkewTracker",
    "percentile_from_counts",
    "get_timeline",
    "set_timeline",
]
