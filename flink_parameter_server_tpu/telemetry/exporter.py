"""Live metrics surface: Prometheus-text exposition + a tiny TCP
endpoint.

Symmetric to ``serving/server.py``: the serve path answers queries over
a newline-delimited TCP socket, the telemetry path answers scrapes over
one.  The server speaks enough HTTP/1.0 for ``curl`` and a Prometheus
scrape job (``GET /metrics``, ``GET /healthz``), and also answers the
bare line protocol (``metrics\\n`` / ``healthz\\n``) so a test or a
shell one-liner (``nc``) needs no HTTP client.  One thread per
connection, one response per request, connection closed after — a
scrape surface, not a serving plane.

Elastic-aggregation work (arXiv:2204.03211, PAPERS.md) assumes exactly
this: a queryable live parameter-service metrics surface that external
controllers poll to make scaling decisions.
"""
from __future__ import annotations

import json
import socket
from typing import List, Optional

from ..utils.net import LineServer
from .registry import Histogram, MetricsRegistry, get_registry

# metric names go out namespaced; label values get minimal escaping
_PREFIX = "fps_"


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n"
    )


def _fmt_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    body = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(merged.items())
    )
    return "{" + body + "}"


def _fmt_value(v) -> str:
    if v is None:
        return "NaN"  # Prometheus-legal marker for an unreadable gauge
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(
    registry: Optional[MetricsRegistry] = None,
    *,
    collectors=None,
    include_hot_keys: bool = True,
) -> str:
    """Render the registry in Prometheus exposition format (0.0.4).

    Counters get the conventional ``_total`` suffix (unless already
    named that way); histograms expand to cumulative ``_bucket{le=}``
    series plus ``_sum``/``_count``.  The merged hot-key sketch
    (telemetry/hotkeys.py) is appended as ``fps_hot_key_traffic``
    gauge lines whenever any sketch is registered; ``collectors`` are
    extra zero-arg callables returning exposition lines."""
    reg = registry if registry is not None else get_registry()
    by_name: dict = {}
    for inst in reg.instruments():
        by_name.setdefault(inst.name, []).append(inst)
    lines: List[str] = []
    for name in sorted(by_name):
        insts = by_name[name]
        kind = insts[0].kind
        out_name = _PREFIX + name
        if kind == "counter" and not out_name.endswith("_total"):
            out_name += "_total"
        lines.append(f"# TYPE {out_name} {kind}")
        for inst in insts:
            if isinstance(inst, Histogram):
                counts = inst.bucket_counts()
                cum = 0
                for bound, c in zip(inst.bounds, counts):
                    cum += c
                    lines.append(
                        f"{out_name}_bucket"
                        f"{_fmt_labels(inst.labels, {'le': repr(float(bound))})}"
                        f" {cum}"
                    )
                cum += counts[-1]
                lines.append(
                    f"{out_name}_bucket"
                    f"{_fmt_labels(inst.labels, {'le': '+Inf'})} {cum}"
                )
                lines.append(
                    f"{out_name}_sum{_fmt_labels(inst.labels)} "
                    f"{_fmt_value(inst.sum)}"
                )
                lines.append(
                    f"{out_name}_count{_fmt_labels(inst.labels)} "
                    f"{inst.count}"
                )
            else:
                lines.append(
                    f"{out_name}{_fmt_labels(inst.labels)} "
                    f"{_fmt_value(inst.value)}"
                )
    if include_hot_keys:
        from .hotkeys import get_aggregator

        agg = get_aggregator()
        if agg.labels():
            lines.extend(agg.exposition(prefix=_PREFIX))
    for coll in collectors or ():
        try:
            lines.extend(coll())
        except Exception:  # a broken collector must not kill a scrape
            pass
    return "\n".join(lines) + "\n"


class TelemetryServer(LineServer):
    """``GET /metrics`` (Prometheus text) + ``GET /healthz`` (JSON) over
    TCP, serving LIVE registry values while training runs.

    ``port=0`` binds an ephemeral port (read it back from ``.port``).
    ``health`` is an optional ``resilience.HealthMonitor``: with one
    attached, ``/healthz`` reports per-component heartbeat ages and
    degrades ``status`` to ``"stalled"`` past ``stall_after_s`` — the
    watchdog's view, scrapeable before the watchdog fires.

    Socket plumbing comes from :class:`~..utils.net.LineServer`; the
    scrape endpoint overrides :meth:`handle_connection` whole because
    its protocol is one-shot (one answer, HTTP or bare, then close),
    not line-per-request.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        health=None,
        stall_after_s: Optional[float] = None,
        max_request_bytes: int = 8192,
        collectors=None,
        profiler=None,
    ):
        super().__init__(host, port, name="telemetry")
        self.registry = registry if registry is not None else get_registry()
        self.health = health
        self.stall_after_s = stall_after_s
        self.max_request_bytes = int(max_request_bytes)
        self.collectors = list(collectors) if collectors else []
        # the profiler whose latency budget the `budget` path serves
        # (None = the process default, resolved per request so a late
        # set_profiler() is picked up)
        self.profiler = profiler

    def start(self) -> "TelemetryServer":
        super().start()
        return self

    # -- request handling --------------------------------------------------
    def handle_connection(self, conn: socket.socket) -> None:
        conn.settimeout(5.0)
        buf = b""
        # one request line is enough; drain headers best-effort so
        # an HTTP client's request doesn't RST on early close
        while b"\n" not in buf and len(buf) < self.max_request_bytes:
            chunk = conn.recv(4096)
            if not chunk:
                return
            buf += chunk
        first = buf.split(b"\n", 1)[0].decode(
            "utf-8", "replace"
        ).strip()
        http = first.upper().startswith(("GET ", "HEAD "))
        head_only = first.upper().startswith("HEAD ")
        path = first.split()[1] if http and len(
            first.split()
        ) >= 2 else first
        path = path.strip().lstrip("/").lower() or "metrics"
        if path.startswith("metrics"):
            body = prometheus_text(
                self.registry, collectors=self.collectors
            )
            # the Prometheus text exposition content type, verbatim —
            # scrapers key the parser off version=0.0.4
            ctype = "text/plain; version=0.0.4; charset=utf-8"
            status = "200 OK"
        elif path.startswith("healthz"):
            body = json.dumps(self._healthz()) + "\n"
            ctype = "application/json"
            status = "200 OK"
        elif path.startswith("hotkeys"):
            from .hotkeys import get_aggregator

            body = json.dumps(
                {"hot_keys": get_aggregator().snapshot()}
            ) + "\n"
            ctype = "application/json"
            status = "200 OK"
        elif path.startswith("hot"):
            # the live hot-key TABLE (psctl hot): sketch top-K joined
            # with the client-edge lease-cache state — which hot keys
            # are currently leased somewhere, how old, how often hit
            body = json.dumps({"hot": self._hot_table()}) + "\n"
            ctype = "application/json"
            status = "200 OK"
        elif path.startswith("budget"):
            # the latency-budget profiler's per-verb phase breakdown
            # (telemetry/profiler.py) — the `psctl budget` answer
            from .profiler import get_profiler

            prof = (
                self.profiler if self.profiler is not None
                else get_profiler()
            )
            body = json.dumps(
                {"budgets": prof.budget_report(),
                 "run_id": self.registry.run_id}
            ) + "\n"
            ctype = "application/json"
            status = "200 OK"
        elif path.startswith("conns"):
            # this endpoint's own live connection ledger (the shard
            # servers answer their own over the `conns` wire verb)
            body = json.dumps({"conns": self.conn_table()}) + "\n"
            ctype = "application/json"
            status = "200 OK"
        elif path.startswith("timeline"):
            # the timeline recorder's series window (telemetry/
            # timeline.py): rates/values/windowed-percentiles per
            # instrument plus marks, anomalies and skew verdicts —
            # `psctl watch`/`psctl timeline` read this.  No recorder
            # installed answers null (the opt-in contract; same shape
            # as the flight recorder's)
            from .timeline import get_timeline

            tl = get_timeline()
            body = json.dumps(
                {"timeline": (
                    tl.payload() if tl is not None else None
                ),
                 "run_id": self.registry.run_id}
            ) + "\n"
            ctype = "application/json"
            status = "200 OK"
        elif path.startswith("adaptive"):
            # the adaptive runtime's live decision surface (adaptive/
            # controller.py): per-worker effective bounds + skew
            # ratios, hedged-push wins, rebalance moves, the decision
            # ring — `psctl adaptive` renders this.  No runtime
            # installed answers null (opt-in, like `timeline`)
            from ..adaptive.controller import get_adaptive_runtime

            rt = get_adaptive_runtime()
            body = json.dumps(
                {"adaptive": (
                    rt.payload() if rt is not None else None
                ),
                 "run_id": self.registry.run_id}
            ) + "\n"
            ctype = "application/json"
            status = "200 OK"
        elif path.startswith("tiers"):
            # the two-tier store's per-shard snapshot (tierstore/
            # metrics.py): resident/cold/pinned row counts, slab
            # bytes, hit/miss/promote/demote/spill counters per
            # registered tiered store — `psctl tiers` renders this.
            # No tiered shard registered answers null (the cluster is
            # not running store_backend="tiered")
            from ..tierstore.metrics import tiers_snapshot

            body = json.dumps(
                {"tiers": tiers_snapshot(),
                 "run_id": self.registry.run_id}
            ) + "\n"
            ctype = "application/json"
            status = "200 OK"
        elif path.startswith("workloads"):
            # the live per-workload rate table (workloads/runtime.py):
            # cumulative update/prediction/query counters + query
            # latency percentiles per registered workload — `psctl
            # workloads` diffs two scrapes into rates
            from ..workloads.runtime import workload_table

            body = json.dumps(
                {"workloads": workload_table(self.registry),
                 "run_id": self.registry.run_id}
            ) + "\n"
            ctype = "application/json"
            status = "200 OK"
        else:
            body = (
                f"unknown path {path!r} "
                f"(metrics|healthz|hotkeys|hot|budget|conns|"
                f"timeline|adaptive|tiers|workloads)\n"
            )
            ctype = "text/plain; charset=utf-8"
            status = "404 Not Found"
        payload = body.encode("utf-8")
        # wire accounting (utils/net.py): one frame each way per
        # scrape, attributed to the path as the verb
        verb = path.split("?", 1)[0][:16] or "metrics"
        if not verb.replace("_", "").isalnum():
            verb = "other"
        stats = self._stats_for(conn)
        stats.last_verb = verb
        stats.bytes_in += len(buf)
        stats.frames_in += 1
        self.meter.count("in", verb, len(buf))
        if http:
            head = (
                f"HTTP/1.0 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n"
            ).encode("ascii")
            # HEAD answers headers (with the GET body's exact
            # Content-Length) and no body — RFC 9110 §9.3.2
            sent = head if head_only else head + payload
            conn.sendall(sent)
        else:
            sent = payload
            conn.sendall(sent)
        stats.bytes_out += len(sent)
        stats.frames_out += 1
        self.meter.count("out", verb, len(sent))

    def _hot_table(self, n: int = 16) -> dict:
        """The ``hot`` path's payload: the merged sketch top-K
        (telemetry/hotkeys.py) joined per key with the registered
        client-edge caches' lease state (hotcache/cache.py) — the one
        view that answers "who is hot, and is the tier absorbing
        them?" live."""
        from ..hotcache.cache import cache_snapshots
        from .hotkeys import get_aggregator

        agg = get_aggregator()
        snaps = cache_snapshots()
        # key -> the freshest lease entry across every cache
        by_key: dict = {}
        for label, snap in snaps.items():
            for entry in snap.get("keys", ()):
                cur = by_key.get(entry["key"])
                if cur is None or entry["age"] < cur["age"]:
                    by_key[entry["key"]] = {
                        "age": entry["age"],
                        "hits": entry["hits"],
                        "cache": label,
                    }
        top = []
        for rank, item in enumerate(agg.top_k(n)):
            row = {
                "rank": rank,
                "key": item["key"],
                "count": item["count"],
                "err": item["err"],
                "leased": item["key"] in by_key,
            }
            row.update(by_key.get(item["key"], {}))
            top.append(row)
        return {
            "top": top,
            "total_observed": agg.total(),
            "error_bound": agg.error_bound(),
            "caches": {
                label: {
                    k: snap[k]
                    for k in ("hits", "misses", "hit_rate", "entries",
                              "revocations", "stale_rejects", "bound")
                }
                for label, snap in snaps.items()
            },
        }

    def _healthz(self) -> dict:
        out = {"status": "ok", "run_id": self.registry.run_id}
        if self.health is not None:
            ages = self.health.ages()
            out["heartbeat_age_s"] = {
                c: round(a, 3) for c, a in sorted(ages.items())
            }
            if self.stall_after_s is not None:
                stalled = self.health.stalled(self.stall_after_s)
                if stalled:
                    out["status"] = "stalled"
                    out["stalled"] = stalled
        return out


def scrape(host: str, port: int, path: str = "metrics",
           timeout: float = 5.0) -> str:
    """One-shot line-protocol scrape (test/shell helper): send the bare
    path, read to EOF, return the body."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.sendall(path.strip().encode("utf-8") + b"\n")
        chunks = []
        while True:
            c = s.recv(1 << 16)
            if not c:
                break
            chunks.append(c)
    return b"".join(chunks).decode("utf-8", "replace")


__all__ = ["prometheus_text", "TelemetryServer", "scrape"]
