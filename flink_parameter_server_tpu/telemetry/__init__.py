"""Unified telemetry plane (docs/observability.md).

One registry, one tracer, one live endpoint, one end-of-run report —
the seam every subsystem (train / serving / ingest / recovery) measures
through, and the seam every later perf PR is judged through:

  * :mod:`.registry` — typed instruments (Counter, Gauge, Histogram)
    with ``component=`` labels; process-wide default via
    :func:`get_registry`; JSON-lines ``emit`` with shared ts/run_id.
  * :mod:`.spans` — nestable wall-clock spans, ring-buffered, Chrome
    trace-event export; the HOST-side complement of
    ``training/tracing.py``'s device-side ``jax.named_scope``.
  * :mod:`.exporter` — Prometheus-text rendering + the TCP
    ``/metrics`` / ``/healthz`` endpoint (live during training).
  * :mod:`.report` — ``results/<platform>/run_report.{md,json}``.
  * :mod:`.distributed` — cross-process trace propagation
    (``t=<trace>:<span>`` wire tokens) + the clock-aligning
    :class:`TraceCollector` that merges per-process rings into one
    Chrome/Perfetto trace.
  * :mod:`.hotkeys` — count-min + space-saving hot-key sketches over
    pull/push/serving key traffic, merged across shards.
  * :mod:`.flightrec` — the bounded blackbox ring dumped to
    ``results/<platform>/flightrec_<reason>.json`` on crash, stall,
    or stale-epoch storm.
  * :mod:`.slo` — declarative objectives evaluated as multi-window
    burn rates, consumable by the elastic controller.
  * :mod:`.timeline` — the time axis: a background sampler polling
    the registry into bounded per-instrument ring series (counters as
    rates, gauges as values, histograms as windowed p50/p99), plus
    the :class:`SkewTracker` per-entity straggler attribution.
  * :mod:`.detectors` — online anomaly detectors (EWMA drift +
    rolling-MAD outlier) riding the timeline sample loop; firings
    count, note the flight recorder, and pressure the elastic
    controller.
  * :mod:`.profiler` — the latency-budget profiler: per-phase cost
    attribution of every cluster round (client serialize → wire →
    queue wait → WAL → scatter → serialize → parse), plus a sampling
    :class:`StackSampler` with folded-stack/flamegraph export.
"""
from .distributed import (
    TraceCollector,
    TraceContext,
    format_token,
    new_trace,
    parse_token,
)
from .exporter import TelemetryServer, prometheus_text, scrape
from .flightrec import FlightRecorder, StormDetector, get_recorder, set_recorder
from .hotkeys import (
    HotKeyAggregator,
    HotKeySketch,
    SpaceSavingTopK,
    get_aggregator,
    set_aggregator,
)
from .profiler import (
    PHASES,
    PhaseProfiler,
    StackSampler,
    get_profiler,
    set_profiler,
)
from .slo import SLOEngine, SLOSpec, default_slos
from .registry import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_run_id,
    get_registry,
    json_line,
    set_registry,
)
from .report import build_run_report, render_markdown, write_run_report
from .spans import SpanTracer, get_tracer, set_tracer, span
from .detectors import EWMADriftDetector, RollingMADDetector
from .timeline import (
    SkewTracker,
    TimelineRecorder,
    get_timeline,
    percentile_from_counts,
    set_timeline,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "default_run_id",
    "json_line",
    "get_registry",
    "set_registry",
    "SpanTracer",
    "get_tracer",
    "set_tracer",
    "span",
    "TelemetryServer",
    "prometheus_text",
    "scrape",
    "build_run_report",
    "render_markdown",
    "write_run_report",
    "TraceCollector",
    "TraceContext",
    "format_token",
    "new_trace",
    "parse_token",
    "FlightRecorder",
    "StormDetector",
    "get_recorder",
    "set_recorder",
    "HotKeyAggregator",
    "HotKeySketch",
    "SpaceSavingTopK",
    "get_aggregator",
    "set_aggregator",
    "SLOEngine",
    "SLOSpec",
    "default_slos",
    "PHASES",
    "PhaseProfiler",
    "StackSampler",
    "get_profiler",
    "set_profiler",
    "TimelineRecorder",
    "SkewTracker",
    "percentile_from_counts",
    "get_timeline",
    "set_timeline",
    "EWMADriftDetector",
    "RollingMADDetector",
]
