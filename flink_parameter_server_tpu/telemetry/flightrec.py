"""Flight recorder — a bounded blackbox for post-mortems.

A live ``/metrics`` scrape answers "what is happening"; it answers
nothing once the process is wedged or dead.  The flight recorder is
the other half: every process keeps a bounded ring of recent
OPERATIONAL events (WAL/epoch flips, migrations, restarts, stalls,
storms — noted explicitly via :meth:`FlightRecorder.note`), and on a
trigger dumps that ring TOGETHER with the span-tracer tail and a full
registry snapshot to ``results/<platform>/flightrec_<reason>.json`` —
so the post-mortem starts from a file, not from hoping someone was
scraping at 3 a.m.

Triggers (wired across the repo, each falls back to the process-wide
recorder installed via :func:`set_recorder` — no recorder installed
means no files written, ever):

  * **stall watchdog** — :class:`~..resilience.health.StallWatchdog`
    dumps once per stall episode (``flightrec_stall_<component>``);
  * **crash** — :class:`~..resilience.recovery.RecoveringDriver`
    dumps before each supervised restart
    (``flightrec_crash_<failure_class>``);
  * **stale-epoch storm** — :class:`~..cluster.client.ClusterClient`
    dumps when membership-refresh retries exceed the storm threshold
    inside the window (``flightrec_stale_epoch_storm``) — the
    signature of a flip that clients cannot converge on.

Dumps are throttled per reason (``min_dump_interval_s``) so a storm
produces one artifact, not one per retry.  The dump format is linted
by ``tools/check_metric_lines.py --flightrec`` (valid JSON object,
``reason``/``pid``/``run_id``/``events`` present, every event carries
a numeric ``ts``).
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .registry import MetricsRegistry, _finite, default_run_id
from .spans import SpanTracer


class StormDetector:
    """Edge-triggered rate trip: ``note()`` returns True exactly when
    the noted-event count inside ``window_s`` first crosses
    ``threshold`` (then re-arms only after the window quiets down) —
    the stale-epoch-storm trigger, reusable for any event flood."""

    def __init__(
        self,
        threshold: int = 25,
        window_s: float = 5.0,
        clock=time.monotonic,
    ):
        if threshold < 1 or window_s <= 0:
            raise ValueError(
                f"threshold={threshold}, window_s={window_s}: need "
                f"threshold >= 1 and window_s > 0"
            )
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._times: deque = deque()
        self._tripped = False
        self.storms = 0

    def note(self) -> bool:
        now = self._clock()
        with self._lock:
            self._times.append(now)
            cutoff = now - self.window_s
            while self._times and self._times[0] < cutoff:
                self._times.popleft()
            if len(self._times) >= self.threshold:
                if self._tripped:
                    return False
                self._tripped = True
                self.storms += 1
                return True
            self._tripped = False
            return False


class FlightRecorder:
    """Bounded event ring + the dump path.

    ``note(kind, **fields)`` is the hot-path API: one dict appended to
    a deque under a lock — cheap enough for epoch flips, restarts and
    stall events (NOT per-push; per-request traffic belongs in the
    registry/sketches, the recorder keeps the OPERATIONAL timeline).

    ``dump(reason)`` assembles the blackbox: the event ring, the last
    ``span_tail`` spans of ``tracer`` (when attached), and a full
    snapshot of ``registry``; writes
    ``results/<platform>/flightrec_<reason>.json`` and returns the
    path (``None`` when throttled)."""

    def __init__(
        self,
        capacity: int = 1024,
        *,
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[SpanTracer] = None,
        span_tail: int = 256,
        min_dump_interval_s: float = 5.0,
        results_dir: Optional[str] = None,
        platform: Optional[str] = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity={capacity}: must be >= 1")
        self.registry = registry
        self.tracer = tracer
        self.span_tail = int(span_tail)
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.results_dir = results_dir
        self.platform = platform
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(capacity))
        self._last_dump: Dict[str, float] = {}
        self.dumps: List[str] = []

    # -- the ring ----------------------------------------------------------
    def note(self, kind: str, **fields: Any) -> None:
        event = {"ts": round(time.time(), 6), "kind": str(kind)}
        event.update(fields)
        with self._lock:
            self._events.append(event)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    # -- the dump ----------------------------------------------------------
    def _dir(self) -> str:
        if self.results_dir is not None:
            return self.results_dir
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        platform = self.platform
        if platform is None:
            try:
                import jax

                platform = jax.default_backend()
            except Exception:
                platform = "cpu"
        return os.path.join(repo, "results", platform)

    def dump(self, reason: str, *, force: bool = False) -> Optional[str]:
        reason_slug = re.sub(r"[^A-Za-z0-9_.-]+", "_", str(reason)) or "unknown"
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason_slug)
            if (
                not force
                and last is not None
                and now - last < self.min_dump_interval_s
            ):
                return None
            self._last_dump[reason_slug] = now
            events = list(self._events)
        doc: Dict[str, Any] = {
            "reason": str(reason),
            "pid": os.getpid(),
            "run_id": (
                self.registry.run_id if self.registry is not None
                else default_run_id()
            ),
            "generated_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "ts": round(time.time(), 3),
            "events": events,
        }
        if self.tracer is not None:
            doc["spans"] = self.tracer.spans()[-self.span_tail:]
        if self.registry is not None:
            doc["metrics"] = self.registry.snapshot()
        out_dir = self._dir()
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"flightrec_{reason_slug}.json")
        with open(path, "w") as f:
            json.dump(_finite(doc), f, indent=2)
            f.write("\n")
        with self._lock:
            self.dumps.append(path)
        return path


# -- the process-wide default -------------------------------------------------
# Deliberately NOT created lazily: with no recorder installed the
# trigger sites are no-ops, so unit tests and library users never find
# surprise artifacts under results/.
_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[FlightRecorder] = None


def get_recorder() -> Optional[FlightRecorder]:
    with _DEFAULT_LOCK:
        return _DEFAULT


def set_recorder(rec: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = rec
    return rec


__all__ = [
    "FlightRecorder",
    "StormDetector",
    "get_recorder",
    "set_recorder",
]
