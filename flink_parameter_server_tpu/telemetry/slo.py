"""SLO engine — declarative objectives evaluated as burn rates.

A threshold alert ("p99 > 25 ms") pages on one bad scrape and sleeps
through a slow bleed.  The SRE-standard fix is an ERROR BUDGET: an
objective like "99% of pulls complete within 25 ms" grants a 1% bad
budget, and the alert condition is the budget's BURN RATE — bad
fraction ÷ budget — evaluated over two windows at once: a short
window so a sudden regression fires fast, a long window so a
transient blip does not.  Burn 1.0 = exactly on budget; sustained
burn > ``page_burn`` on BOTH windows = a real breach.

:class:`SLOSpec` declares one objective over a registry metric:

  * ``kind="latency"`` — over a histogram (``metric``): an
    observation is GOOD when ≤ ``threshold``; good counts come from
    the bucket counts (linear interpolation inside the bucket holding
    the threshold, same approximation as
    :meth:`~.registry.Histogram.percentile`);
  * ``kind="bound"`` — over gauges (``metric``): each engine sample
    is one observation, GOOD when every matching gauge reads ≤
    ``threshold`` (staleness bounds, queue depths).

:class:`SLOEngine` samples the registry (explicitly via
:meth:`sample` or on its own poll thread), keeps a time-indexed ring
per objective, and exposes the verdicts three ways: probe gauges on
``/metrics`` (``fps_slo_burn_rate{slo=,window=}``,
``fps_slo_healthy{slo=}``), the ``slo`` section of ``run_report``,
and :meth:`verdicts` — which
:class:`~..elastic.controller.ElasticController` consumes as a
scale/replace pressure signal.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .registry import Histogram, MetricsRegistry, get_registry


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective: ``target`` fraction of observations
    of ``metric`` must be GOOD (≤ ``threshold``)."""

    name: str
    metric: str
    threshold: float
    target: float = 0.99
    kind: str = "latency"  # "latency" (histogram) | "bound" (gauge)

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"slo {self.name}: target={self.target} must be in (0, 1)"
            )
        if self.kind not in ("latency", "bound"):
            raise ValueError(
                f"slo {self.name}: kind={self.kind!r}: 'latency' | 'bound'"
            )


# -- the stock objectives the repo's planes ship with -------------------------
def pull_latency_slo(threshold_s: float = 0.025,
                     target: float = 0.99) -> SLOSpec:
    """Cluster pull RTT (``cluster_pull_rtt_seconds``) — the straggler
    signal the elastic controller already thresholds, as a budget."""
    return SLOSpec("pull_p99", "cluster_pull_rtt_seconds",
                   threshold_s, target)


def serving_latency_slo(threshold_s: float = 0.050,
                        target: float = 0.99) -> SLOSpec:
    return SLOSpec("serving_p99", "serving_latency_seconds",
                   threshold_s, target)


def staleness_slo(max_steps: float = 4.0, target: float = 0.95) -> SLOSpec:
    """SSP staleness spread stays within bound (gauge samples)."""
    return SLOSpec("staleness", "cluster_staleness_steps",
                   max_steps, target, kind="bound")


def recovery_time_slo(threshold_s: float = 5.0,
                      target: float = 0.9) -> SLOSpec:
    """Supervised recovery episodes (``recovery_duration_seconds``,
    observed by :class:`~..resilience.recovery.RecoveringDriver`)."""
    return SLOSpec("recovery_time", "recovery_duration_seconds",
                   threshold_s, target)


def failover_slo(threshold_s: float = 1.0,
                 target: float = 0.95) -> SLOSpec:
    """Replica-chain failovers (``replication_failover_seconds``,
    observed per promotion by replication/failover.py) — the
    sub-second availability budget docs/elastic.md promises: 95% of
    primary losses resolved by a follower flip within a second."""
    return SLOSpec("failover_time", "replication_failover_seconds",
                   threshold_s, target)


def default_slos() -> List[SLOSpec]:
    return [
        pull_latency_slo(),
        serving_latency_slo(),
        staleness_slo(),
        recovery_time_slo(),
        failover_slo(),
    ]


class SLOEngine:
    """Sample → ring → multi-window burn rates → verdicts.

    ``windows`` are (short, long) seconds; test-scale engines pass
    sub-second windows and drive :meth:`sample` with a fake clock.
    Verdicts per objective:

      * ``"ok"`` — short-window burn ≤ 1 (inside budget);
      * ``"burning"`` — short-window burn > 1 but not yet a
        sustained breach;
      * ``"breach"`` — burn > ``page_burn`` on BOTH windows (the
        page-worthy condition, and the controller's pressure signal);
      * ``"no_data"`` — nothing observed yet.
    """

    def __init__(
        self,
        slos: Optional[Sequence[SLOSpec]] = None,
        *,
        registry: Optional[MetricsRegistry] = None,
        windows: Tuple[float, float] = (60.0, 300.0),
        page_burn: float = 2.0,
        clock=time.monotonic,
        register_gauges: bool = True,
    ):
        short, long_ = float(windows[0]), float(windows[1])
        if not 0 < short < long_:
            raise ValueError(
                f"windows={windows}: need 0 < short < long"
            )
        self.slos = list(slos) if slos is not None else default_slos()
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.registry = registry if registry is not None else get_registry()
        self.windows = (short, long_)
        self.page_burn = float(page_burn)
        self._clock = clock
        self._lock = threading.Lock()
        # per slo: deque of (t, good_cumulative, total_cumulative)
        self._rings: Dict[str, deque] = {
            s.name: deque(maxlen=4096) for s in self.slos
        }
        # bound-kind objectives have no cumulative instrument to read —
        # each engine sample IS one observation, accumulated here
        self._bound_totals: Dict[str, list] = {
            s.name: [0.0, 0.0] for s in self.slos if s.kind == "bound"
        }
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if register_gauges:
            for spec in self.slos:
                for window in ("short", "long"):
                    self.registry.gauge(
                        "slo_burn_rate", component="slo", slo=spec.name,
                        window=window,
                        fn=lambda n=spec.name, w=window: self._burn(n, w),
                    )
                self.registry.gauge(
                    "slo_healthy", component="slo", slo=spec.name,
                    fn=lambda n=spec.name: (
                        1.0 if self.status(n)["verdict"] in ("ok", "no_data")
                        else 0.0
                    ),
                )

    # -- sampling ----------------------------------------------------------
    def _cumulative(self, spec: SLOSpec) -> Tuple[float, float]:
        """(good, total) cumulative observation counts for the spec —
        summed across every instrument sharing the metric name."""
        good = total = 0.0
        for inst in self.registry.instruments():
            if inst.name != spec.metric:
                continue
            if spec.kind == "latency":
                if not isinstance(inst, Histogram):
                    continue
                counts = inst.bucket_counts()
                bounds = inst.bounds
                t = float(sum(counts))
                g = 0.0
                lo = 0.0
                for b, c in zip(bounds, counts):
                    if b <= spec.threshold:
                        g += c
                    elif lo < spec.threshold:
                        # the bucket straddling the threshold: linear
                        # interpolation (the histogram's own percentile
                        # approximation, applied in reverse)
                        g += c * (spec.threshold - lo) / (b - lo)
                    lo = b
                total += t
                good += min(g, t)
            else:  # bound: gauges, one observation per engine sample
                v = inst.value
                if v is None:
                    continue
                total += 1.0
                if float(v) <= spec.threshold:
                    good += 1.0
        if spec.kind == "bound":
            # accumulate the point sample into the running totals (a
            # gauge read has no history of its own)
            acc = self._bound_totals[spec.name]
            acc[0] += good
            acc[1] += total
            return acc[0], acc[1]
        return good, total

    def sample(self) -> None:
        """One evaluation pass: append each objective's cumulative
        (good, total) to its ring, stamped with the engine clock."""
        now = self._clock()
        for spec in self.slos:
            good, total = self._cumulative(spec)
            with self._lock:
                self._rings[spec.name].append((now, good, total))

    # -- reads -------------------------------------------------------------
    def _window_delta(
        self, name: str, window_s: float
    ) -> Tuple[float, float]:
        """(bad, total) observed inside the trailing window."""
        with self._lock:
            ring = list(self._rings[name])
        if not ring:
            return 0.0, 0.0
        t_now, g_now, n_now = ring[-1]
        base = ring[0]
        for entry in ring:
            # oldest sample still inside the window; fall back to the
            # oldest sample we have (honest partial window at startup)
            if entry[0] >= t_now - window_s:
                base = entry
                break
        _t0, g0, n0 = base
        total = max(0.0, n_now - n0)
        bad = max(0.0, (n_now - g_now) - (n0 - g0))
        return bad, total

    def _burn(self, name: str, window: str) -> Optional[float]:
        spec = next((s for s in self.slos if s.name == name), None)
        if spec is None:
            return None
        w = self.windows[0] if window == "short" else self.windows[1]
        bad, total = self._window_delta(name, w)
        if total <= 0:
            return 0.0
        budget = 1.0 - spec.target
        return (bad / total) / budget

    def status(self, name: str) -> Dict[str, Any]:
        spec = next((s for s in self.slos if s.name == name), None)
        if spec is None:
            raise KeyError(f"no SLO named {name!r}")
        bad_s, total_s = self._window_delta(name, self.windows[0])
        bad_l, total_l = self._window_delta(name, self.windows[1])
        budget = 1.0 - spec.target
        burn_short = (bad_s / total_s) / budget if total_s > 0 else 0.0
        burn_long = (bad_l / total_l) / budget if total_l > 0 else 0.0
        if total_l <= 0 and total_s <= 0:
            verdict = "no_data"
        elif burn_short > self.page_burn and burn_long > self.page_burn:
            verdict = "breach"
        elif burn_short > 1.0:
            verdict = "burning"
        else:
            verdict = "ok"
        return {
            "slo": spec.name,
            "metric": spec.metric,
            "threshold": spec.threshold,
            "target": spec.target,
            "verdict": verdict,
            "burn_short": round(burn_short, 4),
            "burn_long": round(burn_long, 4),
            "window_total": total_s,
        }

    def verdicts(self) -> List[Dict[str, Any]]:
        return [self.status(s.name) for s in self.slos]

    def breached(self) -> List[str]:
        """Names of objectives currently in ``"breach"`` — the
        controller's pressure signal."""
        return [v["slo"] for v in self.verdicts() if v["verdict"] == "breach"]

    # -- the poll loop ------------------------------------------------------
    def start(self, interval_s: float = 1.0) -> "SLOEngine":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, args=(float(interval_s),),
                name="slo-engine", daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — the sampler must survive
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "SLOEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


__all__ = [
    "SLOEngine",
    "SLOSpec",
    "default_slos",
    "failover_slo",
    "pull_latency_slo",
    "recovery_time_slo",
    "serving_latency_slo",
    "staleness_slo",
]
