"""Lock-order witness — the runtime oracle behind fpsanalyze L001.

The static pass (``tools/fpsanalyze``) derives the lock-acquisition
graph from the AST; this module derives the SAME graph from live
execution, so the two cross-check each other the way the PR-7 latency
budget was checked against its span oracle: a cycle the static
analysis misses (dynamic dispatch, monkeypatching, a lock passed
through three layers) still trips the witness, and a static cycle that
never executes is visibly absent from the witnessed order.

Mechanics: a :class:`WitnessedLock` wraps a real ``threading.Lock`` /
``RLock``.  Each thread keeps its held-stack; acquiring ``B`` while
holding ``A`` records the edge ``A → B`` into one global partial
order.  If ``B ⇝ A`` already exists, that acquisition INVERTS the
established order — the classic deadlock precondition — and the
witness records it (or raises :class:`LockInversion` in strict mode).

Identity is the lock's **creation site** (``module.qualname:line``),
matching fpsanalyze's class-level lock identity: every instance of
``ParamShard._lock`` shares one node, so an inversion between two
shard instances' locks is still an inversion of the same order the
static rule reasons about.  Re-acquiring a name already held by the
current thread is treated as re-entrant (no edge, no inversion) — the
conservative choice for RLocks and for sibling instances from one
site; it can mask, never fabricate.

Opt-in and zero-cost when off: nothing in the package imports this
module on the hot path.  Tests wrap a workload with::

    from flink_parameter_server_tpu.telemetry import lockwitness

    with lockwitness.capture() as w:      # patches threading.Lock/RLock
        ...build shards/clients, run traffic...
    assert w.inversions == []             # the tier-1 oracle

``capture`` only wraps locks whose creating frame lives under the
package (stdlib/jax internals keep their real locks — wrapping a lock
that ``threading.Condition`` wants to ``_release_save`` mid-``wait``
needs the delegation below, and there is no reason to pay it for
foreign code).
"""
from __future__ import annotations

import contextlib
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "LockInversion",
    "LockWitness",
    "WitnessedLock",
    "capture",
]


class LockInversion(RuntimeError):
    """Strict-mode signal: this acquisition inverted the established
    lock order (a ``B ⇝ A`` path already exists while ``A`` is held
    and ``B`` is being acquired)."""


class WitnessedLock:
    """A threading.Lock/RLock wrapper that reports acquisitions to its
    witness.  Supports the ``Condition`` protocol by delegation when
    the inner lock does (``_release_save``/``_acquire_restore``/
    ``_is_owned``)."""

    def __init__(self, inner, name: str, witness: "LockWitness"):
        self._inner = inner
        self._name = name
        self._witness = witness

    # -- core protocol -----------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            inv = self._witness._on_acquire(self._name)
            if inv is not None and self._witness.raise_on_inversion:
                # release before raising: a raised acquisition must not
                # leave the lock wedged
                self._witness._on_release(self._name)
                self._inner.release()
                raise LockInversion(inv)
        return got

    def release(self):
        self._witness._on_release(self._name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # -- Condition-protocol delegation -------------------------------------
    def _release_save(self):
        self._witness._on_release_all(self._name)
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._witness._on_acquire(self._name, check=False)

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain Lock heuristic (mirrors threading.Condition's fallback)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<WitnessedLock {self._name} of {self._inner!r}>"


class LockWitness:
    """The global partial order + per-thread held stacks."""

    def __init__(self, raise_on_inversion: bool = False):
        self.raise_on_inversion = raise_on_inversion
        # real, unwrapped lock: the witness must never witness itself
        self._glock = threading._allocate_lock()
        self._edges: Dict[str, Set[str]] = {}
        self._tls = threading.local()
        self.inversions: List[dict] = []
        self.acquisitions = 0  # total witnessed acquires (liveness)

    # -- wrapping ----------------------------------------------------------
    def wrap(self, lock, name: str) -> WitnessedLock:
        return WitnessedLock(lock, name, self)

    def edges(self) -> Dict[str, Set[str]]:
        with self._glock:
            return {a: set(bs) for a, bs in self._edges.items()}

    # -- bookkeeping -------------------------------------------------------
    def _held(self) -> List[List]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st  # list of [name, count], innermost last

    def _path_exists(self, src: str, dst: str) -> bool:
        """True when src ⇝ dst in the recorded order (caller holds
        _glock)."""
        seen = {src}
        frontier = [src]
        while frontier:
            n = frontier.pop()
            if n == dst:
                return True
            for nxt in self._edges.get(n, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return False

    def _on_acquire(self, name: str,
                    check: bool = True) -> Optional[str]:
        stack = self._held()
        for entry in stack:
            if entry[0] == name:
                entry[1] += 1  # re-entrant (RLock / sibling instance)
                return None
        inversion: Optional[str] = None
        if check and stack:
            held_names = [e[0] for e in stack]
            with self._glock:
                self.acquisitions += 1
                for h in held_names:
                    if h == name:
                        continue
                    if self._path_exists(name, h):
                        inversion = (
                            f"lock-order inversion: acquiring "
                            f"{name!r} while holding {h!r}, but the "
                            f"witnessed order already has "
                            f"{name!r} ⇝ {h!r}"
                        )
                        self.inversions.append({
                            "acquiring": name,
                            "holding": h,
                            "thread": threading.current_thread().name,
                        })
                    else:
                        self._edges.setdefault(h, set()).add(name)
        else:
            with self._glock:
                self.acquisitions += 1
        stack.append([name, 1])
        return inversion

    def _on_release(self, name: str) -> None:
        stack = self._held()
        for entry in reversed(stack):
            if entry[0] == name:
                entry[1] -= 1
                if entry[1] <= 0:
                    stack.remove(entry)
                return
        # releasing a lock this thread never witnessed acquiring (it
        # was acquired before capture started): ignore

    def _on_release_all(self, name: str) -> None:
        stack = self._held()
        for entry in reversed(stack):
            if entry[0] == name:
                stack.remove(entry)
                return


def _creation_site(depth: int = 2) -> str:
    f = sys._getframe(depth)
    code = f.f_code
    qual = getattr(code, "co_qualname", code.co_name)
    mod = f.f_globals.get("__name__", "?")
    return f"{mod}.{qual}:{f.f_lineno}"


@contextlib.contextmanager
def capture(
    raise_on_inversion: bool = False,
    include: Tuple[str, ...] = ("flink_parameter_server_tpu",),
    witness: Optional[LockWitness] = None,
):
    """Patch ``threading.Lock``/``threading.RLock`` so every lock
    CREATED inside the block by a module under ``include`` is
    witnessed, named by its creation site.  Locks created elsewhere
    (stdlib, jax) stay real.  Yields the :class:`LockWitness`;
    restores the factories on exit.  Objects built inside the block
    keep their witnessed locks afterwards — harmless (the wrapper is
    a thin passthrough once the test stops reading the witness)."""
    w = witness if witness is not None else LockWitness(
        raise_on_inversion
    )
    real_lock, real_rlock = threading.Lock, threading.RLock

    def _should_wrap() -> bool:
        mod = sys._getframe(2).f_globals.get("__name__", "")
        return any(
            mod == p or mod.startswith(p + ".") for p in include
        )

    def make_lock():
        inner = real_lock()
        if not _should_wrap():
            return inner
        return w.wrap(inner, _creation_site(2))

    def make_rlock():
        inner = real_rlock()
        if not _should_wrap():
            return inner
        return w.wrap(inner, _creation_site(2))

    threading.Lock = make_lock
    threading.RLock = make_rlock
    try:
        yield w
    finally:
        threading.Lock = real_lock
        threading.RLock = real_rlock
