"""Hot-key traffic analytics — who is actually being pulled/pushed?

The r2 device trace showed the workload Zipf-skewed; ROADMAP item 3
(a client/edge hot-row cache for serving) is gated on MEASURING that
skew on the live key traffic rather than assuming it.  This module is
the measurement: a bounded-memory sketch pair over pull/push key
streams —

  * **count-min** (Cormode–Muthukrishnan): ``depth × width`` counters,
    per-row hashes from :func:`~..ops.hashing.fmix32_np`; the estimate
    for any key overestimates its true count by at most
    ``ε·N = (e/width)·N`` with probability ``1 − e^−depth`` (the
    documented accuracy bound tests pin against an exact numpy
    oracle);
  * **space-saving** (Metwally et al.): exact top-K candidate
    tracking in ``K`` counters; every key whose true count exceeds
    ``N/K`` is guaranteed present, and each reported count carries its
    per-key overestimation bound ``err``.

Per-shard sketches register with the process-wide
:class:`HotKeyAggregator`; merging is exact for count-min (same
seeds/shape → table addition) and standard-approximate for
space-saving (missing-side minima fold into ``err``).  The final
cross-shard top-K selection reuses :func:`~..ops.topk.dense_topk` —
the same partial-top-K-then-merge shape ROADMAP item 3's serving
fan-out needs, exercised here on sketch counters first.

Everything is host-side numpy on the hot path (one ``np.add.at`` per
observed batch); the overhead A/B in
``benchmarks/telemetry_overhead.py`` holds the whole plane (tracing +
sketch + SLO) under the 3% bar.
"""
from __future__ import annotations

import heapq
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.hashing import fmix32_np


class CountMinSketch:
    """Conservative frequency estimates in ``depth × width`` int64
    counters.  ``add`` is vectorized (one ``np.add.at`` per row);
    ``merge`` requires identical (width, depth, seed)."""

    def __init__(self, width: int = 2048, depth: int = 4, seed: int = 0):
        if width < 8 or depth < 1:
            raise ValueError(
                f"width={width}, depth={depth}: need width >= 8, depth >= 1"
            )
        self.width = int(width)
        self.depth = int(depth)
        self.seed = int(seed)
        self.table = np.zeros((self.depth, self.width), np.int64)
        self.total = 0
        # per-row salts: fmix32(id * odd + salt) decorrelates the rows
        rng = np.random.default_rng(self.seed)
        self._salts = rng.integers(1, 2**31, size=self.depth, dtype=np.int64)
        self._salts32 = self._salts.astype(np.uint32)

    @property
    def epsilon(self) -> float:
        """Overestimation factor: ``estimate − true ≤ ε·N`` w.p.
        ``1 − e^−depth``."""
        return math.e / self.width

    def _rows(self, ids: np.ndarray) -> np.ndarray:
        # all depth rows in one vectorized uint32 mix (wraparound IS
        # the & 0xFFFFFFFF; staying in uint32 avoids int64 temporaries
        # on the per-request hot path)
        ids32 = np.asarray(ids).reshape(-1).astype(np.uint32)
        with np.errstate(over="ignore"):
            h = (
                ids32[None, :] * np.uint32(0x9E3779B1)
                + self._salts32[:, None]
            )
        return np.asarray(fmix32_np(h), np.int64) % self.width

    def add(self, ids, counts=None) -> None:
        ids = np.asarray(ids).reshape(-1)
        if ids.size == 0:
            return
        # one bincount over flattened (row, slot) indices: much cheaper
        # than per-row np.add.at on the per-request hot path (and the
        # unweighted integer path when counts are implicit ones)
        slots = self._rows(ids)
        flat = (
            slots + (np.arange(self.depth, dtype=np.int64)[:, None]
                     * self.width)
        ).reshape(-1)
        size = self.depth * self.width
        if counts is None:
            delta = np.bincount(flat, minlength=size).astype(np.int64)
            total = ids.size
        else:
            counts = np.asarray(counts, np.int64).reshape(-1)
            w = np.broadcast_to(
                counts, (self.depth, ids.size)
            ).reshape(-1)
            delta = np.bincount(flat, weights=w, minlength=size).astype(
                np.int64
            )
            total = int(counts.sum())
        self.table += delta.reshape(self.depth, self.width)
        self.total += total

    def estimate(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.size == 0:
            return np.zeros(0, np.int64)
        slots = self._rows(ids)
        ests = self.table[np.arange(self.depth)[:, None], slots]
        return ests.min(axis=0)

    def merge(self, other: "CountMinSketch") -> None:
        if (self.width, self.depth, self.seed) != (
            other.width, other.depth, other.seed
        ):
            raise ValueError(
                "count-min merge needs identical (width, depth, seed)"
            )
        self.table += other.table
        self.total += other.total

    def halve(self) -> None:
        """Windowed decay: halve every counter (and the stream total).
        Halving preserves the overestimation guarantee relative to the
        halved stream — the exponential-decay trick that keeps the
        estimates tracking CURRENT traffic instead of all-time
        traffic."""
        self.table >>= 1
        self.total //= 2


class SpaceSavingTopK:
    """Metwally space-saving: at most ``capacity`` tracked keys; every
    key with true count > N/capacity is guaranteed tracked, and each
    tracked key's count overestimates truth by at most its ``err``."""

    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError(f"capacity={capacity}: must be >= 1")
        self.capacity = int(capacity)
        self._counts: Dict[int, int] = {}
        self._errs: Dict[int, int] = {}
        self.total = 0
        # sorted key cache for vectorized membership tests (rebuilt
        # whenever the tracked set changes)
        self._key_cache: Optional[np.ndarray] = None

    def update(
        self, ids, counts=None, *, assume_unique: bool = False
    ) -> None:
        """Batch update.  Tracked keys accumulate exactly; untracked
        keys compete for slots in ONE merge step per batch — the
        incoming batch is treated as an exact sketch and space-saving-
        merged in (each admitted newcomer inherits the pre-batch
        minimum as count floor and error, the same invariant as
        per-item insertion, vectorized so the per-request cost is
        O(uniq + k) instead of O(uniq · k)).  ``assume_unique`` skips
        the dedupe when the caller already collapsed the batch (the
        sketch flush path)."""
        ids = np.asarray(ids).reshape(-1)
        if ids.size == 0:
            return
        if assume_unique:
            uniq = ids
            c = (
                np.ones(ids.size, np.int64) if counts is None
                else np.asarray(counts, np.int64).reshape(-1)
            )
        elif counts is None:
            uniq, c = np.unique(ids, return_counts=True)
        else:
            counts = np.asarray(counts, np.int64).reshape(-1)
            uniq, inv = np.unique(ids, return_inverse=True)
            c = np.zeros(uniq.size, np.int64)
            np.add.at(c, inv, counts)
        self.total += int(c.sum())
        cs, errs = self._counts, self._errs
        # vectorized membership against the sorted key cache: the
        # absent set on a Zipf tail can be thousands of keys per
        # flush, and a python `in` loop over them dominated profiles
        if self._key_cache is None:
            self._key_cache = np.fromiter(
                sorted(cs.keys()), np.int64, len(cs)
            )
        cache = self._key_cache
        if cache.size:
            pos = np.searchsorted(cache, uniq)
            present = (pos < cache.size) & (
                cache[np.minimum(pos, cache.size - 1)] == uniq
            )
        else:
            present = np.zeros(uniq.size, bool)
        for key, n in zip(uniq[present].tolist(), c[present].tolist()):
            cs[key] += n  # at most `capacity` iterations
        absent_k, absent_c = uniq[~present], c[~present]
        if absent_k.size == 0:
            return
        # only the top `capacity` newcomers can possibly survive the
        # trim — cap the dict churn before touching python objects
        if absent_k.size > self.capacity:
            top = np.argpartition(-absent_c, self.capacity - 1)[
                : self.capacity
            ]
            absent_k, absent_c = absent_k[top], absent_c[top]
        free = self.capacity - len(cs)
        if absent_k.size <= free:
            for key, n in zip(absent_k.tolist(), absent_c.tolist()):
                cs[key] = n
                errs[key] = 0
            self._key_cache = None
            return
        # strongest newcomers first: free slots go to the largest
        # batch counts, and the displacement floors below ratchet in
        # the same order per-item insertion would visit them
        order = np.argsort(-absent_c, kind="stable")
        absent_k, absent_c = absent_k[order], absent_c[order]
        if free > 0:
            for key, n in zip(
                absent_k[:free].tolist(), absent_c[:free].tolist()
            ):
                cs[key] = n
                errs[key] = 0
            absent_k, absent_c = absent_k[free:], absent_c[free:]
        # at capacity: sequential space-saving over a min-heap of the
        # live counts — each admitted newcomer displaces the CURRENT
        # minimum, entering at (displaced count + n) with err capped
        # at the displaced key's count, i.e. the bound on how often
        # the newcomer could have occurred unseen in that slot.  The
        # previous batch path gave every newcomer the same pre-batch
        # floor and trimmed the union by raw count, which could evict
        # incumbents counted above the rolling minimum (the
        # over-admission documented in PR 11); with the ratcheting
        # heap floor a batch admits exactly what per-item insertion
        # admits, and errors ratchet with it.
        heap = [(c, k) for k, c in cs.items()]
        heapq.heapify(heap)
        for key, n in zip(absent_k.tolist(), absent_c.tolist()):
            floor, victim = heap[0]
            heapq.heapreplace(heap, (floor + n, key))
            del cs[victim]
            errs.pop(victim, None)
            cs[key] = floor + n
            errs[key] = floor
        self._key_cache = None

    def halve(self) -> None:
        """Windowed decay (the fossilization fix): halve every tracked
        count and error, dropping keys that decay to zero.  Without
        this, a long-running stream's top-K freezes on early-epoch
        keys — a key that was hot in hour 1 keeps a count no current
        key can catch, so lease grants (hotcache/policy.py) would chase
        stale celebrities forever.  Periodic halving turns the counts
        into an exponentially-decayed window: a key must KEEP being hot
        to stay on top."""
        counts = {k: c >> 1 for k, c in self._counts.items() if c >> 1}
        self._counts = counts
        self._errs = {
            k: self._errs.get(k, 0) >> 1 for k in counts
        }
        self.total //= 2
        self._key_cache = None

    @property
    def min_tracked(self) -> int:
        """The smallest tracked count (0 while under capacity) — the
        ceiling on any UNtracked key's true count."""
        if len(self._counts) < self.capacity:
            return 0
        return min(self._counts.values())

    def items(self) -> List[Tuple[int, int, int]]:
        """``(key, count, err)`` tuples, unordered."""
        return [
            (k, c, self._errs.get(k, 0)) for k, c in self._counts.items()
        ]

    def top_k(self, n: Optional[int] = None) -> List[Tuple[int, int, int]]:
        out = sorted(self.items(), key=lambda t: (-t[1], t[0]))
        return out if n is None else out[:n]

    def merge(self, other: "SpaceSavingTopK") -> None:
        """Standard approximate merge: shared keys add counts and
        errors; keys missing on one side absorb that side's
        ``min_tracked`` into both count and error (the key may have
        occurred up to that often unseen); trim back to capacity."""
        self_min, other_min = self.min_tracked, other.min_tracked
        merged: Dict[int, int] = {}
        errs: Dict[int, int] = {}
        for k, c in self._counts.items():
            oc = other._counts.get(k)
            if oc is None:
                merged[k] = c + other_min
                errs[k] = self._errs.get(k, 0) + other_min
            else:
                merged[k] = c + oc
                errs[k] = self._errs.get(k, 0) + other._errs.get(k, 0)
        for k, c in other._counts.items():
            if k in merged:
                continue
            merged[k] = c + self_min
            errs[k] = other._errs.get(k, 0) + self_min
        keep = sorted(merged, key=lambda k: (-merged[k], k))[: self.capacity]
        self._counts = {k: merged[k] for k in keep}
        self._errs = {k: errs[k] for k in keep}
        self._key_cache = None
        self.total += other.total


class HotKeySketch:
    """The pair wired into the traffic path: count-min for any-key
    estimates, space-saving for the top-K candidate set.  ``top_k``
    reports the space-saving candidates with the TIGHTER of the two
    counts (both overestimate; the min keeps both bounds).

    Hot-path discipline: ``observe`` only APPENDS the id batch to a
    small buffer (one lock, one list append); the unique/bincount/
    dict work runs once per ~``buffer_ids`` (default 16k) observed
    ids, amortizing the vectorized pass across many requests.  Every
    read (``top_k``/``estimate``/``merge``/``total``) flushes first,
    so readers never see a stale window."""

    def __init__(
        self,
        k: int = 64,
        *,
        width: int = 2048,
        depth: int = 3,
        seed: int = 0,
        buffer_ids: int = 16384,
        decay_window: Optional[int] = None,
    ):
        self.cms = CountMinSketch(width, depth, seed)
        self.topk = SpaceSavingTopK(k)
        self._lock = threading.Lock()
        self._buffer_ids = max(1, int(buffer_ids))
        self._pending: List[np.ndarray] = []
        self._pending_n = 0
        # windowed decay: every `decay_window` observed ids both
        # sketches are halved, so top-K and estimates track CURRENT
        # popularity (a mid-stream popularity shift overtakes the old
        # regime within ~one window).  None = all-time counts, the
        # pre-decay behaviour.
        if decay_window is not None and decay_window < 1:
            raise ValueError(
                f"decay_window={decay_window}: must be >= 1 or None"
            )
        self.decay_window = decay_window
        self._since_decay = 0
        self.decays = 0

    def _flush_locked(self) -> None:
        if not self._pending:
            return
        ids = (
            self._pending[0] if len(self._pending) == 1
            else np.concatenate(self._pending)
        )
        self._pending = []
        self._pending_n = 0
        uniq, c = np.unique(ids, return_counts=True)
        self.cms.add(uniq, c)
        self.topk.update(uniq, c, assume_unique=True)
        self._maybe_decay_locked(int(ids.size))

    def _maybe_decay_locked(self, observed: int) -> None:
        if self.decay_window is None:
            return
        self._since_decay += observed
        while self._since_decay >= self.decay_window:
            self._since_decay -= self.decay_window
            self.cms.halve()
            self.topk.halve()
            self.decays += 1

    def decay(self) -> None:
        """Explicitly halve both sketches (flushing first) — the
        manual form of ``decay_window``."""
        with self._lock:
            self._flush_locked()
            self.cms.halve()
            self.topk.halve()
            self.decays += 1

    @property
    def total(self) -> int:
        with self._lock:
            self._flush_locked()
            return self.topk.total

    def observe(self, ids, counts=None) -> None:
        """One observed key batch (pull ids, push ids, serving lookup
        ids) — any shape, flattened.  With explicit ``counts`` the
        batch is folded immediately (migration/merge paths); the
        common counts-free path is buffered (see class docstring)."""
        ids = np.asarray(ids).reshape(-1)
        if ids.size == 0:
            return
        if counts is not None:
            counts = np.asarray(counts, np.int64).reshape(-1)
            with self._lock:
                self._flush_locked()
                self.cms.add(ids, counts)
                self.topk.update(ids, counts)
                self._maybe_decay_locked(int(counts.sum()))
            return
        with self._lock:
            self._pending.append(ids)
            self._pending_n += ids.size
            if self._pending_n >= self._buffer_ids:
                self._flush_locked()

    def estimate(self, ids) -> np.ndarray:
        with self._lock:
            self._flush_locked()
            return self.cms.estimate(ids)

    def error_bound(self) -> int:
        """Absolute count-min overestimation bound ``ceil(ε·N)`` at the
        current stream length."""
        with self._lock:
            self._flush_locked()
            return int(math.ceil(self.cms.epsilon * self.cms.total))

    def top_k(self, n: Optional[int] = None) -> List[Dict[str, int]]:
        with self._lock:
            self._flush_locked()
            items = self.topk.top_k(n)
            if not items:
                return []
            keys = np.asarray([k for k, _, _ in items], np.int64)
            cms_est = self.cms.estimate(keys)
        return [
            {"key": int(k), "count": int(min(c, e)), "err": int(err)}
            for (k, c, err), e in zip(items, cms_est)
        ]

    def merge(self, other: "HotKeySketch") -> None:
        with self._lock, other._lock:
            self._flush_locked()
            other._flush_locked()
            self.cms.merge(other.cms)
            self.topk.merge(other.topk)


class HotKeyAggregator:
    """Process-wide registry of per-shard (and serving) sketches —
    the merged view ``/metrics`` and ``run_report`` expose.

    Registration is by label (``shard-0``, ``serving``); re-registering
    a label replaces the sketch (a replaced shard starts a fresh
    window).  ``top_k`` merges every registered sketch into a scratch
    copy and picks the final K with :func:`~..ops.topk.dense_topk`
    (counts as 1-d scores — the cross-shard partial-top-K merge)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sketches: Dict[str, HotKeySketch] = {}

    def register(self, label: str, sketch: HotKeySketch) -> HotKeySketch:
        with self._lock:
            self._sketches[str(label)] = sketch
        return sketch

    def unregister(self, label: str) -> None:
        with self._lock:
            self._sketches.pop(str(label), None)

    def labels(self) -> List[str]:
        with self._lock:
            return sorted(self._sketches)

    def clear(self) -> None:
        with self._lock:
            self._sketches.clear()

    def _merged(self) -> Optional[HotKeySketch]:
        with self._lock:
            sketches = list(self._sketches.values())
        if not sketches:
            return None
        first = sketches[0]
        merged = HotKeySketch(
            first.topk.capacity, width=first.cms.width,
            depth=first.cms.depth, seed=first.cms.seed,
        )
        for s in sketches:
            merged.merge(s)
        return merged

    def candidates(self, n: int = 16) -> List[Dict[str, int]]:
        """The merged top-``n`` WITHOUT the ops/topk final selection —
        pure numpy/python, so a latency-sensitive caller (the hotcache
        lease policy's refresh thread) never dispatches a jax op while
        holding the GIL next to a serving hot path.  Same candidate
        set and count bounds as :meth:`top_k`; only the final ranking
        kernel differs (a python sort)."""
        merged = self._merged()
        if merged is None:
            return []
        return merged.top_k(n)

    def top_k(self, n: int = 16) -> List[Dict[str, int]]:
        merged = self._merged()
        if merged is None:
            return []
        candidates = merged.top_k(None)
        if not candidates:
            return []
        # final selection over the merged candidate set via ops/topk —
        # counts as (rows, 1) scores against the unit query
        import jax.numpy as jnp

        from ..ops.topk import dense_topk

        scores = jnp.asarray(
            [[float(c["count"])] for c in candidates], jnp.float32
        )
        _top_scores, top_idx = dense_topk(
            scores, jnp.ones((1, 1), jnp.float32), min(n, len(candidates))
        )
        order = [int(i) for i in np.asarray(top_idx[0]) if int(i) >= 0]
        return [candidates[i] for i in order]

    def total(self) -> int:
        with self._lock:
            return sum(s.total for s in self._sketches.values())

    def error_bound(self) -> int:
        merged = self._merged()
        return 0 if merged is None else merged.error_bound()

    def exposition(self, n: int = 16, prefix: str = "fps_") -> List[str]:
        """Prometheus-text lines for the merged top-K — appended to the
        ``/metrics`` body by :func:`~.exporter.prometheus_text`."""
        top = self.top_k(n)
        if not top:
            return []
        lines = [f"# TYPE {prefix}hot_key_traffic gauge"]
        for rank, item in enumerate(top):
            lines.append(
                f'{prefix}hot_key_traffic{{key="{item["key"]}",'
                f'rank="{rank}"}} {item["count"]}'
            )
        lines.append(f"# TYPE {prefix}hot_key_error_bound gauge")
        lines.append(f"{prefix}hot_key_error_bound {self.error_bound()}")
        return lines

    def snapshot(self, n: int = 16) -> Dict[str, object]:
        """The ``run_report`` shape: merged top-K + provenance."""
        return {
            "top": self.top_k(n),
            "total_observed": self.total(),
            "cms_error_bound": self.error_bound(),
            "sketches": self.labels(),
        }


# -- the process-wide default -------------------------------------------------
_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[HotKeyAggregator] = None


def get_aggregator() -> HotKeyAggregator:
    """The process-wide aggregator (created on first use) — what the
    exporter and report read."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = HotKeyAggregator()
        return _DEFAULT


def set_aggregator(agg: Optional[HotKeyAggregator]) -> None:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = agg


__all__ = [
    "CountMinSketch",
    "HotKeyAggregator",
    "HotKeySketch",
    "SpaceSavingTopK",
    "get_aggregator",
    "set_aggregator",
]
