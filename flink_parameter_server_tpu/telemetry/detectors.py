"""Online anomaly detectors over timeline series.

Two deliberately simple, O(1)-per-point (or O(window)) detectors that
run INSIDE the :class:`~.timeline.TimelineRecorder` sample loop —
cheap enough to ride every poll, strong enough to catch the two
failure shapes the straggler study (arXiv:2308.15482, PAPERS.md) says
matter:

  * :class:`EWMADriftDetector` — exponentially-weighted mean/variance
    per series; fires when a point lands ``k`` EW-sigmas from the EW
    mean.  Catches LEVEL SHIFTS (a shard's RTT steps up and stays up)
    and then adapts: the state keeps absorbing points, so a sustained
    shift fires once per episode, not forever.
  * :class:`RollingMADDetector` — rolling median + median-absolute-
    deviation window per series; fires on robust z
    (``|x - med| / (1.4826 * MAD)``) past ``k``.  Catches OUTLIER
    SPIKES without the mean/variance being dragged by the spike
    itself (the classic EWMA blind spot), at O(window log window) per
    point over a small window.

Both are edge-triggered with hysteresis: one anomaly record at
episode START, silence while the episode persists, re-arm only after
the score drops below ``rearm_fraction * k``.  That is what makes
"one flightrec dump per episode" structural rather than throttle-luck,
and what keeps ``timeline_anomalies_total{metric,kind}`` a count of
EPISODES, not of samples spent inside one.

Scale floors (``rel_floor``/``abs_floor``) keep a near-constant series
from manufacturing infinite z-scores out of float jitter — the
documented zero-false-positive contract on stationary noise
(tests/test_timeline.py pins it against a numpy reference).

Detectors are metric-scoped (``metric`` + optional derived ``field``
— "rate", "value", "p50", "p99") and keep independent state per label
set, so one detector instance watches every shard/worker series of
its metric at once.
"""
from __future__ import annotations

import math
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .registry import _label_key


def _scale_floor(scale: float, center: float, rel_floor: float,
                 abs_floor: float) -> float:
    return max(scale, rel_floor * abs(center), abs_floor)


class _EpisodeState:
    """Edge-trigger bookkeeping shared by both detectors."""

    __slots__ = ("active", "episode_started", "peak_score", "n")

    def __init__(self):
        self.active = False
        self.episode_started: Optional[float] = None
        self.peak_score = 0.0
        self.n = 0


class _BaseDetector:
    """Match + per-label-set state + edge-triggered episode ledger.

    Subclasses implement :meth:`_score_and_update` (score the point
    against the series state, then absorb it); this base decides
    warmup, firing edges, hysteresis re-arm, and the anomaly record
    shape the recorder consumes.
    """

    kind = "base"

    def __init__(
        self,
        metric: str,
        *,
        field: Optional[str] = None,
        k: float = 4.0,
        warmup: int = 10,
        rearm_fraction: float = 0.5,
        rel_floor: float = 0.05,
        abs_floor: float = 1e-9,
    ):
        if k <= 0 or warmup < 2:
            raise ValueError(
                f"k={k}, warmup={warmup}: need k > 0 and warmup >= 2"
            )
        if not 0.0 < rearm_fraction <= 1.0:
            raise ValueError(
                f"rearm_fraction={rearm_fraction}: must be in (0, 1]"
            )
        self.metric = metric
        self.field = field
        self.k = float(k)
        self.warmup = int(warmup)
        self.rearm_fraction = float(rearm_fraction)
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        self._lock = threading.Lock()
        self._state: Dict[Tuple[Tuple[str, str], ...], Any] = {}
        self._episodes: Dict[
            Tuple[Tuple[str, str], ...], _EpisodeState
        ] = {}
        self.episodes: List[dict] = []  # closed + open episode ledger

    # -- subclass seam -----------------------------------------------------
    def _new_state(self) -> Any:
        raise NotImplementedError

    def _score_and_update(self, state: Any,
                          value: float) -> Optional[float]:
        """Return the point's score vs the PRE-UPDATE state (None while
        warming up), then absorb the point into the state."""
        raise NotImplementedError

    # -- the recorder-facing API -------------------------------------------
    def observe(self, name: str, labels: Dict[str, str], field: str,
                value: float, ts: float) -> Optional[dict]:
        """Score one timeline point; returns an anomaly record exactly
        at episode start, else None."""
        if name != self.metric:
            return None
        if self.field is not None and field != self.field:
            return None
        key = _label_key(labels)
        with self._lock:
            state = self._state.get(key)
            if state is None:
                state = self._new_state()
                self._state[key] = state
                self._episodes[key] = _EpisodeState()
            ep = self._episodes[key]
            score = self._score_and_update(state, float(value))
            ep.n += 1
            if score is None:
                return None
            if ep.active:
                ep.peak_score = max(ep.peak_score, score)
                if score < self.k * self.rearm_fraction:
                    ep.active = False  # episode over; re-armed
                return None
            if score <= self.k:
                return None
            ep.active = True
            ep.episode_started = ts
            ep.peak_score = score
            record = {
                "ts": round(ts, 6),
                "metric": self.metric,
                "labels": dict(labels),
                "field": field,
                "kind": self.kind,
                "value": value,
                "score": round(score, 4),
                "threshold": self.k,
            }
            self.episodes.append(record)
            return record


class EWMADriftDetector(_BaseDetector):
    """EW mean/variance drift detector (level shifts).

    State per series: EW mean ``m`` and EW variance ``v`` with
    smoothing ``alpha`` (West 1979 incremental form:
    ``d = x - m;  m += alpha*d;  v = (1-alpha)*(v + alpha*d*d)``).
    Score = ``|x - m_pre| / max(sqrt(v_pre), floors)``.  The state
    absorbs every point INCLUDING anomalous ones — a sustained level
    shift therefore fires at its leading edge and then becomes the
    new normal, which is exactly the drift (not outlier) semantics.
    """

    kind = "ewma_drift"

    def __init__(self, metric: str, *, field: Optional[str] = None,
                 alpha: float = 0.2, k: float = 4.0, warmup: int = 10,
                 **kwargs):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha={alpha}: must be in (0, 1)")
        super().__init__(metric, field=field, k=k, warmup=warmup,
                         **kwargs)
        self.alpha = float(alpha)

    def _new_state(self) -> dict:
        return {"n": 0, "mean": 0.0, "var": 0.0}

    def _score_and_update(self, state: dict,
                          value: float) -> Optional[float]:
        score: Optional[float] = None
        if state["n"] >= self.warmup:
            sigma = _scale_floor(
                math.sqrt(max(0.0, state["var"])), state["mean"],
                self.rel_floor, self.abs_floor,
            )
            score = abs(value - state["mean"]) / sigma
        if state["n"] == 0:
            state["mean"] = value
        else:
            d = value - state["mean"]
            incr = self.alpha * d
            state["mean"] += incr
            state["var"] = (1.0 - self.alpha) * (
                state["var"] + d * incr
            )
        state["n"] += 1
        return score


class RollingMADDetector(_BaseDetector):
    """Rolling median/MAD outlier detector (spikes).

    State per series: a bounded window of recent points.  Score =
    ``|x - median| / max(1.4826 * MAD, floors)`` — the robust z-score
    (1.4826 makes MAD a consistent sigma estimator under normality).
    Median and MAD shrug off the spike itself, so a single wild point
    cannot raise the bar for detecting the next one.
    """

    kind = "mad_outlier"

    def __init__(self, metric: str, *, field: Optional[str] = None,
                 window: int = 24, k: float = 6.0, warmup: int = 12,
                 **kwargs):
        if window < 4:
            raise ValueError(f"window={window}: must be >= 4")
        super().__init__(metric, field=field, k=k, warmup=warmup,
                         **kwargs)
        if self.warmup > window:
            raise ValueError(
                f"warmup={warmup} > window={window}: the warmup bar "
                f"could never be met from a full window"
            )
        self.window = int(window)

    def _new_state(self) -> deque:
        return deque(maxlen=self.window)

    @staticmethod
    def _median(sorted_vals: List[float]) -> float:
        n = len(sorted_vals)
        mid = n // 2
        if n % 2:
            return sorted_vals[mid]
        return 0.5 * (sorted_vals[mid - 1] + sorted_vals[mid])

    def _score_and_update(self, state: deque,
                          value: float) -> Optional[float]:
        score: Optional[float] = None
        if len(state) >= self.warmup:
            vals = sorted(state)
            med = self._median(vals)
            mad = self._median(sorted(abs(v - med) for v in vals))
            scale = _scale_floor(
                1.4826 * mad, med, self.rel_floor, self.abs_floor
            )
            score = abs(value - med) / scale
        state.append(value)
        return score


__all__ = ["EWMADriftDetector", "RollingMADDetector"]
