"""Wall-clock span tracer — host-side phase attribution.

``training/tracing.py`` covers the DEVICE side (``jax.named_scope``
annotations inside the jitted step, Perfetto/XPlane traces).  What it
cannot see is where the HOST went: ingest wait, WAL fsync, snapshot
publish, dispatch queueing — precisely the silent stalls the straggler
study (arXiv:2308.15482) blames for PS throughput loss.  This tracer
makes those visible next to the device steps: nestable ``span("pull")``
context managers, a fixed-size ring buffer (old spans fall off; tracing
a week-long job must not OOM the host), and a Chrome trace-event JSON
export (``chrome://tracing`` / Perfetto ``ui.perfetto.dev`` both load
it) so the host timeline sits beside the profiler's device timeline.

Distributed tracing (telemetry/distributed.py): a span can carry a
``(trace_id, span_id, parent_id)`` identity.  Nested spans on the same
thread inherit the enclosing span's trace; handing a ``trace_id`` /
``parent_id`` explicitly stitches causality ACROSS threads and — via
the ``t=<trace>:<span>`` wire token cluster/shard.py speaks — across
processes.  Untraced spans carry ``None`` ids and cost no id
generation.

Overhead discipline: a disabled tracer's ``span()`` returns a shared
no-op context manager — two attribute reads, no allocation — so the
driver can leave the call sites in place unconditionally.

Stack bookkeeping: per-thread span stacks live in a dict keyed by
thread ident, with dead-thread entries evicted whenever a NEW thread
first spans and the table has grown past a small bound — a
``LineServer`` front end spawns one handler thread per TCP connection,
and a long-lived server that churns thousands of short connections
must not keep a stack list per thread that ever existed
(tests/test_tracing.py pins the bound with a 200-connection churn).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# prune dead-thread stacks once the table outgrows this many entries
_STACK_TABLE_SOFT_CAP = 32


def gen_id(nbytes: int = 8) -> str:
    """A random hex id (trace ids: 8 bytes, span ids: 4) — unique
    across processes, cheap enough for one per traced request."""
    return os.urandom(nbytes).hex()


class _NullSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = (
        "tracer", "name", "component", "t0",
        "trace_id", "span_id", "parent_id",
    )

    def __init__(
        self,
        tracer: "SpanTracer",
        name: str,
        component: str,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        span_id: Optional[str] = None,
    ):
        self.tracer = tracer
        self.name = name
        self.component = component
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.span_id = span_id

    def __enter__(self):
        stack = self.tracer._stack()
        if self.trace_id is None and stack:
            # same-thread nesting inherits the enclosing trace (the
            # cross-thread/process case hands ids in explicitly)
            top = stack[-1]
            if top.trace_id is not None:
                self.trace_id = top.trace_id
                self.parent_id = top.span_id
        if self.trace_id is not None and self.span_id is None:
            self.span_id = gen_id(4)
        stack.append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        stack = self.tracer._stack()
        depth = len(stack) - 1
        stack.pop()
        self.tracer._record(
            self.name, self.component, self.t0, t1, depth,
            self.trace_id, self.span_id, self.parent_id,
        )
        return False


class SpanTracer:
    """Ring-buffered wall-clock tracer.

    Spans nest per-thread (a ``publish`` inside a ``dispatch`` carries
    depth 1); the buffer holds the most recent ``capacity`` spans across
    all threads.  ``export_chrome_trace()`` emits the standard
    trace-event JSON array of complete (``ph: "X"``) events — depth is
    preserved implicitly by Chrome's per-tid flame stacking and
    explicitly in each event's ``args.depth``.

    ``process`` names this tracer's lane when several rings are merged
    into one cross-process trace (telemetry/distributed.py
    ``TraceCollector``); ``pid`` defaults to the OS pid.
    """

    def __init__(
        self,
        capacity: int = 65536,
        *,
        enabled: bool = True,
        pid: Optional[int] = None,
        process: Optional[str] = None,
    ):
        if capacity <= 0:
            raise ValueError(f"capacity={capacity}: must be > 0")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self.pid = int(pid) if pid is not None else os.getpid()
        self.process = process
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self.capacity)
        self._stacks: Dict[int, list] = {}
        self._stacks_lock = threading.Lock()
        # perf_counter has an arbitrary epoch; anchor it to wall time
        # once so exported timestamps are meaningful across processes
        self._epoch_wall = time.time()
        self._epoch_perf = time.perf_counter()

    # -- recording ---------------------------------------------------------
    def _stack(self) -> list:
        # dict reads are GIL-atomic; only creation takes the lock
        ident = threading.get_ident()
        st = self._stacks.get(ident)
        if st is None:
            with self._stacks_lock:
                st = self._stacks.setdefault(ident, [])
                if len(self._stacks) > _STACK_TABLE_SOFT_CAP:
                    live = {t.ident for t in threading.enumerate()}
                    for k in list(self._stacks):
                        if k != ident and k not in live:
                            del self._stacks[k]
        return st

    def stack_count(self) -> int:
        """Per-thread stack entries currently tracked (bounded by live
        threads + the soft cap, NOT by threads ever seen)."""
        with self._stacks_lock:
            return len(self._stacks)

    def _record(
        self, name: str, component: str, t0: float, t1: float, depth: int,
        trace_id: Optional[str] = None, span_id: Optional[str] = None,
        parent_id: Optional[str] = None,
    ) -> None:
        with self._lock:
            self._spans.append((
                name, component, t0, t1, depth, threading.get_ident(),
                trace_id, span_id, parent_id,
            ))

    def span(
        self,
        name: str,
        component: str = "host",
        *,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        span_id: Optional[str] = None,
    ):
        """``with tracer.span("ingest", component="ingest"): ...`` —
        returns the shared no-op when disabled.  ``trace_id`` /
        ``parent_id`` attach the span to a distributed trace (same-
        thread children then inherit it automatically)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, component, trace_id, parent_id, span_id)

    def record(
        self, name: str, t0: float, t1: float, component: str = "host",
        *,
        trace_id: Optional[str] = None,
        span_id: Optional[str] = None,
        parent_id: Optional[str] = None,
    ) -> None:
        """Retroactive span from already-taken ``time.perf_counter()``
        stamps — for intervals whose boundaries live in someone else's
        control flow (the driver times dispatches at callback edges;
        wrapping the jitted call itself would mean forking the loop)."""
        if not self.enabled:
            return
        self._record(
            name, component, float(t0), float(t1), 0,
            trace_id, span_id, parent_id,
        )

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- reads -------------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def wall_clock_anchor(self) -> tuple:
        """``(epoch_wall, epoch_perf)`` — the wall-time anchoring of
        this ring's perf_counter timestamps (the collector's raw
        material for cross-process clock alignment)."""
        return self._epoch_wall, self._epoch_perf

    def spans(self) -> List[Dict[str, Any]]:
        """Recorded spans, oldest first: name/component/start/dur/depth/
        tid (seconds, perf_counter timebase) plus trace_id/span_id/
        parent_id (None for untraced spans)."""
        with self._lock:
            raw = list(self._spans)
        return [
            {
                "name": n, "component": c, "start": t0,
                "dur": t1 - t0, "depth": d, "tid": tid,
                "trace_id": tr, "span_id": sp, "parent_id": pa,
            }
            for (n, c, t0, t1, d, tid, tr, sp, pa) in raw
        ]

    def export_chrome_trace(self, path: Optional[str] = None) -> str:
        """Chrome trace-event JSON (the array form — both catapult and
        Perfetto accept it).  Timestamps are microseconds since the
        tracer's wall-clock epoch; writes to ``path`` when given,
        returns the JSON string either way."""
        events = []
        if self.process is not None:
            events.append({
                "name": "process_name", "ph": "M", "pid": self.pid,
                "tid": 0, "args": {"name": self.process},
            })
        with self._lock:
            raw = list(self._spans)
        for (name, component, t0, t1, depth, tid, tr, sp, pa) in raw:
            args: Dict[str, Any] = {"depth": depth}
            if tr is not None:
                args["trace_id"] = tr
                args["span_id"] = sp
                args["parent_id"] = pa
            events.append({
                "name": name,
                "cat": component,
                "ph": "X",
                "ts": round((t0 - self._epoch_perf) * 1e6, 3),
                "dur": round((t1 - t0) * 1e6, 3),
                "pid": self.pid,
                "tid": tid,
                "args": args,
            })
        doc = json.dumps(events)
        if path is not None:
            with open(path, "w") as f:
                f.write(doc)
        return doc


# -- the process-wide default -------------------------------------------------
_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[SpanTracer] = None


def get_tracer() -> SpanTracer:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SpanTracer()
        return _DEFAULT


def set_tracer(tracer: Optional[SpanTracer]) -> None:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = tracer


def span(name: str, component: str = "host"):
    """Module-level convenience over the default tracer."""
    return get_tracer().span(name, component)


__all__ = ["SpanTracer", "gen_id", "get_tracer", "set_tracer", "span"]
