"""Wall-clock span tracer — host-side phase attribution.

``training/tracing.py`` covers the DEVICE side (``jax.named_scope``
annotations inside the jitted step, Perfetto/XPlane traces).  What it
cannot see is where the HOST went: ingest wait, WAL fsync, snapshot
publish, dispatch queueing — precisely the silent stalls the straggler
study (arXiv:2308.15482) blames for PS throughput loss.  This tracer
makes those visible next to the device steps: nestable ``span("pull")``
context managers, a fixed-size ring buffer (old spans fall off; tracing
a week-long job must not OOM the host), and a Chrome trace-event JSON
export (``chrome://tracing`` / Perfetto ``ui.perfetto.dev`` both load
it) so the host timeline sits beside the profiler's device timeline.

Overhead discipline: a disabled tracer's ``span()`` returns a shared
no-op context manager — two attribute reads, no allocation — so the
driver can leave the call sites in place unconditionally.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


class _NullSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("tracer", "name", "component", "t0")

    def __init__(self, tracer: "SpanTracer", name: str, component: str):
        self.tracer = tracer
        self.name = name
        self.component = component

    def __enter__(self):
        self.tracer._stack().append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        stack = self.tracer._stack()
        depth = len(stack) - 1
        stack.pop()
        self.tracer._record(
            self.name, self.component, self.t0, t1, depth
        )
        return False


class SpanTracer:
    """Ring-buffered wall-clock tracer.

    Spans nest per-thread (a ``publish`` inside a ``dispatch`` carries
    depth 1); the buffer holds the most recent ``capacity`` spans across
    all threads.  ``export_chrome_trace()`` emits the standard
    trace-event JSON array of complete (``ph: "X"``) events — depth is
    preserved implicitly by Chrome's per-tid flame stacking and
    explicitly in each event's ``args.depth``.
    """

    def __init__(self, capacity: int = 65536, *, enabled: bool = True):
        if capacity <= 0:
            raise ValueError(f"capacity={capacity}: must be > 0")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self.capacity)
        self._local = threading.local()
        # perf_counter has an arbitrary epoch; anchor it to wall time
        # once so exported timestamps are meaningful across processes
        self._epoch_wall = time.time()
        self._epoch_perf = time.perf_counter()

    # -- recording ---------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _record(self, name: str, component: str, t0: float, t1: float,
                depth: int) -> None:
        with self._lock:
            self._spans.append(
                (name, component, t0, t1, depth, threading.get_ident())
            )

    def span(self, name: str, component: str = "host"):
        """``with tracer.span("ingest", component="ingest"): ...`` —
        returns the shared no-op when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, component)

    def record(self, name: str, t0: float, t1: float,
               component: str = "host") -> None:
        """Retroactive span from already-taken ``time.perf_counter()``
        stamps — for intervals whose boundaries live in someone else's
        control flow (the driver times dispatches at callback edges;
        wrapping the jitted call itself would mean forking the loop)."""
        if not self.enabled:
            return
        self._record(name, component, float(t0), float(t1), 0)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- reads -------------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def spans(self) -> List[Dict[str, Any]]:
        """Recorded spans, oldest first: name/component/start/dur/depth/
        tid (seconds, perf_counter timebase)."""
        with self._lock:
            raw = list(self._spans)
        return [
            {
                "name": n, "component": c, "start": t0,
                "dur": t1 - t0, "depth": d, "tid": tid,
            }
            for (n, c, t0, t1, d, tid) in raw
        ]

    def export_chrome_trace(self, path: Optional[str] = None) -> str:
        """Chrome trace-event JSON (the array form — both catapult and
        Perfetto accept it).  Timestamps are microseconds since the
        tracer's wall-clock epoch; writes to ``path`` when given,
        returns the JSON string either way."""
        events = []
        with self._lock:
            raw = list(self._spans)
        for (name, component, t0, t1, depth, tid) in raw:
            events.append({
                "name": name,
                "cat": component,
                "ph": "X",
                "ts": round((t0 - self._epoch_perf) * 1e6, 3),
                "dur": round((t1 - t0) * 1e6, 3),
                "pid": 0,
                "tid": tid,
                "args": {"depth": depth},
            })
        doc = json.dumps(events)
        if path is not None:
            with open(path, "w") as f:
                f.write(doc)
        return doc


# -- the process-wide default -------------------------------------------------
_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[SpanTracer] = None


def get_tracer() -> SpanTracer:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SpanTracer()
        return _DEFAULT


def set_tracer(tracer: Optional[SpanTracer]) -> None:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = tracer


def span(name: str, component: str = "host"):
    """Module-level convenience over the default tracer."""
    return get_tracer().span(name, component)


__all__ = ["SpanTracer", "get_tracer", "set_tracer", "span"]
