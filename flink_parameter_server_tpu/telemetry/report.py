"""End-of-run report builder.

One page per run, not a log to grep: steps/sec, pull→push latency
percentiles, serving QPS/p99, snapshot staleness, ingest reconnects,
recovery episodes — pulled from the unified registry and written to
``results/<platform>/run_report.{md,json}``.  docs/perf_status.md's
rule: future bench deltas cite ``run_report.json``, so every number
here carries enough context (run_id, platform, wall clock) to be
compared across rounds without re-deriving provenance.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from .registry import MetricsRegistry, get_registry


def _find(snapshot: Dict[str, Any], name: str, **labels) -> Optional[Any]:
    """First sample of ``name`` whose labels include ``labels``."""
    for sample in snapshot.get(name, ()):
        if all(sample["labels"].get(k) == v for k, v in labels.items()):
            return sample["value"]
    return None


def _sum_counter(snapshot: Dict[str, Any], name: str) -> float:
    return float(
        sum(s["value"] or 0.0 for s in snapshot.get(name, ()))
    )


def _reject_counts(snapshot: Dict[str, Any]) -> tuple:
    """(total, {reason: n}) for serving_rejected_total: the aggregate
    (unlabelled) instrument and the per-cause breakdown share the
    metric name, so a blind name-sum would double-count."""
    total = 0.0
    by_reason: Dict[str, int] = {}
    for s in snapshot.get("serving_rejected_total", ()):
        reason = (s.get("labels") or {}).get("reason")
        v = s["value"] or 0.0
        if reason is None:
            total += v
        else:
            by_reason[reason] = by_reason.get(reason, 0) + int(v)
    return total, by_reason


def _hist_percentiles(registry: MetricsRegistry, name: str) -> Dict[str, Any]:
    for inst in registry.instruments():
        if inst.name == name and inst.kind == "histogram" and inst.count:
            return {
                "p50_ms": round(inst.percentile(50) * 1e3, 3),
                "p99_ms": round(inst.percentile(99) * 1e3, 3),
                "mean_ms": round(inst.sum / inst.count * 1e3, 3),
                "count": inst.count,
            }
    return {"p50_ms": None, "p99_ms": None, "mean_ms": None, "count": 0}


def build_run_report(
    registry: Optional[MetricsRegistry] = None,
    *,
    wall_s: Optional[float] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Assemble the cross-component summary dict from the registry.

    ``wall_s`` overrides the elapsed-time base for the steps/sec rate
    (callers that know the measured window pass it; the default is time
    since the registry was created).  ``extra`` merges verbatim under
    ``"extra"`` — the telemetry-overhead bench records its A/B there.
    """
    reg = registry if registry is not None else get_registry()
    snap = reg.snapshot()
    wall = float(wall_s) if wall_s is not None else max(
        1e-9, time.time() - reg.created_at
    )
    steps = _sum_counter(snap, "train_steps_total")
    events = _sum_counter(snap, "train_events_total")
    report: Dict[str, Any] = {
        "run_id": reg.run_id,
        "generated_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "wall_s": round(wall, 3),
        "train": {
            "steps": int(steps),
            "events": int(events),
            "steps_per_sec": round(steps / wall, 2),
            "updates_per_sec": round(events / wall, 1),
            "pull_push": _hist_percentiles(reg, "pull_push_latency_seconds"),
            "checkpoints": int(_sum_counter(snap, "checkpoints_total")),
        },
        "serving": {
            "requests": int(_sum_counter(snap, "serving_requests_total")),
            "rejected": int(_reject_counts(snap)[0]),
            "rejected_by_reason": _reject_counts(snap)[1],
            "qps": _find(snap, "serving_qps", component="serving"),
            "latency": _hist_percentiles(reg, "serving_latency_seconds"),
            "batch_fill": _find(
                snap, "serving_batch_fill", component="serving"
            ),
            "snapshot_staleness_steps": _find(
                snap, "snapshot_staleness_steps", component="serving"
            ),
        },
        "ingest": {
            "batches": int(_sum_counter(snap, "ingest_batches_total")),
            "reconnects": int(
                _sum_counter(snap, "ingest_reconnects_total")
            ),
            "wal_appends": int(_sum_counter(snap, "wal_appends_total")),
        },
        "recovery": {
            "restarts": int(
                _sum_counter(snap, "recovery_restarts_total")
            ),
            "replayed_steps": int(
                _sum_counter(snap, "recovery_replayed_steps_total")
            ),
            "dropped_steps": int(
                _sum_counter(snap, "recovery_dropped_steps_total")
            ),
            "stall_episodes": int(
                _sum_counter(snap, "stall_episodes_total")
            ),
        },
        "elastic": {
            "epoch": _find(snap, "elastic_epoch", component="elastic"),
            "epoch_flips": int(
                _sum_counter(snap, "elastic_epoch_flips_total")
            ),
            "epoch_refreshes": int(
                _sum_counter(snap, "elastic_epoch_refreshes_total")
            ),
            "rows_migrated": int(
                _sum_counter(snap, "elastic_rows_migrated_total")
            ),
            "migration_stall": _hist_percentiles(
                reg, "elastic_migration_stall_seconds"
            ),
            "hedged_pulls": int(
                _sum_counter(snap, "elastic_hedged_pulls_total")
            ),
            "hedges_won": int(
                _sum_counter(snap, "elastic_hedges_won_total")
            ),
            "shard_replacements": int(
                _sum_counter(snap, "elastic_shard_replacements_total")
            ),
            "stale_epoch_storms": int(
                _sum_counter(snap, "elastic_stale_epoch_storms_total")
            ),
        },
    }
    hedged = report["elastic"]["hedged_pulls"]
    report["elastic"]["hedge_win_rate"] = (
        round(report["elastic"]["hedges_won"] / hedged, 4)
        if hedged else None
    )
    budget = _latency_budget_section()
    if budget:
        report["latency_budget"] = budget
    net = _net_section(snap)
    if net:
        report["net"] = net
    slo = _slo_section(snap)
    if slo:
        report["slo"] = slo
    hot = _hot_keys_section()
    if hot is not None:
        report["hot_keys"] = hot
    hotcache = _hotcache_section()
    if hotcache is not None:
        report["hotcache"] = hotcache
    meshstore = _meshstore_section(snap, reg)
    if meshstore is not None:
        report["meshstore"] = meshstore
    timeline = _timeline_section()
    if timeline is not None:
        report["timeline"] = timeline
    adaptive = _adaptive_section()
    if adaptive is not None:
        report["adaptive"] = adaptive
    if extra:
        report["extra"] = dict(extra)
    return report


def _latency_budget_section() -> Dict[str, Any]:
    """Per-verb phase budgets from the process profiler
    (telemetry/profiler.py) — empty when no phases were observed.
    This is the section docs/perf_status.md cites as the required
    evidence for the ROADMAP item 2 transport rework: it names the
    top cost center of a round with its % of round time."""
    from .profiler import get_profiler

    return get_profiler().budget_report()


def _net_section(snap: Dict[str, Any]) -> Dict[str, Any]:
    """Bytes/frames on the wire by (role, direction), summed over
    verbs (utils/net.py accounting) — the baseline ROADMAP item 4's
    "bytes down" criterion is judged against."""
    out: Dict[str, Any] = {}
    for name, kind in (("net_bytes_total", "bytes"),
                       ("net_frames_total", "frames")):
        for s in snap.get(name, ()):
            role = s["labels"].get("role", "?")
            direction = s["labels"].get("direction", "?")
            key = f"{role}_{kind}_{direction}"
            out[key] = int(out.get(key, 0) + (s["value"] or 0))
    return out


def _slo_section(snap: Dict[str, Any]) -> Dict[str, Any]:
    """Per-objective verdict roll-up from the SLO engine's probe
    gauges (telemetry/slo.py) — empty when no engine is attached."""
    out: Dict[str, Any] = {}
    for s in snap.get("slo_healthy", ()):
        name = s["labels"].get("slo")
        if name is None:
            continue
        v = s["value"]
        out[name] = {
            "healthy": None if v is None else bool(v),
        }
    for s in snap.get("slo_burn_rate", ()):
        name = s["labels"].get("slo")
        window = s["labels"].get("window", "short")
        if name is None:
            continue
        out.setdefault(name, {})[f"burn_{window}"] = s["value"]
    return out


def _hot_keys_section(n: int = 10) -> Optional[Dict[str, Any]]:
    """Merged hot-key sketch snapshot (telemetry/hotkeys.py) — None
    when no sketch is registered."""
    from .hotkeys import get_aggregator

    agg = get_aggregator()
    if not agg.labels():
        return None
    return agg.snapshot(n)


def _hotcache_section() -> Optional[Dict[str, Any]]:
    """Hot-key lease cache roll-up (hotcache/, docs/hotcache.md) —
    per-cache hit/miss/revoke/staleness figures plus the aggregate hit
    rate; None when no cache is registered."""
    from ..hotcache.cache import cache_snapshots

    snaps = cache_snapshots()
    if not snaps:
        return None
    hits = sum(s["hits"] for s in snaps.values())
    misses = sum(s["misses"] for s in snaps.values())
    return {
        "caches": {
            label: {
                k: s[k]
                for k in ("hits", "misses", "hit_rate", "entries",
                          "fills", "revocations", "stale_rejects",
                          "evictions", "max_served_age", "bound")
            }
            for label, s in snaps.items()
        },
        "hits": hits,
        "misses": misses,
        "hit_rate": (
            round(hits / (hits + misses), 4) if hits + misses else None
        ),
    }


def _meshstore_section(
    snap: Dict[str, Any], reg: MetricsRegistry
) -> Optional[Dict[str, Any]]:
    """On-device mesh store roll-up (meshstore/, docs/meshstore.md):
    pull/push volume, gather/scatter collective latency, the per-kind
    collective-op ledger and the resident byte gauges.  None when the
    mesh backend never registered (the usual socket-shard run)."""
    pulls = _sum_counter(snap, "meshstore_pulls_total")
    pushes = _sum_counter(snap, "meshstore_pushes_total")
    if not snap.get("meshstore_pulls_total") and not snap.get(
        "meshstore_table_bytes"
    ):
        return None
    collective_ops = {}
    for s in snap.get("meshstore_collective_ops_total", ()):
        kind = (s.get("labels") or {}).get("kind", "?")
        collective_ops[kind] = int(
            collective_ops.get(kind, 0) + (s["value"] or 0)
        )
    return {
        "pulls": int(pulls),
        "pushes": int(pushes),
        "rows_pulled": int(
            _sum_counter(snap, "meshstore_rows_pulled_total")
        ),
        "rows_pushed": int(
            _sum_counter(snap, "meshstore_rows_pushed_total")
        ),
        "wal_appends": int(
            _sum_counter(snap, "meshstore_wal_appends_total")
        ),
        "collective_ops": collective_ops,
        "gather": _hist_percentiles(reg, "meshstore_gather_seconds"),
        "scatter": _hist_percentiles(reg, "meshstore_scatter_seconds"),
        "table_bytes": _find(
            snap, "meshstore_table_bytes", component="meshstore"
        ),
        "device_bytes": _find(
            snap, "meshstore_device_bytes", component="meshstore"
        ),
        "opt_state_bytes": _find(
            snap, "meshstore_opt_state_bytes", component="meshstore"
        ),
    }


def _timeline_section(max_rows: int = 40) -> Optional[Dict[str, Any]]:
    """Timeline roll-up (telemetry/timeline.py): per-series
    min/max/last plus the anomaly-episode ledger from the process
    recorder — None when no recorder is installed (the opt-in
    contract, same as the flight recorder's)."""
    from .timeline import get_timeline

    tl = get_timeline()
    if tl is None:
        return None
    rows = tl.summary()
    anomalies = tl.anomalies()
    return {
        "interval_s": tl.interval_s,
        "samples": tl._samples,
        "series": len(rows),
        "rows": rows[:max_rows],
        "rows_truncated": max(0, len(rows) - max_rows),
        "anomalies": anomalies,
        "skew": [t.snapshot() for t in tl.skew],
    }


def _adaptive_section(
    max_decisions: int = 40,
) -> Optional[Dict[str, Any]]:
    """Adaptive-runtime roll-up (adaptive/controller.py): per-worker
    effective bounds, hedge wins, rebalance moves, the decision tail —
    None when no runtime is installed (opt-in, like the timeline)."""
    from ..adaptive.controller import get_adaptive_runtime

    rt = get_adaptive_runtime()
    if rt is None:
        return None
    payload = rt.payload()
    decisions = payload.pop("decisions", [])
    payload["decisions"] = decisions[-max_decisions:]
    payload["decisions_truncated"] = max(
        0, len(decisions) - max_decisions
    )
    return payload


def _default_platform() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "cpu"


def render_markdown(report: Dict[str, Any]) -> str:
    t, s = report["train"], report["serving"]
    i, r = report["ingest"], report["recovery"]
    e = report.get("elastic", {})
    pp, sl = t["pull_push"], s["latency"]

    def fmt(v, unit=""):
        return "—" if v is None else f"{v}{unit}"

    lines = [
        "# Run report",
        "",
        f"run `{report['run_id']}` · generated {report['generated_at']} "
        f"· wall {report['wall_s']} s",
        "",
        "| metric | value |",
        "|---|---|",
        f"| train steps | {t['steps']} |",
        f"| steps/sec | {t['steps_per_sec']} |",
        f"| updates/sec | {t['updates_per_sec']} |",
        f"| pull→push p50 / p99 | {fmt(pp['p50_ms'], ' ms')} / "
        f"{fmt(pp['p99_ms'], ' ms')} |",
        f"| checkpoints | {t['checkpoints']} |",
        f"| serving requests (rejected) | {s['requests']} "
        f"({s['rejected']}) |",
        f"| serving QPS | {fmt(s['qps'])} |",
        f"| serving p50 / p99 | {fmt(sl['p50_ms'], ' ms')} / "
        f"{fmt(sl['p99_ms'], ' ms')} |",
        f"| snapshot staleness (steps) | "
        f"{fmt(s['snapshot_staleness_steps'])} |",
        f"| ingest batches / reconnects | {i['batches']} / "
        f"{i['reconnects']} |",
        f"| WAL appends | {i['wal_appends']} |",
        f"| recovery restarts / replayed / dropped | {r['restarts']} / "
        f"{r['replayed_steps']} / {r['dropped_steps']} |",
        f"| stall episodes | {r['stall_episodes']} |",
    ]
    if e:
        ms = e.get("migration_stall", {})
        win = e.get("hedge_win_rate")
        lines += [
            f"| elastic epoch (flips / client refreshes) | "
            f"{fmt(e['epoch'])} ({e['epoch_flips']} / "
            f"{e['epoch_refreshes']}) |",
            f"| rows migrated | {e['rows_migrated']} |",
            f"| migration stall p50 / p99 | "
            f"{fmt(ms.get('p50_ms'), ' ms')} / "
            f"{fmt(ms.get('p99_ms'), ' ms')} |",
            f"| hedged pulls (won / win rate) | {e['hedged_pulls']} "
            f"({e['hedges_won']} / {fmt(win)}) |",
            f"| shard replacements | {e['shard_replacements']} |",
            f"| stale-epoch storms | {e.get('stale_epoch_storms', 0)} |",
        ]
    net = report.get("net")
    if net:
        lines.append(
            f"| wire bytes (server in / out) | "
            f"{net.get('server_bytes_in', 0)} / "
            f"{net.get('server_bytes_out', 0)} |"
        )
        lines.append(
            f"| wire frames (server in / out) | "
            f"{net.get('server_frames_in', 0)} / "
            f"{net.get('server_frames_out', 0)} |"
        )
    budget = report.get("latency_budget")
    if budget:
        lines += ["", "## Latency budget", ""]
        for verb in sorted(budget):
            b = budget[verb]
            if not b.get("round_ms"):
                continue
            lines.append(
                f"**{verb}**: round p50 {b['round_ms']} ms over "
                f"{b['rounds']} frames — top cost center: "
                f"`{b['top_phase']}` ({b['top_pct']}% of round time, "
                f"coverage: {b['coverage']})"
            )
            lines.append("")
            lines += ["| phase | p50 ms | mean ms | % of round |",
                      "|---|---|---|---|"]
            for p in b["phases"]:
                lines.append(
                    f"| {p['phase']} | {p['p50_ms']} | {p['mean_ms']} "
                    f"| {p['pct']} |"
                )
            lines.append("")
    slo = report.get("slo")
    if slo:
        lines += ["", "## SLO verdicts", ""]
        lines += ["| objective | healthy | burn short / long |",
                  "|---|---|---|"]
        for name in sorted(slo):
            v = slo[name]
            healthy = v.get("healthy")
            lines.append(
                f"| {name} | "
                f"{'—' if healthy is None else ('yes' if healthy else 'NO')}"
                f" | {fmt(v.get('burn_short'))} / "
                f"{fmt(v.get('burn_long'))} |"
            )
    hot = report.get("hot_keys")
    if hot:
        lines += ["", "## Hot keys", ""]
        lines.append(
            f"top keys over {hot['total_observed']} observed "
            f"(count-min error bound ±{hot['cms_error_bound']}, "
            f"sketches: {', '.join(hot['sketches'])}):"
        )
        lines.append("")
        lines += ["| key | count | err |", "|---|---|---|"]
        for item in hot["top"][:10]:
            lines.append(
                f"| {item['key']} | {item['count']} | {item['err']} |"
            )
    hotcache = report.get("hotcache")
    if hotcache:
        lines += ["", "## Hot-key lease cache", ""]
        lines.append(
            f"aggregate: {hotcache['hits']} hits / "
            f"{hotcache['misses']} misses "
            f"(hit rate {fmt(hotcache['hit_rate'])})"
        )
        lines.append("")
        lines += [
            "| cache | hits | misses | hit rate | entries | revoked "
            "| stale rejects | worst served age / bound |",
            "|---|---|---|---|---|---|---|---|",
        ]
        for label in sorted(hotcache["caches"]):
            c = hotcache["caches"][label]
            lines.append(
                f"| {label} | {c['hits']} | {c['misses']} | "
                f"{fmt(c['hit_rate'])} | {c['entries']} | "
                f"{c['revocations']} | {c['stale_rejects']} | "
                f"{c['max_served_age']} / {c['bound']} |"
            )
    mesh = report.get("meshstore")
    if mesh:
        g, sc = mesh["gather"], mesh["scatter"]
        ops = mesh.get("collective_ops", {})
        lines += ["", "## Mesh store", ""]
        lines += [
            "| metric | value |",
            "|---|---|",
            f"| pulls / pushes | {mesh['pulls']} / {mesh['pushes']} |",
            f"| rows pulled / pushed | {mesh['rows_pulled']} / "
            f"{mesh['rows_pushed']} |",
            f"| WAL appends | {mesh['wal_appends']} |",
            f"| collective ops (gather / scatter) | "
            f"{ops.get('gather', 0)} / {ops.get('scatter', 0)} |",
            f"| gather p50 / p99 | {fmt(g['p50_ms'], ' ms')} / "
            f"{fmt(g['p99_ms'], ' ms')} |",
            f"| scatter p50 / p99 | {fmt(sc['p50_ms'], ' ms')} / "
            f"{fmt(sc['p99_ms'], ' ms')} |",
            f"| table / per-device / opt-state bytes | "
            f"{fmt(mesh['table_bytes'])} / {fmt(mesh['device_bytes'])} "
            f"/ {fmt(mesh['opt_state_bytes'])} |",
        ]
    tl = report.get("timeline")
    if tl:
        lines += ["", "## Timeline", ""]
        lines.append(
            f"{tl['series']} series × {tl['samples']} samples at "
            f"{tl['interval_s']} s cadence; "
            f"{len(tl['anomalies'])} anomaly episode(s)"
        )
        lines.append("")
        lines += ["| series | labels | field | min | max | last |",
                  "|---|---|---|---|---|---|"]
        for row in tl["rows"]:
            labels = ",".join(
                f"{k}={v}" for k, v in sorted(row["labels"].items())
                if k != "component"
            ) or "—"
            lines.append(
                f"| {row['metric']} | {labels} | {row['field']} | "
                f"{row['min']:.4g} | {row['max']:.4g} | "
                f"{row['last']:.4g} |"
            )
        if tl.get("rows_truncated"):
            lines.append(
                f"| … {tl['rows_truncated']} more series | | | | | |"
            )
        if tl["anomalies"]:
            lines.append("")
            lines += ["| anomaly ts | metric | kind | score |",
                      "|---|---|---|---|"]
            for a in tl["anomalies"][:20]:
                lines.append(
                    f"| {a['ts']} | {a['metric']} | {a['kind']} | "
                    f"{a['score']} |"
                )
        for sk in tl.get("skew", ()):
            last = sk.get("last")
            if last:
                lines.append("")
                lines.append(
                    f"skew[{sk['metric']} by {sk['entity_label']}]: "
                    f"top entity `{last['entity']}` at "
                    f"{last['ratio']}× fleet median"
                    f"{' **FLAGGED**' if last['flagged'] else ''}"
                )
    extra = report.get("extra")
    if extra:
        lines += ["", "## Extra", ""]
        for k in sorted(extra):
            lines.append(f"- `{k}`: {extra[k]}")
    return "\n".join(lines) + "\n"


def write_run_report(
    report: Dict[str, Any],
    *,
    platform: Optional[str] = None,
    results_dir: Optional[str] = None,
) -> Dict[str, str]:
    """Write ``run_report.md`` + ``run_report.json`` under
    ``results/<platform>/`` (repo-relative by default) and return the
    two paths."""
    if results_dir is None:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        results_dir = os.path.join(
            repo, "results", platform or _default_platform()
        )
    os.makedirs(results_dir, exist_ok=True)
    json_path = os.path.join(results_dir, "run_report.json")
    md_path = os.path.join(results_dir, "run_report.md")
    with open(json_path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    with open(md_path, "w") as f:
        f.write(render_markdown(report))
    return {"json": json_path, "md": md_path}


__all__ = ["build_run_report", "render_markdown", "write_run_report"]
