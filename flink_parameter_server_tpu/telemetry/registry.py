"""Unified metric registry — the single seam every subsystem measures
through.

Before this module, `training/metrics.py`, `serving/metrics.py` and
`resilience/health.py` each invented their own JSON-ish emit format and
nothing correlated a slow step with ingest stalls, serving admission
pressure, or a recovery replay.  The straggler study (arXiv:2308.15482,
PAPERS.md) diagnoses PS slowdowns from exactly that cross-component
timeline, and the elastic-aggregation line of work (arXiv:2204.03211)
assumes a queryable live metrics surface.  This registry is both: a
process-wide, thread-safe table of typed instruments (Counter, Gauge,
Histogram) carrying ``component=train|serving|ingest|recovery`` labels,
snapshot-able at any moment (the ``/metrics`` endpoint in
``exporter.py`` renders it live) and emittable as one JSON line per
sample (the sink contract the three legacy emitters now publish
through).

Identity: an instrument is (name, sorted label set).  Asking twice for
the same identity returns the same instrument; asking with a different
type raises — a counter silently shadowed by a gauge is the classic
way dashboards lie.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# -- run identity -------------------------------------------------------------
# One id per process by default, shared by every emitter so interleaved
# JSON lines from train/serve/recover correlate without guesswork.
_RUN_ID_LOCK = threading.Lock()
_RUN_ID: Optional[str] = None


def default_run_id() -> str:
    """Process-wide run id (pid + start-time; stable for the process)."""
    global _RUN_ID
    with _RUN_ID_LOCK:
        if _RUN_ID is None:
            _RUN_ID = f"{os.getpid():x}-{int(time.time() * 1e3) & 0xFFFFFFFF:08x}"
        return _RUN_ID


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic accumulator (events, steps, rejects, restarts)."""

    kind = "counter"

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) < 0")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value.  Either ``set()`` stored values or a live
    ``fn`` probe (queue depth, heartbeat age) resolved at read time —
    a stored gauge read mid-stall would report the pre-stall value,
    which is exactly the lie the probe form exists to avoid."""

    kind = "gauge"

    def __init__(
        self,
        name: str,
        labels: Dict[str, str],
        fn: Optional[Callable[[], Optional[float]]] = None,
    ):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._value: Optional[float] = None
        self._fn = fn

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def set_fn(self, fn: Callable[[], Optional[float]]) -> None:
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            fn = self._fn
            stored = self._value
        if fn is not None:
            try:
                v = fn()
            except Exception:  # a dead probe must not kill a scrape
                return None
            return None if v is None else float(v)
        return stored


# Default histogram boundaries: seconds, spanning sub-ms device steps
# through multi-second recovery episodes (upper bounds; +inf implicit).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Histogram:
    """Fixed-boundary histogram (Prometheus-shaped: per-bucket counts,
    sum, count).  Boundaries are upper bounds of non-cumulative bins;
    the overflow bin is implicit.  ``percentile`` interpolates linearly
    within the winning bin — approximate by construction, but stable
    under concurrency and O(buckets) to read, which is what a live
    ``/metrics`` scrape needs."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        labels: Dict[str, str],
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        bounds = tuple(float(b) for b in buckets)
        if not bounds or list(bounds) != sorted(bounds) or len(
            set(bounds)
        ) != len(bounds):
            raise ValueError(
                f"histogram {name}: buckets must be a non-empty strictly "
                f"increasing sequence, got {buckets!r}"
            )
        self.name = name
        self.labels = dict(labels)
        self.bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)  # last = overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        # bisect by hand to stay allocation-free under the lock
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            self._counts[lo] += 1
            self._sum += v
            self._count += 1

    # -- reads ------------------------------------------------------------
    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def bucket_counts(self) -> List[int]:
        """Non-cumulative per-bin counts (len(bounds) + 1, overflow last)."""
        with self._lock:
            return list(self._counts)

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100]) by linear
        interpolation inside the winning bin; the overflow bin clamps to
        the largest finite boundary (an honest floor, not a guess)."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"q={q}: must be in [0, 100]")
        counts = self.bucket_counts()
        total = sum(counts)
        if total == 0:
            return 0.0
        rank = q / 100.0 * total
        seen = 0.0
        for i, c in enumerate(counts):
            if seen + c >= rank and c > 0:
                if i == len(self.bounds):  # overflow bin
                    return self.bounds[-1]
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = self.bounds[i]
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
        return self.bounds[-1]

    @property
    def value(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "buckets": list(self._counts),
            }


class MetricsRegistry:
    """Thread-safe instrument table + JSON-lines sink.

    ``counter/gauge/histogram`` are get-or-create by (name, labels);
    ``snapshot()`` is a consistent-enough point-in-time read (each
    instrument is internally consistent; cross-instrument skew is
    bounded by one lock hop), ``emit(sink)`` writes ONE single-line
    JSON sample carrying the shared ``ts``/``run_id`` fields every
    emitter in the repo now stamps.
    """

    def __init__(self, run_id: Optional[str] = None):
        self._lock = threading.Lock()
        self._instruments: Dict[
            Tuple[str, Tuple[Tuple[str, str], ...]], Any
        ] = {}
        self.run_id = run_id if run_id is not None else default_run_id()
        self.created_at = time.time()

    # -- instrument accessors ---------------------------------------------
    def _get_or_create(self, cls, name: str, labels: Dict[str, str],
                       **kwargs):
        key = (name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, labels, **kwargs)
                self._instruments[key] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"instrument {name}{labels} already registered as "
                    f"{inst.kind}, requested {cls.kind}"
                )
            return inst

    def counter(self, name: str, *, component: Optional[str] = None,
                **labels: str) -> Counter:
        if component is not None:
            labels["component"] = component
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, *, component: Optional[str] = None,
              fn: Optional[Callable[[], Optional[float]]] = None,
              **labels: str) -> Gauge:
        if component is not None:
            labels["component"] = component
        g = self._get_or_create(Gauge, name, labels)
        if fn is not None:
            g.set_fn(fn)
        return g

    def histogram(self, name: str, *, component: Optional[str] = None,
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **labels: str) -> Histogram:
        if component is not None:
            labels["component"] = component
        h = self._get_or_create(Histogram, name, labels, buckets=buckets)
        if tuple(float(b) for b in buckets) != h.bounds:
            raise ValueError(
                f"histogram {name}{labels}: bucket boundaries differ from "
                f"the registered instrument's"
            )
        return h

    def instruments(self) -> List[Any]:
        with self._lock:
            return list(self._instruments.values())

    # -- reads -------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """``{name: [{"labels": {...}, "kind": ..., "value": ...}, ...]}``
        — gauges resolve their live probes here; a probe that fails or
        returns None yields value None (visible, not invented)."""
        out: Dict[str, Any] = {}
        for inst in self.instruments():
            v = inst.value
            if isinstance(v, float) and (
                math.isnan(v) or math.isinf(v)
            ):
                v = None  # JSON has no inf/nan; a poisoned gauge shows
                # as null rather than producing an unparseable line
            out.setdefault(inst.name, []).append(
                {"labels": dict(inst.labels), "kind": inst.kind, "value": v}
            )
        return out

    def emit(self, sink=None) -> str:
        """One single-line JSON sample of the whole registry (the
        JSON-lines sink contract; round-trips through ``json.loads``)."""
        return json_line(
            {"kind": "registry", "metrics": self.snapshot()},
            sink, run_id=self.run_id,
        )


def _finite(v):
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return None
    if isinstance(v, dict):
        return {k: _finite(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_finite(x) for x in v]
    return v


def json_line(payload: Dict[str, Any], sink=None, *,
              run_id: Optional[str] = None) -> str:
    """The one emit path every JSON-lines emitter in the repo funnels
    through: stamp the shared ``ts``/``run_id`` fields, null out
    non-finite floats (strict JSON has no NaN/Infinity), and guarantee
    the result is a single line that round-trips ``json.loads``."""
    body = {"ts": round(time.time(), 3),
            "run_id": run_id if run_id is not None else default_run_id()}
    body.update({k: _finite(v) for k, v in payload.items()})
    line = json.dumps(body, allow_nan=False)
    assert "\n" not in line  # json.dumps without indent never wraps
    if sink is not None:
        sink.write(line + "\n")
    return line


# -- the process-wide default -------------------------------------------------
_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (created on first use).  Every
    subsystem publishes here unless handed an explicit registry — which
    is what makes one ``/metrics`` endpoint see train, serve, ingest
    and recovery at once."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT


def set_registry(registry: Optional[MetricsRegistry]) -> None:
    """Swap the process default (tests isolate themselves with this;
    None resets to lazy re-creation)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = registry


__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "default_run_id",
    "json_line",
    "get_registry",
    "set_registry",
]
