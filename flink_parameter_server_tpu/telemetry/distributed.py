"""Cross-process request tracing — wire tokens + the ring merger.

PR 3's span tracer sees ONE process.  A pull that crosses
``ClusterClient`` → ``ShardServer`` → (hedged retry) → a
migration-frozen shard is invisible as a single causal story — exactly
the blind spot the straggler study (arXiv:2308.15482, PAPERS.md) names
as the source of silent PS throughput loss.  This module closes it
with three small pieces:

  * :class:`TraceContext` — the identity a request carries:
    ``(trace_id, span_id)``, serialized on the wire as the compact
    frame option ``t=<trace>:<span>`` (cluster/shard.py's
    ``key=value`` trailing-option grammar, so a PR-5-era server
    ignores the token and answers normally — the protocol versioning
    is "old peers skip what they don't know");
  * :func:`parse_token` / :func:`format_token` — tolerant codecs (a
    malformed token yields ``None``, never a protocol error: tracing
    must not be able to fail a request);
  * :class:`TraceCollector` — gathers every participating process's
    :class:`~.spans.SpanTracer` ring, aligns their clocks, and merges
    them into ONE Chrome/Perfetto trace where each process is a lane
    and a hedged pull shows primary and backup racing across lanes.

Clock alignment: each ring anchors its ``perf_counter`` timestamps to
its own wall clock, and wall clocks drift between hosts.  The
collector therefore estimates a per-ring offset NTP-style from
request/response span pairs: a server-side span (child) should sit
centered inside the client-side span (parent) that issued the request
— ``offset = midpoint(parent) − midpoint(child)`` per pair, median
over all pairs between the two rings.  Rings with no pair to an
already-aligned ring keep their raw wall anchoring (offset 0) — an
honest fallback, flagged in :meth:`TraceCollector.offsets`.  The
estimate's error is bounded by the asymmetry of the request's
out/back network legs (the classic NTP caveat, documented in
docs/observability.md): on one host it is microseconds; across hosts
expect ±½ RTT.
"""
from __future__ import annotations

import dataclasses
import json
import statistics
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .spans import SpanTracer, gen_id

#: the frame-option key trace tokens ride under (``t=<trace>:<span>``)
TRACE_OPT = "t"


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One request's identity: the trace it belongs to and the span
    that is its direct parent on the far side."""

    trace_id: str
    span_id: str

    def token(self) -> str:
        return f"{self.trace_id}:{self.span_id}"


def new_trace() -> TraceContext:
    """A fresh root context (one per logical client request)."""
    return TraceContext(gen_id(8), gen_id(4))


def format_token(ctx: TraceContext) -> str:
    """The LINE-protocol wire form: ``t=<trace>:<span>``.  The binary
    framing (utils/frames.py) carries the bare :meth:`TraceContext.
    token` value as a ``T_TRACE`` TLV instead — same grammar, parsed
    by the same :func:`parse_token` on the server."""
    return f"{TRACE_OPT}={ctx.token()}"


def parse_token(tok: Optional[str]) -> Optional[TraceContext]:
    """Inverse of :meth:`TraceContext.token` — tolerant: ``None`` or a
    malformed token yields ``None`` (a bad trace header must never
    fail the request it rode in on)."""
    if not tok or not isinstance(tok, str):
        return None
    trace_id, sep, span_id = tok.partition(":")
    if not sep or not trace_id or not span_id:
        return None
    return TraceContext(trace_id, span_id)


class TraceCollector:
    """Merge per-process span rings into one cross-process trace.

    Usage::

        col = TraceCollector()
        col.add(client_tracer, "client")
        for i, t in enumerate(shard_tracers):
            col.add(t, f"shard-{i}")
        col.export("results/cpu/merged_trace.json")

    Each added ring becomes one Chrome-trace process lane (synthetic
    lane pids 1..N — several rings usually share one OS pid on the
    thread-backed runtime, and lanes must not collapse).  Events are
    clock-aligned (see module docstring) and sorted by timestamp;
    every ``X`` event's ``args`` carries ``trace_id`` / ``span_id`` /
    ``parent_id`` keys (``None`` for untraced spans) so the lint
    (tools/check_metric_lines.py) and the tests can follow causality
    without heuristics.
    """

    def __init__(self, *, align: bool = True):
        self.align = bool(align)
        self._rings: List[Tuple[SpanTracer, str]] = []

    def add(self, tracer: SpanTracer, name: Optional[str] = None
            ) -> "TraceCollector":
        label = (
            name if name is not None
            else (tracer.process or f"proc-{len(self._rings)}")
        )
        self._rings.append((tracer, label))
        return self

    # -- alignment ---------------------------------------------------------
    @staticmethod
    def _absolute_spans(tracer: SpanTracer) -> List[Dict[str, Any]]:
        wall, perf = tracer.wall_clock_anchor()
        out = []
        for s in tracer.spans():
            s = dict(s)
            s["t0"] = wall + (s["start"] - perf)
            s["t1"] = s["t0"] + s["dur"]
            out.append(s)
        return out

    def _estimate_offsets(
        self, spans_per_ring: Sequence[List[Dict[str, Any]]]
    ) -> List[float]:
        """Per-ring additive corrections (seconds).  Ring 0 is the
        reference; other rings align through parent/child span pairs
        against any already-aligned ring, in passes, so a chain
        client → shard → sub-request still aligns end to end."""
        n = len(spans_per_ring)
        offsets: List[Optional[float]] = [None] * n
        if n:
            offsets[0] = 0.0
        # span_id → (ring, t0, t1) for every traced span
        by_span: Dict[str, Tuple[int, float, float]] = {}
        for r, spans in enumerate(spans_per_ring):
            for s in spans:
                if s["span_id"] is not None:
                    by_span[s["span_id"]] = (r, s["t0"], s["t1"])
        for _pass in range(n):
            progressed = False
            for r, spans in enumerate(spans_per_ring):
                if offsets[r] is not None:
                    continue
                deltas: List[float] = []
                for s in spans:
                    # this ring's span is the CHILD of an aligned span
                    pa = s.get("parent_id")
                    if pa is not None and pa in by_span:
                        pr, p0, p1 = by_span[pa]
                        if pr != r and offsets[pr] is not None:
                            parent_mid = (p0 + p1) / 2 + offsets[pr]
                            deltas.append(parent_mid - (s["t0"] + s["t1"]) / 2)
                    # this ring's span is the PARENT of an aligned span
                    sp = s.get("span_id")
                    if sp is None:
                        continue
                    for other_r, others in enumerate(spans_per_ring):
                        if other_r == r or offsets[other_r] is None:
                            continue
                        for o in others:
                            if o.get("parent_id") == sp:
                                child_mid = (
                                    (o["t0"] + o["t1"]) / 2
                                    + offsets[other_r]
                                )
                                deltas.append(
                                    child_mid - (s["t0"] + s["t1"]) / 2
                                )
                if deltas:
                    offsets[r] = float(statistics.median(deltas))
                    progressed = True
            if not progressed:
                break
        return [o if o is not None else 0.0 for o in offsets]

    # -- the merge ---------------------------------------------------------
    def offsets(self) -> Dict[str, float]:
        """Applied per-ring clock corrections, seconds (0.0 = reference
        or no pair to align through)."""
        spans_per_ring = [
            self._absolute_spans(t) for t, _ in self._rings
        ]
        offs = (
            self._estimate_offsets(spans_per_ring)
            if self.align else [0.0] * len(self._rings)
        )
        return {name: off for (_t, name), off in zip(self._rings, offs)}

    def merged_events(self) -> List[Dict[str, Any]]:
        """The merged Chrome trace-event list: one ``process_name``
        metadata event per ring, then every span as a ``ph: "X"``
        event, timestamp-sorted, in microseconds since the earliest
        aligned span."""
        spans_per_ring = [
            self._absolute_spans(t) for t, _ in self._rings
        ]
        offs = (
            self._estimate_offsets(spans_per_ring)
            if self.align else [0.0] * len(self._rings)
        )
        xs: List[Dict[str, Any]] = []
        for lane, ((_tracer, name), spans, off) in enumerate(
            zip(self._rings, spans_per_ring, offs), start=1
        ):
            for s in spans:
                xs.append({
                    "name": s["name"],
                    "cat": s["component"],
                    "ph": "X",
                    "ts": (s["t0"] + off) * 1e6,
                    "dur": s["dur"] * 1e6,
                    "pid": lane,
                    "tid": s["tid"],
                    "args": {
                        "depth": s["depth"],
                        "trace_id": s["trace_id"],
                        "span_id": s["span_id"],
                        "parent_id": s["parent_id"],
                        "process": name,
                        "clock_offset_us": round(off * 1e6, 3),
                    },
                })
        xs.sort(key=lambda e: e["ts"])
        t_base = xs[0]["ts"] if xs else 0.0
        for e in xs:
            e["ts"] = round(e["ts"] - t_base, 3)
            e["dur"] = round(e["dur"], 3)
        meta = [
            {
                "name": "process_name", "ph": "M", "pid": lane, "tid": 0,
                "args": {"name": name},
            }
            for lane, (_t, name) in enumerate(self._rings, start=1)
        ]
        return meta + xs

    def export(self, path: Optional[str] = None) -> str:
        doc = json.dumps(self.merged_events())
        if path is not None:
            with open(path, "w") as f:
                f.write(doc)
        return doc


__all__ = [
    "TRACE_OPT",
    "TraceContext",
    "TraceCollector",
    "format_token",
    "new_trace",
    "parse_token",
]
