"""Word2vec skip-gram with negative sampling (SGNS) on the PS.

Reference parity: BASELINE.json config #3 — "word2vec skip-gram w/
negative sampling (async sparse push)".  The classic PS formulation keeps
*both* embedding matrices on the server, keyed by word id; workers stream
(center, context) pairs, pull the touched rows, compute the SGNS gradient
and push sparse deltas (the reference's async-sparse-push pattern,
SURVEY.md §2 "Asynchrony").

TPU-first: one store row per word holds ``(2, dim)`` — slot 0 the input
("in") embedding, slot 1 the output ("out") embedding — so one sharded
gather fetches everything a pair needs.  A microbatch of B pairs with N
negatives pulls ``(B, N+2)`` rows, computes the loss/gradients as fused
batched matvecs, and pushes one ``(B, N+2, 2, dim)`` scatter-add (zeros in
the untouched slot).  Negative sampling happens host-side in the data
stream (unigram^0.75), or on-device via ``sample_negatives``.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.batched import BatchedWorkerLogic, PushRequest
from ..core.store import ShardedParamStore
from ..core.transform import transform_batched
from ..utils.initializers import ranged_random_factor

Array = jax.Array

IN, OUT = 0, 1  # slots in the (2, dim) store row


class SkipGramNS(BatchedWorkerLogic):
    """Batch: ``center`` (B,), ``context`` (B,), ``negatives`` (B, N),
    ``mask`` (B,) — produces per-pair SGNS loss and sparse pushes.

    ``dedup_scale`` (requires ``vocab_size``): scale each lane's delta by
    1/count(id-in-batch) so Zipf-hot words take one *averaged* step per
    microbatch instead of count× summed steps — keeps high learning rates
    stable under skew (see :mod:`..ops.dedup`)."""

    def __init__(
        self,
        learning_rate: float = 0.025,
        *,
        dedup_scale: bool = False,
        vocab_size: Optional[int] = None,
    ):
        self.learning_rate = learning_rate
        self.dedup_scale = dedup_scale
        self.vocab_size = vocab_size
        if dedup_scale and vocab_size is None:
            raise ValueError("dedup_scale=True requires vocab_size")

    def init_state(self, rng: Array):
        return ()  # the whole model lives on the PS

    def keys(self, batch: Dict[str, Array]) -> Array:
        return jnp.concatenate(
            [
                batch["center"][:, None],
                batch["context"][:, None],
                batch["negatives"],
            ],
            axis=1,
        )  # (B, N+2)

    def step(self, state, batch: Dict[str, Array], pulled: Array):
        # pulled: (B, N+2, 2, dim)
        lr = self.learning_rate
        v = pulled[:, 0, IN]  # (B, d) center input embedding
        u_pos = pulled[:, 1, OUT]  # (B, d) context output embedding
        u_neg = pulled[:, 2:, OUT]  # (B, N, d)

        pos_logit = jnp.sum(v * u_pos, axis=-1)  # (B,)
        neg_logit = jnp.einsum("bd,bnd->bn", v, u_neg)  # (B, N)
        # SGNS: maximize log σ(pos) + Σ log σ(-neg)
        g_pos = jax.nn.sigmoid(pos_logit) - 1.0  # dL/d(pos_logit)
        g_neg = jax.nn.sigmoid(neg_logit)  # dL/d(neg_logit)

        d_v = g_pos[:, None] * u_pos + jnp.einsum("bn,bnd->bd", g_neg, u_neg)
        d_upos = g_pos[:, None] * v
        d_uneg = g_neg[..., None] * v[:, None, :]  # (B, N, d)

        B, d = v.shape
        N = u_neg.shape[1]
        deltas = jnp.zeros((B, N + 2, 2, d), v.dtype)
        deltas = deltas.at[:, 0, IN].set(-lr * d_v)
        deltas = deltas.at[:, 1, OUT].set(-lr * d_upos)
        deltas = deltas.at[:, 2:, OUT].set(-lr * d_uneg)

        mask = batch.get("mask")
        lane_mask = None
        if mask is not None:
            lane_mask = jnp.broadcast_to(mask[:, None], (B, N + 2))

        if self.dedup_scale:
            from ..ops.dedup import occurrence_scale

            keys = self.keys(batch)
            scale = occurrence_scale(keys, self.vocab_size, lane_mask)
            deltas = deltas * scale[..., None, None]

        loss = -(
            jax.nn.log_sigmoid(pos_logit)
            + jnp.sum(jax.nn.log_sigmoid(-neg_logit), axis=-1)
        )
        if mask is not None:
            loss = loss * mask
        out = {"loss": loss}
        return state, PushRequest(self.keys(batch), deltas, lane_mask), out


def make_store(
    vocab_size: int,
    dim: int,
    *,
    seed: int = 0,
    mesh=None,
    init_scale: float = 0.5,
    scatter_impl: str = "xla",
    layout: str = "dense",
) -> ShardedParamStore:
    """(vocab, 2, dim) store; input slot random-uniform (the word2vec
    convention: U(-0.5/dim, 0.5/dim)), output slot zero."""
    base = ranged_random_factor(
        seed, (dim,), low=-init_scale / dim, high=init_scale / dim
    )

    def init(ids: Array) -> Array:
        in_emb = base(ids)
        return jnp.stack([in_emb, jnp.zeros_like(in_emb)], axis=1)

    return ShardedParamStore.create(
        vocab_size, (2, dim), init_fn=init, mesh=mesh,
        scatter_impl=scatter_impl, layout=layout,
    )


def sample_negatives(
    rng: Array, probs_cdf: Array, shape: Tuple[int, ...]
) -> Array:
    """Device-side unigram^0.75 sampling by inverse-CDF binary search —
    branch-free and jit-friendly."""
    u = jax.random.uniform(rng, shape)
    return jnp.searchsorted(probs_cdf, u).astype(jnp.int32)


def train_skipgram(
    pairs,
    *,
    vocab_size: int,
    dim: int = 64,
    learning_rate: float = 0.025,
    dedup_scale: bool = False,
    seed: int = 0,
    mesh=None,
    **kwargs,
):
    """End-to-end SGNS training over an iterable of pair microbatches.
    ``result.store.values()`` is the (vocab, 2, dim) embedding table."""
    logic = SkipGramNS(
        learning_rate, dedup_scale=dedup_scale, vocab_size=vocab_size
    )
    store = make_store(vocab_size, dim, seed=seed, mesh=mesh)
    return transform_batched(
        pairs, logic, store, rng=jax.random.PRNGKey(seed), mesh=mesh, **kwargs
    )


__all__ = ["SkipGramNS", "make_store", "sample_negatives", "train_skipgram", "IN", "OUT"]
