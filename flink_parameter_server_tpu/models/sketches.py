"""Streaming sketches on the PS: count-min / bloom co-occurrence and
tug-of-war (AMS) sketches, with time-aware decay.

Reference parity (SURVEY.md §2 #10): the reference ships PS-backed
distributed sketches over word/token streams — bloom-filter-based
co-occurrence counting and tug-of-war (AMS) style sketches, including
time-aware variants, used for streaming word-similarity experiments.

TPU-first: a sketch *is* a parameter store — a flat counter table sharded
over ``ps`` — and a sketch update *is* a push: hash the microbatch of items
with a vectorised hash family (one fused kernel,
:mod:`..ops.hashing`), scatter-add the counts.  Queries are pulls + a
min/median reduction.  The time-aware variant decays the whole table with
one fused scalar multiply per window tick (instead of per-cell timestamp
bookkeeping).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.batched import BatchedWorkerLogic, PushRequest
from ..core.store import ShardedParamStore
from ..ops.hashing import bucket_hash, hash_params, pair_key, sign_hash
from ..utils.initializers import zeros

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class CountMinConfig:
    width: int = 4096
    depth: int = 4
    seed: int = 0

    @property
    def capacity(self) -> int:
        return self.width * self.depth


class CountMinSketch(BatchedWorkerLogic):
    """Count-min over a keyed stream.  Batch: ``key`` (B,) int ids,
    optional ``count`` (B,), ``mask`` (B,).  The store is the flat
    (depth·width,) counter table; row d of the sketch occupies ids
    ``[d·width, (d+1)·width)``."""

    def __init__(self, config: CountMinConfig):
        self.config = config
        self._a, self._b = hash_params(config.depth, config.seed)
        self._row_offset = np.arange(config.depth, dtype=np.int64) * config.width

    def cells(self, keys: Array) -> Array:
        """(B, depth) flat cell ids for each key."""
        buckets = bucket_hash(keys, self._a, self._b, self.config.width)
        return buckets + jnp.asarray(self._row_offset, jnp.int32)[None, :]

    # -- BatchedWorkerLogic -------------------------------------------------
    def init_state(self, rng):
        return ()

    def keys(self, batch: Dict[str, Array]) -> Array:
        return self.cells(batch["key"])

    def step(self, state, batch: Dict[str, Array], pulled: Array):
        counts = batch.get("count")
        if counts is None:
            counts = jnp.ones_like(batch["key"], jnp.float32)
        deltas = jnp.broadcast_to(
            counts.astype(jnp.float32)[:, None], pulled.shape
        )
        mask = batch.get("mask")
        lane_mask = (
            jnp.broadcast_to(mask[:, None], deltas.shape) if mask is not None else None
        )
        # Estimate *before* this batch's increment (streaming pre-count).
        out = {"estimate": jnp.min(pulled, axis=1)}
        return state, PushRequest(self.keys(batch), deltas, lane_mask), out

    def make_store(self, *, mesh=None, **store_opts) -> ShardedParamStore:
        # store_opts passes through scatter_impl/layout: a Zipf text
        # stream hammers the same hot cells every batch, the exact case
        # scatter_impl="xla_sorted" exists for
        return ShardedParamStore.create(
            self.config.capacity, (), init_fn=zeros(()), mesh=mesh,
            **store_opts,
        )

    def query(self, store: ShardedParamStore, keys: Array) -> Array:
        """Point estimate: min over the depth rows' cells."""
        return jnp.min(store.pull(self.cells(keys)), axis=1)

    def top_k(
        self, store: ShardedParamStore, candidate_ids: Array, k: int
    ) -> Tuple[Array, Array]:
        """Heavy hitters among ``candidate_ids``: (estimates, ids) of the
        k largest estimated counts — the streaming-experiment query the
        reference's sketches serve (estimate-then-rank), as one batched
        pull + ``lax.top_k``.  Static (k,) output: padded with -inf/-1
        when there are fewer candidates (the ops/topk.py convention)."""
        from ..ops.topk import _pad_topk

        est = self.query(store, candidate_ids)
        top_est, pos = jax.lax.top_k(est, min(k, candidate_ids.shape[0]))
        ids = jnp.take(candidate_ids, pos)
        top_est, ids = _pad_topk(top_est[None], ids[None], k)
        return top_est[0], ids[0]


class BloomCooccurrence(CountMinSketch):
    """Co-occurrence counting for unordered word pairs — the reference's
    bloom/co-occurrence sketch.  Batch: ``word_a``/``word_b`` (B,).
    Pair ids are formed with a mixing pairing function then count-min
    counted; :meth:`similarity` gives the normalised co-occurrence score
    used for streaming word similarity."""

    PAIR_SPACE = 1 << 30

    def keys(self, batch: Dict[str, Array]) -> Array:
        pk = pair_key(batch["word_a"], batch["word_b"], self.PAIR_SPACE)
        return self.cells(pk)

    def step(self, state, batch: Dict[str, Array], pulled: Array):
        b2 = dict(batch)
        b2["key"] = pair_key(batch["word_a"], batch["word_b"], self.PAIR_SPACE)
        return super().step(state, b2, pulled)

    def query_pair(self, store: ShardedParamStore, a: Array, b: Array) -> Array:
        return self.query(store, pair_key(a, b, self.PAIR_SPACE))

    def similarity(
        self,
        pair_store: ShardedParamStore,
        word_store: ShardedParamStore,
        word_sketch: "CountMinSketch",
        a: Array,
        b: Array,
        eps: float = 1e-6,
    ) -> Array:
        """Cosine-style similarity  c(a,b) / sqrt(c(a) c(b))."""
        cab = self.query_pair(pair_store, a, b)
        ca = word_sketch.query(word_store, a)
        cb = word_sketch.query(word_store, b)
        return cab / jnp.sqrt(jnp.maximum(ca * cb, eps))


@dataclasses.dataclass(frozen=True)
class TugOfWarConfig:
    """AMS F2 sketch: ``num_estimators = groups × per_group`` ±1 counters;
    estimate = median over groups of the mean of squared counters."""

    groups: int = 8
    per_group: int = 16
    seed: int = 1

    @property
    def num_estimators(self) -> int:
        return self.groups * self.per_group


class TugOfWarSketch(BatchedWorkerLogic):
    """Second-moment (F2) sketch over a keyed stream.  Every item updates
    *all* estimators (dense small push): z_j += s_j(key) · count."""

    def __init__(self, config: TugOfWarConfig):
        self.config = config
        self._a, self._b = hash_params(config.num_estimators, config.seed)
        self._est_ids = np.arange(config.num_estimators, dtype=np.int32)

    def init_state(self, rng):
        return ()

    def keys(self, batch: Dict[str, Array]) -> Array:
        B = batch["key"].shape[0]
        return jnp.broadcast_to(
            jnp.asarray(self._est_ids)[None, :], (B, self.config.num_estimators)
        )

    def step(self, state, batch: Dict[str, Array], pulled: Array):
        counts = batch.get("count")
        if counts is None:
            counts = jnp.ones_like(batch["key"], jnp.float32)
        signs = sign_hash(batch["key"], self._a, self._b)  # (B, E)
        deltas = signs * counts.astype(jnp.float32)[:, None]
        mask = batch.get("mask")
        lane_mask = (
            jnp.broadcast_to(mask[:, None], deltas.shape) if mask is not None else None
        )
        return state, PushRequest(self.keys(batch), deltas, lane_mask), {}

    def make_store(self, *, mesh=None, **store_opts) -> ShardedParamStore:
        return ShardedParamStore.create(
            self.config.num_estimators, (), init_fn=zeros(()), mesh=mesh,
            **store_opts,
        )

    def estimate_f2(self, store: ShardedParamStore) -> Array:
        """Median-of-means estimate of Σ f_x² from the counters."""
        z = store.values().reshape(self.config.groups, self.config.per_group)
        means = jnp.mean(z * z, axis=1)
        return jnp.median(means)


def decay(store: ShardedParamStore, gamma: float) -> ShardedParamStore:
    """Time-aware variant: exponentially decay every counter by ``gamma``
    (one fused multiply over the sharded table) — call once per time
    window, the TPU analogue of the reference's time-aware sketches."""
    return ShardedParamStore(store.spec, store.table * gamma)


__all__ = [
    "CountMinConfig",
    "CountMinSketch",
    "BloomCooccurrence",
    "TugOfWarConfig",
    "TugOfWarSketch",
    "decay",
]
