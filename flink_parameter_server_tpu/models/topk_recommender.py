"""Online MF with top-K recommendation serving.

Reference parity (SURVEY.md §2 #8, §3.3): the reference's
``PSOnlineMatrixFactorizationAndTopK`` interleaves top-K item queries with
the rating stream: per event it serves the querying user's top-K items from
the worker-local user vector + pulled item vectors, pruned LEMP-style.

TPU-first: training stays the batched MF step; serving is
:func:`..ops.topk.sharded_topk` — exact MIPS via per-shard MXU matmul +
hierarchical ``top_k`` (output parity with LEMP, not mechanism parity).
``query_topk`` answers a batch of user queries in one jitted call;
``MFWithTopK`` interleaves a query per training microbatch the way the
reference interleaves query events in the input stream.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.store import ShardedParamStore
from ..ops.topk import dense_topk, sharded_topk
from .matrix_factorization import OnlineMatrixFactorization

Array = jax.Array


def _logical_table(spec, table: Array) -> Array:
    """MIPS needs LOGICAL rows; unpacking a lane-packed table is a
    reshape (+ a slice when the physical row carries pad lanes) — free
    under jit, so serving composes with the packed training layout.
    The unpacked view is (padded_capacity, d); ``valid_rows`` masks the
    padding rows at the topk call sites.

    Gate on the layout alone: even at pack == 1 (row width 65-127) the
    physical rows are lane-PADDED to width 128, so the raw table would
    shape-mismatch ``queries @ table.T`` — ``unpack_table`` handles
    pack == 1 by slicing off the pad lanes."""
    if spec.layout == "packed":
        from ..ops.packed import unpack_table

        return unpack_table(table, spec.padded_capacity, spec.row_width)
    return table


def query_topk(
    item_store: ShardedParamStore,
    user_vectors: Array,
    user_ids: Array,
    k: int,
    *,
    exclude: Optional[Array] = None,
) -> Tuple[Array, Array]:
    """Top-k items for ``user_ids`` (B,) given worker-state user vectors.

    ``exclude``: optional (B, E) item ids to mask out (already-rated items
    — the reference's recommenders exclude seen pairs).
    Returns (scores (B,k), item_ids (B,k)).  (The former ``approx_recall``
    parameter was removed — see the ops/topk.py decision note.)
    """
    spec = item_store.spec
    queries = jnp.take(user_vectors, user_ids.astype(jnp.int32), axis=0)

    table = _logical_table(spec, item_store.table)

    if exclude is None:
        if spec.mesh is not None:
            return sharded_topk(
                table, queries, k,
                mesh=spec.mesh, ps_axis=spec.ps_axis,
                valid_rows=spec.capacity,
            )
        return dense_topk(table, queries, k, valid_rows=spec.capacity)

    # With exclusions: over-fetch k+E candidates then drop excluded ones.
    e = exclude.shape[1]
    if spec.mesh is not None:
        scores, ids = sharded_topk(
            table, queries, k + e,
            mesh=spec.mesh, ps_axis=spec.ps_axis, valid_rows=spec.capacity,
        )
    else:
        scores, ids = dense_topk(
            table, queries, k + e, valid_rows=spec.capacity,
        )
    banned = (ids[:, :, None] == exclude[:, None, :]).any(-1)
    scores = jnp.where(banned, -jnp.inf, scores)
    re_scores, pos = jax.lax.top_k(scores, k)
    re_ids = jnp.take_along_axis(ids, pos, axis=1)
    # Lanes that survived only as -inf (banned or padding) carry no real
    # candidate: mark them id -1 like the ops-level padding convention.
    re_ids = jnp.where(jnp.isneginf(re_scores), -1, re_ids)
    return re_scores, re_ids


def make_mf_topk_step(logic: OnlineMatrixFactorization, spec, k: int):
    """Fused train+serve step: MF update plus a top-K answer for the
    batch's ``query_user`` ids — the batched analogue of the reference's
    interleaved query events in the rating stream.

    Queries are served against the *pre-push* table (bounded staleness of
    one microbatch — same semantics as training pulls).  Use in place of
    ``make_train_step`` and jit the result.
    """
    from ..core import store as store_mod

    def step(table, state, batch: Dict[str, Array]):
        ids = logic.keys(batch)
        pulled = store_mod.pull(spec, table, ids)
        new_state, req, out = logic.step(state, batch, pulled)
        if "query_user" in batch:
            q = jnp.take(
                new_state, batch["query_user"].astype(jnp.int32), axis=0
            )
            serve_table = _logical_table(spec, table)
            if spec.mesh is not None:
                scores, top_ids = sharded_topk(
                    serve_table, q, k,
                    mesh=spec.mesh, ps_axis=spec.ps_axis,
                    valid_rows=spec.capacity,
                )
            else:
                scores, top_ids = dense_topk(
                    serve_table, q, k, valid_rows=spec.capacity,
                )
            out = dict(out, topk_scores=scores, topk_ids=top_ids)
        table = store_mod.push(spec, table, req.ids, req.deltas, req.mask)
        return table, new_state, out

    return step


__all__ = ["query_topk", "make_mf_topk_step"]
