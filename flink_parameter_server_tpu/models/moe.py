"""Mixture-of-experts layer with expert parallelism over an ``ep`` axis.

The reference has no expert parallelism (SURVEY.md §2: "EP — NO"); like
pipeline parallelism this exists because distributed scale is first-class
in the rebuild: a sparse-expert FFN whose experts are sharded across the
``ep`` mesh axis, with token routing as ``all_to_all`` over ICI — the
canonical Switch-Transformer-style dispatch.

Semantics (top-1 switch routing with capacity):

  * gate: ``softmax(x @ w_gate)``; each token goes to its argmax expert,
    its output scaled by the gate probability,
  * each expert processes at most ``capacity`` tokens per device shard
    (first-come within the shard's token order); overflow tokens pass
    through the residual unchanged (standard switch behavior),
  * dispatch/return are two ``all_to_all``s over ``ep``: tokens bucketed
    per expert locally, regrouped so each device runs only its local
    experts' FFNs — one MXU batch per local expert.

The dense oracle (:func:`moe_reference`) replicates the identical
capacity/ordering semantics for parity tests.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from ..utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int
    capacity: int  # max tokens PER EXPERT per device shard
    dtype: object = jnp.float32


def init_moe_params(rng: Array, cfg: MoEConfig, mesh: Optional[Mesh] = None,
                    ep_axis: str = "ep") -> Dict:
    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = cfg.d_model**-0.5
    scale_out = cfg.d_ff**-0.5
    params = {
        "w_gate": (
            scale_in * jax.random.normal(k1, (cfg.d_model, cfg.num_experts))
        ).astype(cfg.dtype),
        "w_up": (
            scale_in
            * jax.random.normal(k2, (cfg.num_experts, cfg.d_model, cfg.d_ff))
        ).astype(cfg.dtype),
        "w_down": (
            scale_out
            * jax.random.normal(k3, (cfg.num_experts, cfg.d_ff, cfg.d_model))
        ).astype(cfg.dtype),
    }
    if mesh is not None and ep_axis in mesh.axis_names:
        params["w_up"] = jax.device_put(
            params["w_up"], NamedSharding(mesh, P(ep_axis, None, None))
        )
        params["w_down"] = jax.device_put(
            params["w_down"], NamedSharding(mesh, P(ep_axis, None, None))
        )
    return params


def _route(x: Array, w_gate: Array, num_experts: int, capacity: int):
    """Top-1 routing with per-expert capacity, deterministic in token
    order.  Returns (expert_idx, slot, keep_mask, gate_prob) per token."""
    logits = x @ w_gate.astype(x.dtype)  # (N, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # (N,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    # slot of each token within its expert bucket = running count of
    # earlier tokens routed to the same expert
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.int32)  # (N, E)
    slot = jnp.cumsum(onehot, axis=0) * onehot  # (N, E), 1-based
    slot = jnp.sum(slot, axis=-1) - 1  # (N,) 0-based
    keep = slot < capacity
    return expert, slot, keep, gate.astype(x.dtype)


def _expert_ffn(w_up_e: Array, w_down_e: Array, tokens: Array) -> Array:
    return jax.nn.gelu(tokens @ w_up_e) @ w_down_e


def moe_dense(params: Dict, x: Array, cfg: MoEConfig) -> Array:
    """Efficient single-device MoE (no collectives): bucket tokens per
    expert, one vmapped FFN batch per expert — 1× FLOPs (plus capacity
    padding), identical semantics to :func:`moe_apply` on one shard.
    This is the mesh-less path used by the transformer; the O(E·N)
    :func:`moe_reference` below stays as the independent test oracle."""
    E, C, d = cfg.num_experts, cfg.capacity, cfg.d_model
    expert, slot, keep, gate = _route(x, params["w_gate"], E, C)
    buckets = jnp.zeros((E, C, d), x.dtype)
    buckets = buckets.at[
        jnp.where(keep, expert, E - 1), jnp.clip(slot, 0, C - 1)
    ].add(jnp.where(keep[:, None], x, 0.0))
    y = jax.vmap(_expert_ffn)(params["w_up"], params["w_down"], buckets)
    out = y[jnp.where(keep, expert, E - 1), jnp.clip(slot, 0, C - 1)]
    return jnp.where(keep[:, None], out * gate[:, None], 0.0)


def moe_reference(params: Dict, x: Array, cfg: MoEConfig) -> Array:
    """Dense single-device oracle with identical routing semantics."""
    N = x.shape[0]
    expert, slot, keep, gate = _route(
        x, params["w_gate"], cfg.num_experts, cfg.capacity
    )
    out = jnp.zeros_like(x)
    for e in range(cfg.num_experts):
        sel = (expert == e) & keep
        y = _expert_ffn(params["w_up"][e], params["w_down"][e], x)
        out = out + jnp.where(sel[:, None], y, 0.0)
    return jnp.where(keep[:, None], out * gate[:, None], 0.0)


def moe_apply(
    params: Dict,
    x: Array,
    cfg: MoEConfig,
    *,
    mesh: Mesh,
    ep_axis: str = "ep",
    dp_axis: Optional[str] = "dp",
) -> Array:
    """Expert-parallel MoE FFN: ``x`` (N, d) with N sharded over ``dp``
    (if present), experts sharded over ``ep``.  Returns the gated expert
    outputs (0 for dropped tokens) — add to the residual stream.
    """
    E = cfg.num_experts
    ep = mesh.shape[ep_axis]
    assert E % ep == 0, (E, ep)
    e_local = E // ep
    C = cfg.capacity
    d = cfg.d_model

    if dp_axis is not None and dp_axis not in mesh.axis_names:
        dp_axis = None
    lead = (dp_axis,) if dp_axis else (None,)
    x_spec = P(*lead, None)

    def body(w_gate, w_up, w_down, x_loc):
        n_loc = x_loc.shape[0]
        expert, slot, keep, gate = _route(x_loc, w_gate, E, C)

        # bucket local tokens: (E, C, d); dropped tokens go nowhere
        buckets = jnp.zeros((E, C, d), x_loc.dtype)
        tok_idx = jnp.arange(n_loc)
        buckets = buckets.at[
            jnp.where(keep, expert, E - 1),
            jnp.clip(slot, 0, C - 1),
        ].add(jnp.where(keep[:, None], x_loc, 0.0))

        # dispatch: regroup expert buckets onto their owning ep shard:
        # (E, C, d) = (ep, e_local, C, d) -- all_to_all splits the ep dim
        # here and concatenates the arriving shards' buckets
        dispatched = jax.lax.all_to_all(
            buckets.reshape(ep, e_local, C, d),
            ep_axis,
            split_axis=0,
            concat_axis=0,
        )  # (ep, e_local, C, d): sender s's buckets for my experts
        # run my local experts on every sender's bucket
        y = jax.vmap(
            lambda wu, wd, toks: _expert_ffn(wu, wd, toks),
            in_axes=(0, 0, 1),
            out_axes=1,
        )(w_up, w_down, dispatched)  # (ep, e_local, C, d)

        # return trip: send each sender its processed buckets back
        returned = jax.lax.all_to_all(
            y, ep_axis, split_axis=0, concat_axis=0
        ).reshape(E, C, d)

        # un-bucket: token t reads (expert[t], slot[t])
        out = returned[
            jnp.where(keep, expert, E - 1), jnp.clip(slot, 0, C - 1)
        ]
        return jnp.where(keep[:, None], out * gate[:, None], 0.0)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(None, None),  # gate replicated
            P(ep_axis, None, None),
            P(ep_axis, None, None),
            x_spec,
        ),
        out_specs=x_spec,
        check_vma=False,
    )(params["w_gate"], params["w_up"], params["w_down"], x)


__all__ = [
    "MoEConfig",
    "init_moe_params",
    "moe_apply",
    "moe_dense",
    "moe_reference",
]
