"""Online passive-aggressive classification on the parameter server.

Reference parity (SURVEY.md §2 #9, §3.4):
``PassiveAggressiveParameterServer.transformBinary / transformMulticlass``
— online PA linear classification where the model is a weight vector keyed
by feature id, *sparse*: for each labeled example the worker pulls only the
feature ids with nonzero value (multi-pull), waits for all answers, computes
the margin, applies the PA / PA-I / PA-II update rule (aggressiveness C),
pushes ``τ·y·xᵢ`` per feature, and outputs the prediction.

TPU-first mapping: the per-example multi-pull + countdown-until-complete
bookkeeping (reference worker state) disappears — a microbatch of sparse
examples is padded to ``(B, K)`` (ids, values, feature mask) and the whole
multi-pull is ONE sharded gather; the PA update is fused elementwise math;
all pushes are one sharded scatter-add.  Binary keeps scalar weights
(value_shape ``()``); multiclass keeps a per-feature class-weight row
(value_shape ``(num_classes,)``) so one pull fetches every class's weight —
the reference's per-class vectors re-laid-out for one gather instead of C.
"""
from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp

from ..core.api import WorkerLogic
from ..core.batched import BatchedWorkerLogic, PushRequest
from ..core.store import ShardedParamStore
from ..core.transform import transform_batched
from ..utils.initializers import zeros

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PARule:
    """PA update-step size τ.  variant: "PA" | "PA-I" | "PA-II" with
    aggressiveness C (the reference algorithms' constructor param)."""

    variant: str = "PA-I"
    C: float = 1.0

    def tau(self, loss: Array, sq_norm: Array) -> Array:
        sq = jnp.maximum(sq_norm, 1e-12)
        if self.variant == "PA":
            return loss / sq
        if self.variant == "PA-I":
            return jnp.minimum(self.C, loss / sq)
        if self.variant == "PA-II":
            return loss / (sq + 1.0 / (2.0 * self.C))
        raise ValueError(f"unknown PA variant {self.variant}")


class PassiveAggressiveBinary(BatchedWorkerLogic):
    """Batch keys: ``ids`` (B,K) int, ``values`` (B,K) float, ``feat_mask``
    (B,K) bool, ``label`` (B,) ±1, ``mask`` (B,) bool."""

    def __init__(self, rule: PARule = PARule()):
        self.rule = rule

    def init_state(self, rng: Array):
        return ()  # stateless worker: the model lives entirely on the PS

    def keys(self, batch: Dict[str, Array]) -> Array:
        return batch["ids"]

    def step(self, state, batch: Dict[str, Array], pulled: Array):
        x = batch["values"].astype(jnp.float32)
        fmask = batch["feat_mask"]
        x = jnp.where(fmask, x, 0.0)
        y = batch["label"].astype(jnp.float32)
        w = pulled  # (B, K) scalar weights per present feature
        margin = jnp.sum(w * x, axis=-1)
        loss = jnp.maximum(0.0, 1.0 - y * margin)
        tau = self.rule.tau(loss, jnp.sum(x * x, axis=-1))
        deltas = (tau * y)[:, None] * x  # (B, K)
        mask = fmask & batch["mask"][:, None]
        out = {
            "prediction": jnp.sign(margin),
            "margin": margin,
            "loss": loss * batch["mask"],
        }
        return state, PushRequest(batch["ids"], deltas, mask), out


class PassiveAggressiveMulticlass(BatchedWorkerLogic):
    """Multiclass PA (max-margin violator): per-feature class-weight rows.

    τ = loss / (2‖x‖²) — the multiclass PA scaling (the update touches two
    class rows per feature, hence the factor 2 in the squared norm).
    """

    def __init__(self, num_classes: int, rule: PARule = PARule()):
        self.num_classes = num_classes
        self.rule = rule

    def init_state(self, rng: Array):
        return ()

    def keys(self, batch: Dict[str, Array]) -> Array:
        return batch["ids"]

    def step(self, state, batch: Dict[str, Array], pulled: Array):
        x = jnp.where(batch["feat_mask"], batch["values"].astype(jnp.float32), 0.0)
        y = batch["label"].astype(jnp.int32)  # (B,) class index
        w = pulled  # (B, K, C)
        scores = jnp.einsum("bk,bkc->bc", x, w)
        B, C = scores.shape
        true_score = jnp.take_along_axis(scores, y[:, None], axis=1)[:, 0]
        # highest-scoring wrong class
        masked = scores.at[jnp.arange(B), y].set(-jnp.inf)
        runner = jnp.argmax(masked, axis=1)
        runner_score = jnp.max(masked, axis=1)
        loss = jnp.maximum(0.0, 1.0 - (true_score - runner_score))
        tau = self.rule.tau(loss, 2.0 * jnp.sum(x * x, axis=-1))
        onehot_y = jax.nn.one_hot(y, C)
        onehot_r = jax.nn.one_hot(runner, C)
        direction = onehot_y - onehot_r  # (B, C)
        deltas = tau[:, None, None] * x[:, :, None] * direction[:, None, :]
        mask = batch["feat_mask"] & batch["mask"][:, None]
        out = {
            "prediction": jnp.argmax(scores, axis=1),
            "loss": loss * batch["mask"],
        }
        return state, PushRequest(batch["ids"], deltas, mask), out


def transform_binary(
    data,
    *,
    num_features: int,
    rule: PARule = PARule(),
    mesh=None,
    **kwargs,
):
    """Reference ``transformBinary`` analogue: returns TransformResult;
    ``result.store.values()`` is the final weight vector."""
    logic = PassiveAggressiveBinary(rule)
    store = ShardedParamStore.create(
        num_features, (), init_fn=zeros(()), mesh=mesh
    )
    return transform_batched(data, logic, store, mesh=mesh, **kwargs)


def transform_multiclass(
    data,
    *,
    num_features: int,
    num_classes: int,
    rule: PARule = PARule(),
    mesh=None,
    **kwargs,
):
    logic = PassiveAggressiveMulticlass(num_classes, rule)
    store = ShardedParamStore.create(
        num_features, (num_classes,), init_fn=zeros((num_classes,)), mesh=mesh
    )
    return transform_batched(data, logic, store, mesh=mesh, **kwargs)


class PABinaryWorkerLogic(WorkerLogic):
    """Event-API binary PA — the reference's per-example multi-pull with a
    countdown until all feature answers arrive (SURVEY.md §3.4), for
    semantics-parity tests."""

    def __init__(self, rule: PARule = PARule()):
        import collections

        self.rule = rule
        self.pending: Dict[int, dict] = {}
        # param_id -> FIFO of pending-example keys awaiting that answer:
        # O(1) per pull answer instead of a linear scan over all pending
        # examples (which goes quadratic on real streams).
        self._waiting: Dict[int, "collections.deque"] = (
            collections.defaultdict(collections.deque)
        )
        self._next = 0

    def on_recv(self, data, ps):
        ids, values, label = data
        ex = {
            "ids": list(ids),
            "values": dict(zip(ids, values)),
            "label": label,
            "missing": set(ids),
            "weights": {},
        }
        self.pending[self._next] = ex
        for fid in ids:
            self._waiting[fid].append(self._next)
            ps.pull(fid)
        self._next += 1

    def on_pull_recv(self, param_id, param_value, ps):
        import numpy as np

        done = []
        q = self._waiting.get(param_id)
        # Answers go to the oldest example still missing this id — the
        # same order the previous insertion-ordered scan produced.
        while q:
            key = q.popleft()
            ex = self.pending.get(key)
            if ex is None or param_id not in ex["missing"]:
                continue  # stale entry (duplicate id within one example)
            ex["weights"][param_id] = param_value
            ex["missing"].discard(param_id)
            if not ex["missing"]:
                done.append(key)
            break  # one answer satisfies one outstanding pull
        if q is not None and not q:
            # don't leak one empty deque per distinct feature id ever seen
            del self._waiting[param_id]
        for key in done:
            ex = self.pending.pop(key)
            x = np.array([ex["values"][i] for i in ex["ids"]], np.float32)
            w = np.array([ex["weights"][i] for i in ex["ids"]], np.float32)
            y = float(ex["label"])
            margin = float(w @ x)
            loss = max(0.0, 1.0 - y * margin)
            tau = float(self.rule.tau(jnp.asarray(loss), jnp.asarray(float(x @ x))))
            for fid, xi in zip(ex["ids"], x):
                ps.push(fid, tau * y * float(xi))
            ps.output((ex["label"], np.sign(margin), margin))


__all__ = [
    "PARule",
    "PassiveAggressiveBinary",
    "PassiveAggressiveMulticlass",
    "PABinaryWorkerLogic",
    "transform_binary",
    "transform_multiclass",
]
