"""Decoder-only Transformer LM — the dense data-parallel config.

Reference parity: BASELINE.json config #5 ("Transformer-base LM
data-parallel — dense allreduce — stretch the PS API").  The model trains
through :class:`..core.dense.DenseParameterServer` (pull all / push grad);
this module supplies the TPU-shaped model itself.

TPU-first layout (Megatron-style named-axis sharding, XLA inserts the
collectives):

  * ``dp``  — batch;  gradients psum over dp = the "dense allreduce".
  * ``tp``  — attention heads + MLP hidden: QKV/up projections column
    -sharded ``P(None, 'tp')``, output/down row-sharded ``P('tp', None)``.
  * ``sp``  — sequence: activations sharded on T; attention runs
    :func:`..parallel.ring_attention.ring_attention` over the ICI ring
    (long-context support the reference never had).

bfloat16 parameters/activations with fp32 RMSNorm/softmax accumulation —
the MXU-native dtype choice.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.ring_attention import (
    reference_attention,
    ring_attention,
    ring_attention_inner,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16
    use_ring_attention: bool = False
    # Attention implementation for the non-ring path
    # (ops/flash_attention.py): "auto" uses the TPU splash flash kernel
    # when eligible (TPU backend, T % 128 == 0, head_dim % 64 == 0, and
    # either no mesh or a dp-ONLY mesh dividing the batch — dp shards
    # run the kernel independently under shard_map) and the O(T²)
    # reference path otherwise; "on" forces it (raising if ineligible);
    # "off" always uses the reference path.  Ring attention (sp meshes)
    # takes precedence — this knob only governs the non-ring fallback.
    flash_attention: str = "auto"
    # rematerialise each block in the backward pass (jax.checkpoint):
    # activation memory per layer drops from O(T·d_ff) to O(T·d_model),
    # the long-context lever (docs/scaling.md "Memory levers")
    remat: bool = False
    dp_axis: Optional[str] = "dp"
    tp_axis: Optional[str] = None
    sp_axis: Optional[str] = None
    pp_axis: Optional[str] = None  # pipeline stages (forward_pipelined)
    # sparse-expert MLPs (models/moe.py): num_experts > 0 replaces every
    # layer's dense MLP with a top-1 switch MoE, experts sharded over
    # ep_axis (expert parallelism)
    num_experts: int = 0
    ep_axis: Optional[str] = None
    moe_capacity: int = 0

    def __post_init__(self):
        if self.flash_attention not in ("auto", "on", "off"):
            raise ValueError(
                f"flash_attention must be 'auto', 'on' or 'off', got "
                f"{self.flash_attention!r}"
            )
        if self.num_experts > 0:
            assert self.moe_capacity > 0, (
                "num_experts > 0 requires moe_capacity > 0 (capacity 0 "
                "would silently drop every token)"
            )

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def _spec(mesh: Optional[Mesh], *axes) -> Optional[NamedSharding]:
    if mesh is None:
        return None
    axes = tuple(a if a in mesh.axis_names else None for a in axes)
    return NamedSharding(mesh, P(*axes))


def param_shardings(cfg: TransformerConfig, mesh: Optional[Mesh]) -> Dict:
    """Named-axis sharding tree for the parameter pytree."""
    tp = cfg.tp_axis
    layer = {
        "attn_norm": _spec(mesh, None),
        "wqkv": _spec(mesh, None, tp),  # column parallel
        "wo": _spec(mesh, tp, None),  # row parallel
        "mlp_norm": _spec(mesh, None),
    }
    if cfg.num_experts > 0:
        ep = cfg.ep_axis
        layer["moe"] = {
            "w_gate": _spec(mesh, None, None),
            "w_up": _spec(mesh, ep, None, None),
            "w_down": _spec(mesh, ep, None, None),
        }
    else:
        layer["w_up"] = _spec(mesh, None, tp)
        layer["w_down"] = _spec(mesh, tp, None)
    return {
        "embed": _spec(mesh, None, None),
        "final_norm": _spec(mesh, None),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


def init_params(rng: Array, cfg: TransformerConfig, mesh: Optional[Mesh] = None) -> Dict:
    """Initialise the parameter pytree, placed onto its shardings."""
    k_embed, k_layers = jax.random.split(rng)
    d, h, f = cfg.d_model, cfg.n_heads, cfg.d_ff

    def dense(key, shape, scale):
        return (scale * jax.random.normal(key, shape, jnp.float32)).astype(
            cfg.dtype
        )

    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.fold_in(k_layers, i)
        k1, k2, k3, k4 = jax.random.split(k, 4)
        layer = {
            "attn_norm": jnp.ones((d,), jnp.float32),
            "wqkv": dense(k1, (d, 3 * d), d**-0.5),
            "wo": dense(k2, (d, d), (2 * cfg.n_layers * d) ** -0.5),
            "mlp_norm": jnp.ones((d,), jnp.float32),
        }
        if cfg.num_experts > 0:
            from .moe import MoEConfig, init_moe_params

            moe_cfg = MoEConfig(
                d_model=d, d_ff=f, num_experts=cfg.num_experts,
                capacity=cfg.moe_capacity, dtype=cfg.dtype,
            )
            # mesh=None: placement happens once, via param_shardings below
            layer["moe"] = init_moe_params(k3, moe_cfg, None)
        else:
            layer["w_up"] = dense(k3, (d, f), d**-0.5)
            layer["w_down"] = dense(k4, (f, d), (2 * cfg.n_layers * f) ** -0.5)
        layers.append(layer)
    params = {
        # small embed init: with tied output weights a unit-scale embedding
        # makes initial logits (and loss) explode
        "embed": dense(k_embed, (cfg.vocab_size, d), 0.02),
        "final_norm": jnp.ones((d,), jnp.float32),
        "layers": layers,
    }
    shardings = param_shardings(cfg, mesh)
    if mesh is not None:
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s) if s is not None else x,
            params,
            shardings,
        )
    return params


def _rmsnorm(x: Array, gain: Array) -> Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (xf * scale * gain).astype(x.dtype)


def _rope(x: Array, positions: Array) -> Array:
    """Rotary position embedding on (B, T, H, D)."""
    D = x.shape[-1]
    half = D // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs  # B,T,1,half
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _unsharded_attention(
    q: Array, k: Array, v: Array, cfg: TransformerConfig,
    mesh: Optional[Mesh],
) -> Array:
    """The non-ring attention path: splash flash kernel when eligible
    (see TransformerConfig.flash_attention), else the O(T²) reference.

    Flash runs meshless (single-chip jit), or on a dp-ONLY mesh via a
    per-shard shard_map (attention never mixes batch rows).  sp/tp/pp
    meshes keep the ring/reference paths — a bare pallas_call under
    auto-sharded pjit on those would force XLA to gather the batch."""
    from ..ops import flash_attention as _flash

    B, T, Dh = q.shape[0], q.shape[1], q.shape[3]
    if cfg.flash_attention == "off":
        return reference_attention(q, k, v)
    if _flash.eligible(T, Dh, mesh):
        return _flash.flash_mha(q, k, v)
    # dp dispatch honors the config's axis naming: dp_axis=None means
    # "no data-parallel axis" — never probe a literal 'dp' in that case
    # (same convention as the activation-sharding constraints below)
    dp_axis = (
        cfg.dp_axis
        if (mesh is not None and cfg.dp_axis
            and cfg.dp_axis in mesh.axis_names)
        else None
    )
    if dp_axis is not None and _flash.eligible_dp(T, Dh, B, mesh, dp_axis):
        return _flash.flash_mha_dp(q, k, v, mesh=mesh, dp_axis=dp_axis)
    if cfg.flash_attention == "on":
        # interpret-mode pallas at model sizes is an effective hang, and
        # a silent reference fallback would mislabel benchmarks — "on"
        # means the kernel or an error.  (Tests that want interpret mode
        # call flash_mha(interpret=True) directly.)
        raise ValueError(
            f"flash_attention='on' but the flash path is ineligible "
            f"(backend={jax.default_backend()!r}, T={T}, head_dim={Dh}, "
            f"mesh={None if mesh is None else dict(mesh.shape)}); flash "
            f"needs the TPU backend, T % 128 == 0, head_dim % 64 == 0, "
            f"and no mesh or a dp-only mesh dividing the batch. Use "
            f"'auto' to fall back gracefully."
        )
    return reference_attention(q, k, v)


def _apply_block(
    x: Array,
    layer: Dict,
    cfg: TransformerConfig,
    mesh: Optional[Mesh],
    constrain=None,
    ring_inner: Optional[Dict] = None,
) -> Array:
    """One pre-norm residual block (attention + MLP) on (B, T, d).

    ``constrain``: optional activation-sharding anchor applied to the
    attention-residual output (keeps XLA's propagation from resharding
    mid-block on dp/sp meshes).

    ``ring_inner``: set when this block already runs INSIDE a shard_map
    (pipeline stages) whose mesh carries the sp axis — shard_maps don't
    nest, so attention uses :func:`ring_attention_inner` directly.  Keys:
    ``sp_axis``, ``num_blocks``, and ``pos_offset`` (this shard's global
    position of local token 0, for RoPE)."""
    B, T, _d = x.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    if ring_inner is not None:
        positions = positions + ring_inner["pos_offset"]
    h = _rmsnorm(x, layer["attn_norm"])
    qkv = h @ layer["wqkv"]  # (B, T, 3·d)
    qkv = qkv.reshape(B, T, 3, H, Dh)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _rope(q, positions)
    k = _rope(k, positions)
    if ring_inner is not None:
        attn = ring_attention_inner(
            q, k, v,
            sp_axis=ring_inner["sp_axis"],
            num_blocks=ring_inner["num_blocks"],
        )
    elif (
        cfg.use_ring_attention
        and mesh is not None
        and cfg.sp_axis
        and cfg.sp_axis in mesh.axis_names
    ):
        attn = ring_attention(
            q, k, v,
            mesh=mesh,
            sp_axis=cfg.sp_axis,
            dp_axis=cfg.dp_axis if cfg.dp_axis in mesh.axis_names else None,
            tp_axis=cfg.tp_axis if cfg.tp_axis in mesh.axis_names else None,
        )
    else:
        attn = _unsharded_attention(q, k, v, cfg, mesh)
    attn = attn.reshape(B, T, H * Dh)
    x = x + attn @ layer["wo"]
    if constrain is not None:
        x = constrain(x)
    h = _rmsnorm(x, layer["mlp_norm"])
    if "moe" in layer:
        from .moe import MoEConfig, moe_apply, moe_dense

        moe_cfg = MoEConfig(
            d_model=cfg.d_model, d_ff=cfg.d_ff,
            num_experts=cfg.num_experts, capacity=cfg.moe_capacity,
            dtype=cfg.dtype,
        )
        flat = h.reshape(B * T, cfg.d_model)
        if mesh is not None and cfg.ep_axis and cfg.ep_axis in mesh.axis_names:
            y = moe_apply(
                layer["moe"], flat, moe_cfg, mesh=mesh,
                ep_axis=cfg.ep_axis,
                dp_axis=cfg.dp_axis if cfg.dp_axis in mesh.axis_names else None,
            )
        else:
            y = moe_dense(layer["moe"], flat, moe_cfg)
        return x + y.reshape(B, T, cfg.d_model)
    return x + jax.nn.gelu(h @ layer["w_up"]) @ layer["w_down"]


def forward(
    params: Dict,
    tokens: Array,
    cfg: TransformerConfig,
    *,
    mesh: Optional[Mesh] = None,
) -> Array:
    """Causal LM forward: (B, T) int tokens → (B, T, vocab) fp32 logits.

    With ``cfg.sp_axis`` set, T is sharded over ``sp`` and positions are
    global (the caller shards tokens with ``P(dp, sp)``).
    """
    B, T = tokens.shape
    assert T <= cfg.max_seq, f"sequence length {T} > max_seq {cfg.max_seq}"

    act_spec = None
    if mesh is not None:
        act_spec = P(
            cfg.dp_axis if cfg.dp_axis in mesh.axis_names else None,
            cfg.sp_axis if (cfg.sp_axis and cfg.sp_axis in mesh.axis_names) else None,
            None,
        )

    def constrain(x, spec=None):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec if spec is not None else act_spec)
        )

    x = jnp.take(params["embed"], tokens, axis=0)
    x = constrain(x)

    def block(x, layer):
        x = _apply_block(x, layer, cfg, mesh, constrain=constrain)
        return constrain(x)

    if cfg.remat:
        block = jax.checkpoint(block)
    for layer in params["layers"]:
        x = block(x, layer)

    x = _rmsnorm(x, params["final_norm"])
    logits = (x @ params["embed"].T.astype(x.dtype)).astype(jnp.float32)
    return logits


def forward_pipelined(
    params: Dict,
    tokens: Array,
    cfg: TransformerConfig,
    *,
    mesh: Mesh,
    num_microbatches: int = 4,
) -> Array:
    """Causal LM forward with the layer stack pipelined over
    ``cfg.pp_axis`` (GPipe schedule, :mod:`..parallel.pipeline`):
    each stage holds ``n_layers / pp`` blocks; microbatches stream
    through the stage ring.  Embed / final norm / logits run replicated
    outside the pipeline.  With ``cfg.use_ring_attention`` + a mesh that
    also carries ``cfg.sp_axis``, the sequence dim stays sp-sharded
    through the pipeline and each stage runs ring attention over the sp
    ring (PP × SP composition)."""
    from ..parallel.pipeline import pipeline_apply, stack_stage_params

    assert cfg.pp_axis and cfg.pp_axis in mesh.axis_names
    S = mesh.shape[cfg.pp_axis]
    B, T = tokens.shape
    assert T <= cfg.max_seq, f"sequence length {T} > max_seq {cfg.max_seq}"

    x = jnp.take(params["embed"], tokens, axis=0)
    stage_params = stack_stage_params(
        params["layers"], S, mesh=mesh, pp_axis=cfg.pp_axis
    )

    # stage blocks run INSIDE a shard_map over the pp mesh with
    # mesh=None — without pinning flash off, the "mesh is None implies
    # single-chip" gate in _unsharded_attention would let the splash
    # kernel fire inside the pipeline (an un-validated composition);
    # attention inside stages is ring (sp) or the reference path.
    # "on" must not silently become the reference path — same contract
    # as _unsharded_attention: the kernel or an error.
    if cfg.flash_attention == "on":
        raise ValueError(
            "flash_attention='on' is not supported in forward_pipelined "
            "(the splash kernel inside pipeline stages is an "
            "un-validated composition); use 'auto' or 'off'"
        )
    block_cfg = dataclasses.replace(
        cfg, use_ring_attention=False, flash_attention="off"
    )
    use_sp = bool(
        cfg.use_ring_attention
        and cfg.sp_axis
        and cfg.sp_axis in mesh.axis_names
    )
    if use_sp:
        sp_size = mesh.shape[cfg.sp_axis]
        assert T % sp_size == 0, (
            f"sequence length {T} not divisible by the sp axis size "
            f"{sp_size}"
        )
    x_tail_spec = (cfg.sp_axis, None) if use_sp else None

    def stage_fn(stage_local, x_mb):
        ring_inner = None
        if use_sp:
            t_local = x_mb.shape[1]
            ring_inner = {
                "sp_axis": cfg.sp_axis,
                "num_blocks": mesh.shape[cfg.sp_axis],
                "pos_offset": jax.lax.axis_index(cfg.sp_axis) * t_local,
            }

        # stage_local leaves: (layers_per_stage, ...) — scan the blocks
        def step(carry, layer):
            return (
                _apply_block(carry, layer, block_cfg, None,
                             ring_inner=ring_inner),
                None,
            )

        if cfg.remat:  # the long-context memory lever applies per block
            step = jax.checkpoint(step)
        out, _ = jax.lax.scan(step, x_mb, stage_local)
        return out

    x = pipeline_apply(
        stage_params, x, stage_fn,
        mesh=mesh,
        pp_axis=cfg.pp_axis,
        dp_axis=cfg.dp_axis,
        num_microbatches=num_microbatches,
        x_tail_spec=x_tail_spec,
    )
    x = _rmsnorm(x, params["final_norm"])
    return (x @ params["embed"].T.astype(x.dtype)).astype(jnp.float32)


def next_token_xent(
    logits: Array, tokens: Array, row_mask: Optional[Array] = None
) -> Array:
    """Next-token cross entropy from logits: targets = tokens shifted
    left; last position masked; optional (B,) or (B, T) row mask."""
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = jnp.ones_like(nll).at[:, -1].set(0.0)
    if row_mask is not None:
        if row_mask.ndim == 1:  # (B,) row mask from microbatches()
            row_mask = row_mask[:, None]
        mask = mask * row_mask
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def lm_loss(params: Dict, batch: Dict[str, Array], cfg: TransformerConfig,
            *, mesh: Optional[Mesh] = None) -> Array:
    """Next-token cross entropy through :func:`forward`."""
    tokens = batch["tokens"]
    logits = forward(params, tokens, cfg, mesh=mesh)
    return next_token_xent(logits, tokens, batch.get("mask"))


__all__ = [
    "TransformerConfig",
    "init_params",
    "param_shardings",
    "forward",
    "forward_pipelined",
    "lm_loss",
]
