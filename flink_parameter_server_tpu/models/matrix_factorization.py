"""Online matrix factorization on the parameter server.

Reference parity (SURVEY.md §2 #7, §3.2/§3.3): the canonical example of
``flink-parameter-server`` — ``PSOnlineMatrixFactorization.psOnlineMF``:

  * **user vectors live in worker state** (partitioned across workers),
  * **item vectors live on the PS** (sharded across server subtasks),
  * per rating (u, i, r): pull item vector → SGD on the (user, item) pair →
    update the local user vector, push the item delta,
  * ``SGDUpdater`` carries learning rate + regularisation,
  * per-id deterministic random init (ranged random factor descriptors).

TPU-first mapping: a *microbatch of ratings* is one jitted step.  The user
table is a dp-sharded ``(num_users, dim)`` array (worker state), the item
table a ps-sharded :class:`ShardedParamStore`.  Pull is a sharded gather of
the batch's item ids; the SGD math is one fused elementwise+matmul block on
the MXU; user updates are a local scatter-add; item deltas are one sharded
scatter-add push.  Duplicate users/items inside a batch combine additively —
the same hogwild-style interleaving the reference embraces across workers
(SURVEY.md §2 "Asynchrony"), here bounded to one microbatch.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from ..utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.api import WorkerLogic
from ..core.batched import BatchedWorkerLogic, PushRequest
from ..core.store import ShardedParamStore
from ..parallel.mesh import DP_AXIS
from ..utils.initializers import ranged_random_factor

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class SGDUpdater:
    """The reference's ``SGDUpdater`` (learn rate + L2 regularisation) as a
    pure vectorised function over a batch of (user_vec, item_vec, rating)."""

    learning_rate: float = 0.01
    regularization: float = 0.0

    def delta(
        self, rating: Array, user_vec: Array, item_vec: Array
    ) -> Tuple[Array, Array, Array]:
        """Returns (user_delta, item_delta, prediction); batch-shaped."""
        pred = jnp.sum(user_vec * item_vec, axis=-1)
        err = (rating - pred)[..., None]
        lr = self.learning_rate
        reg = self.regularization
        user_delta = lr * (err * item_vec - reg * user_vec)
        item_delta = lr * (err * user_vec - reg * item_vec)
        return user_delta, item_delta, pred


class OnlineMatrixFactorization(BatchedWorkerLogic):
    """Batched MF worker logic: user factors = worker state, item factors =
    PS store.  Batches are dicts with keys ``user``, ``item``, ``rating``,
    ``mask`` (see :func:`..data.streams.microbatches`)."""

    def __init__(
        self,
        num_users: int,
        dim: int,
        *,
        updater: SGDUpdater = SGDUpdater(),
        seed: int = 0,
        init_low: float = -0.01,
        init_high: float = 0.01,
        mesh: Optional[Mesh] = None,
        dp_axis: str = DP_AXIS,
        dtype=jnp.float32,
        dedup_scale: bool = False,
        num_items: Optional[int] = None,
        state_scatter: str = "xla",
    ):
        self.num_users = num_users
        self.dim = dim
        self.updater = updater
        self.seed = seed
        self.init_low = init_low
        self.init_high = init_high
        self.mesh = mesh
        self.dp_axis = dp_axis
        self.dtype = dtype
        # dedup_scale: combine duplicate-id deltas within a batch by MEAN
        # instead of SUM (ops/dedup.py).  At very large microbatches a
        # Zipf-hot user/item otherwise takes count x lr effective steps
        # from one pulled snapshot and SGD diverges; mean-combining keeps
        # the step bounded regardless of batch size (staleness knob).
        self.dedup_scale = dedup_scale
        self.num_items = num_items
        if dedup_scale and num_items is None:
            raise ValueError("dedup_scale=True requires num_items")
        # state_scatter="xla_sorted": the worker-state update combines
        # duplicate-user deltas before the scatter (ops/sorted_scatter)
        # — the same XLA RMW-serialization fix the store side gets from
        # scatter_impl="xla_sorted"; hot users serialize the plain
        # scatter exactly like hot items do.
        if state_scatter not in ("xla", "xla_sorted"):
            raise ValueError(
                f"state_scatter={state_scatter!r}: xla|xla_sorted"
            )
        self.state_scatter = state_scatter

    # -- BatchedWorkerLogic ------------------------------------------------
    def init_state(self, rng: Array) -> Array:
        init = ranged_random_factor(
            self.seed, (self.dim,), low=self.init_low, high=self.init_high,
            dtype=self.dtype,
        )
        ids = jnp.arange(self.num_users, dtype=jnp.int32)
        if self.mesh is not None and self.dp_axis in self.mesh.axis_names:
            sharding = NamedSharding(self.mesh, P(self.dp_axis, None))
            return jax.jit(init, out_shardings=sharding)(ids)
        return init(ids)

    def keys(self, batch: Dict[str, Array]) -> Array:
        return batch["item"]

    def step(self, state: Array, batch: Dict[str, Array], pulled: Array):
        users = batch["user"].astype(jnp.int32)
        ratings = batch["rating"].astype(self.dtype)
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(users.shape, bool)

        user_vecs = jnp.take(state, users, axis=0)
        user_delta, item_delta, pred = self.updater.delta(
            ratings, user_vecs, pulled
        )
        if self.dedup_scale:
            from ..ops.dedup import occurrence_scale

            u_scale = occurrence_scale(users, self.num_users, mask)
            i_scale = occurrence_scale(
                batch["item"].astype(jnp.int32), self.num_items, mask
            )
            user_delta = user_delta * u_scale[..., None].astype(self.dtype)
            item_delta = item_delta * i_scale[..., None].astype(self.dtype)
        m = mask[..., None].astype(self.dtype)
        if self.state_scatter == "xla_sorted":
            from ..ops.sorted_scatter import sorted_dedup_scatter_add

            state = sorted_dedup_scatter_add(
                state, users, user_delta * m, mask
            )
        else:
            state = state.at[users].add(user_delta * m, mode="drop")
        out = {"prediction": pred, "error": (ratings - pred) * mask}
        return state, PushRequest(batch["item"], item_delta, mask), out

    def finish(self, state: Array):
        # close()-time worker dump: the final user factors (the reference's
        # workers emit updated (user, vector) records).
        return {"user_factors": state}


def ps_online_mf(
    ratings,
    *,
    num_users: int,
    num_items: int,
    dim: int = 16,
    learning_rate: float = 0.05,
    regularization: float = 0.0,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    dedup_scale: bool = False,
    scatter_impl: str = "xla",
    layout: str = "dense",
    state_scatter: Optional[str] = None,
    **transform_kwargs,
):
    """End-to-end wrapper mirroring ``PSOnlineMatrixFactorization.psOnlineMF``
    (SURVEY.md §3.3): build the item store + MF worker and run ``transform``.

    ``ratings``: iterable of microbatch dicts (user, item, rating, mask).
    Returns the :class:`TransformResult`; ``result.store.values()`` is the
    final item-factor matrix, ``result.worker_state`` the user factors.

    ``scatter_impl`` / ``layout`` reach the item store (see
    :class:`~..core.store.StoreSpec`); ``state_scatter`` the user-state
    update — it defaults to following ``scatter_impl``, since hot users
    serialize the state RMW exactly like hot items do.
    """
    from ..core.transform import transform_batched

    if state_scatter is None:
        state_scatter = (
            "xla_sorted" if scatter_impl == "xla_sorted" else "xla"
        )
    logic = OnlineMatrixFactorization(
        num_users,
        dim,
        updater=SGDUpdater(learning_rate, regularization),
        seed=seed,
        mesh=mesh,
        dedup_scale=dedup_scale,
        num_items=num_items if dedup_scale else None,
        state_scatter=state_scatter,
    )
    store = ShardedParamStore.create(
        num_items,
        (dim,),
        init_fn=ranged_random_factor(seed + 1, (dim,)),
        mesh=mesh,
        scatter_impl=scatter_impl,
        layout=layout,
    )
    return transform_batched(
        ratings, logic, store, rng=jax.random.PRNGKey(seed), mesh=mesh,
        **transform_kwargs,
    )


def make_locality_mf_step(
    logic: OnlineMatrixFactorization,
    spec,
    mesh: Mesh,
    *,
    dp_axis: str = DP_AXIS,
    ps_axis: str = "ps",
):
    """The whole MF step fused into ONE ``shard_map`` over (dp × ps) —
    the explicit-collective alternative to the jit-auto path.

    Contract: batches must be partition-aligned by user
    (:func:`..data.streams.partitioned_microbatches` with ``key="user"``,
    ``capacity=num_users``) and ``num_users`` divisible by the dp size;
    the user table is then dp-block-sharded and its gather/scatter is
    purely local.  The only collectives per step are the pull's ``psum``
    over ``ps`` and one ``all_gather`` of (ids, deltas) over ``dp`` for
    the push — the reference's entire message plane as two ICI ops
    (SURVEY.md §2 "TPU-native equivalent").  Out-of-partition users are
    masked out defensively (a violation of the alignment contract drops
    those updates rather than corrupting other shards' rows).

    Use: ``step = jax.jit(make_locality_mf_step(logic, store.spec, mesh))``
    then ``table, state, out = step(store.table, state, batch)``.
    """
    dp = mesh.shape[dp_axis]
    ps = mesh.shape[ps_axis]
    assert spec.padded_capacity % ps == 0, (
        f"store padded capacity {spec.padded_capacity} not divisible by the "
        f"mesh ps size {ps} — build the store with this mesh"
    )
    rows = spec.padded_capacity // ps
    assert logic.num_users % dp == 0, (logic.num_users, dp)
    users_per_shard = logic.num_users // dp
    updater = logic.updater
    dtype = logic.dtype

    def body(local_table, local_state, batch):
        # batches MUST carry a "mask" key (shard_map's in_specs are a
        # fixed pytree); partitioned_microbatches always emits one
        users = batch["user"].astype(jnp.int32)
        items = batch["item"].astype(jnp.int32)
        ratings = batch["rating"].astype(dtype)
        mask = batch["mask"]

        # -- pull: each ps shard answers its rows, one psum assembles ----
        ps_idx = jax.lax.axis_index(ps_axis)
        lo = ps_idx * rows
        rel = items - lo
        hit = (rel >= 0) & (rel < rows)
        vals = jnp.take(local_table, jnp.clip(rel, 0, rows - 1), axis=0)
        vals = jnp.where(hit[:, None], vals, jnp.zeros_like(vals))
        pulled = jax.lax.psum(vals, ps_axis)

        # -- local user state (alignment contract: users live here) ------
        dp_idx = jax.lax.axis_index(dp_axis)
        ulo = dp_idx * users_per_shard
        urel = users - ulo
        uvalid = (urel >= 0) & (urel < users_per_shard) & mask
        urel = jnp.clip(urel, 0, users_per_shard - 1)
        user_vecs = jnp.take(local_state, urel, axis=0)

        user_delta, item_delta, pred = updater.delta(ratings, user_vecs, pulled)
        um = uvalid[:, None].astype(dtype)
        local_state = local_state.at[urel].add(user_delta * um)

        # -- push: all_gather the microbatch over dp, local scatter ------
        # gate on uvalid, not mask: an out-of-partition user's item delta
        # was computed from the wrong (clipped) user row and must be
        # dropped, matching the docstring's contract-violation semantics
        g_items = jax.lax.all_gather(items, dp_axis, tiled=True)
        g_deltas = jax.lax.all_gather(
            item_delta * uvalid[:, None].astype(dtype), dp_axis, tiled=True
        )
        rel2 = g_items - lo
        hit2 = (rel2 >= 0) & (rel2 < rows)
        g_deltas = jnp.where(hit2[:, None], g_deltas, jnp.zeros_like(g_deltas))
        local_table = local_table.at[jnp.clip(rel2, 0, rows - 1)].add(
            g_deltas.astype(local_table.dtype)
        )

        out = {"prediction": pred, "error": (ratings - pred) * uvalid}
        return local_table, local_state, out

    batch_spec = {
        "user": P(dp_axis),
        "item": P(dp_axis),
        "rating": P(dp_axis),
        "mask": P(dp_axis),
    }
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(ps_axis, None), P(dp_axis, None), batch_spec),
        out_specs=(
            P(ps_axis, None),
            P(dp_axis, None),
            {"prediction": P(dp_axis), "error": P(dp_axis)},
        ),
        check_vma=False,
    )


class MFWorkerLogic(WorkerLogic):
    """Event-API MF worker — the literal reference programming model
    (SURVEY.md §3.2): buffer the rating, pull the item vector, on answer run
    SGD, update the local user vector, push the item delta.

    Exists for semantics-parity tests and as the migration example from the
    reference's callback style; the batched logic above is the TPU path.
    """

    def __init__(
        self,
        dim: int,
        updater: SGDUpdater = SGDUpdater(),
        seed: int = 0,
        init_low: float = -0.01,
        init_high: float = 0.01,
    ):
        self.dim = dim
        self.updater = updater
        self._init = ranged_random_factor(seed, (dim,), low=init_low, high=init_high)
        self.user_vectors: Dict[int, Any] = {}
        self.pending: Dict[int, list] = {}

    def _user_vec(self, u: int):
        if u not in self.user_vectors:
            import numpy as np

            self.user_vectors[u] = np.asarray(self._init(jnp.array([u]))[0])
        return self.user_vectors[u]

    def on_recv(self, data, ps):
        u, i, r = data
        self.pending.setdefault(i, []).append((u, r))
        ps.pull(i)

    def on_pull_recv(self, param_id, param_value, ps):
        import numpy as np

        item_vec = np.asarray(param_value)
        for u, r in self.pending.pop(param_id, []):
            user_vec = self._user_vec(u)
            ud, idelta, pred = self.updater.delta(
                jnp.asarray(r), jnp.asarray(user_vec), jnp.asarray(item_vec)
            )
            self.user_vectors[u] = user_vec + np.asarray(ud)
            ps.push(param_id, np.asarray(idelta))
            ps.output((u, param_id, float(pred)))


__all__ = [
    "SGDUpdater",
    "OnlineMatrixFactorization",
    "MFWorkerLogic",
    "make_locality_mf_step",
    "ps_online_mf",
]
