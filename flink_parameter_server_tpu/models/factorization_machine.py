"""Factorization Machine (degree-2) on the PS — wide sparse embeddings.

Reference parity: BASELINE.json config #4 — "Factorization Machine on
Criteo-1TB (wide sparse embedding table)".  The PS formulation keys the
model by feature id: each id owns a scalar weight w_i and a latent vector
v_i; examples are sparse (pull only present ids), gradients are sparse
pushes — the same multi-pull pattern as passive-aggressive (SURVEY.md
§3.4) with a wider value row.

TPU-first: one store row per feature = ``(1 + dim,)`` (w_i ‖ v_i), so one
sharded gather per microbatch fetches both.  The O(K²) pairwise interaction
uses the standard linear-time identity

    ΣΣ ⟨v_i, v_j⟩ x_i x_j = ½ (‖Σ x_i v_i‖² − Σ ‖x_i v_i‖²)

which is two fused batched reductions on TPU.  Training is logistic (CTR
convention) or squared loss SGD; the global bias is a reserved feature id
(``bias_id``) the data pipeline appends with value 1.0.
"""
from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp

from ..core.batched import BatchedWorkerLogic, PushRequest
from ..core.store import ShardedParamStore
from ..core.transform import transform_batched
from ..utils.initializers import normal_factor

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FMConfig:
    num_features: int
    dim: int = 8
    learning_rate: float = 0.05
    l2: float = 0.0
    loss: str = "logistic"  # or "squared"


class FactorizationMachine(BatchedWorkerLogic):
    """Batch: ``ids`` (B,K) int, ``values`` (B,K) float, ``feat_mask``
    (B,K) bool, ``label`` (B,) (±1 logistic / float squared), ``mask`` (B,).
    """

    def __init__(self, config: FMConfig):
        self.config = config

    def init_state(self, rng: Array):
        return ()

    def keys(self, batch: Dict[str, Array]) -> Array:
        return batch["ids"]

    def step(self, state, batch: Dict[str, Array], pulled: Array):
        cfg = self.config
        x = jnp.where(batch["feat_mask"], batch["values"].astype(jnp.float32), 0.0)
        w = pulled[..., 0]  # (B, K)
        v = pulled[..., 1:]  # (B, K, d)

        linear = jnp.sum(w * x, axis=-1)  # (B,)
        xv = x[..., None] * v  # (B, K, d)
        s = jnp.sum(xv, axis=1)  # (B, d)  Σ x_i v_i
        interaction = 0.5 * (jnp.sum(s * s, axis=-1) - jnp.sum(xv * xv, axis=(1, 2)))
        y_hat = linear + interaction  # (B,)

        label = batch["label"].astype(jnp.float32)
        if cfg.loss == "logistic":
            # dL/dy_hat for y ∈ {−1,+1}: −y σ(−y ŷ)
            g = -label * jax.nn.sigmoid(-label * y_hat)
            loss = jax.nn.softplus(-label * y_hat)
        else:
            g = y_hat - label
            loss = 0.5 * g * g

        # ∂ŷ/∂w_i = x_i ;  ∂ŷ/∂v_i = x_i (s − x_i v_i)
        dw = g[:, None] * x + cfg.l2 * w
        dv = g[:, None, None] * (x[..., None] * (s[:, None, :] - xv)) + cfg.l2 * v
        deltas = jnp.concatenate(
            [-cfg.learning_rate * dw[..., None], -cfg.learning_rate * dv], axis=-1
        )  # (B, K, 1+d)

        mask = batch["feat_mask"] & batch["mask"][:, None]
        out = {
            "prediction": y_hat,
            "loss": loss * batch["mask"],
        }
        return state, PushRequest(batch["ids"], deltas, mask), out


def make_store(
    config: FMConfig, *, seed: int = 0, init_stddev: float = 0.01, mesh=None,
    dtype=None, scatter_impl: str = "xla", layout: str = "dense",
) -> ShardedParamStore:
    """(num_features, 1+dim) store: w zero-init, v ~ N(0, init_stddev).

    The FM row is NARROW (1+dim = 17 for Criteo shapes) — on TPU pass
    ``layout="packed"`` (or "auto") to pack 7 rows per 128-lane physical
    row: full vector lanes and pallas-scatter eligibility
    (ops/packed.py)."""
    dtype = dtype or jnp.float32
    vinit = normal_factor(seed, (config.dim,), stddev=init_stddev,
                          dtype=dtype)

    def init(ids: Array) -> Array:
        v = vinit(ids)
        return jnp.concatenate([jnp.zeros(ids.shape + (1,), v.dtype), v], axis=-1)

    return ShardedParamStore.create(
        config.num_features, (1 + config.dim,), init_fn=init, mesh=mesh,
        dtype=dtype, scatter_impl=scatter_impl, layout=layout,
    )


def train_fm(data, config: FMConfig, *, seed: int = 0, mesh=None, **kwargs):
    """End-to-end FM training; ``result.store.values()`` is the
    (num_features, 1+dim) model."""
    logic = FactorizationMachine(config)
    store = make_store(config, seed=seed, mesh=mesh)
    return transform_batched(
        data, logic, store, rng=jax.random.PRNGKey(seed), mesh=mesh, **kwargs
    )


__all__ = ["FMConfig", "FactorizationMachine", "make_store", "train_fm"]
