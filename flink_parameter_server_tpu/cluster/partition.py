"""Deterministic key→shard maps for the multi-shard runtime.

Reference parity: the reference routes every pull/push with
``hash(paramId) % psParallelism`` (SURVEY.md §2 "Model parallelism") —
total and balanced, but a resize moves almost every key.  The cluster
runtime needs the routing decision on the HOST (the client picks a
socket before any bytes move), deterministic across processes (client
and shard must agree), and resize-friendly, so two maps are offered:

  * :class:`RangePartitioner` — contiguous key ranges, shard ``i`` owns
    ``[i·rows, (i+1)·rows)``.  This is the layout
    :class:`~..core.store.StoreSpec` already gives a mesh-sharded table
    (row-block sharding over the ``ps`` axis), so a cluster deployed
    this way is byte-compatible with the single-process sharded store.
    Locality-friendly (a presorted batch walks shards in order), but a
    shard-count change moves every boundary.

  * :class:`ConsistentHashPartitioner` — highest-random-weight
    (rendezvous) hashing over the :func:`~..ops.hashing.fmix32_np`
    family: ``shard(k) = argmax_s fmix32(mix(k, s, seed))``.  Total and
    balanced like mod-hash, with the consistent-hash resize property in
    its strongest form: when a shard is ADDED, every key either stays
    exactly where it was or moves to the new shard — no key ever moves
    between pre-existing shards (the invariant
    ``tests/test_cluster.py`` property-checks).  Unlike a vnode ring
    there is no placement table to ship: both ends recompute the map
    from ``(num_shards, seed)``.

Both expose the same surface: ``shard_of(ids)`` (vectorised),
``owned_ids(shard)`` (the shard's global key slice, ascending — what a
shard materialises its local table from), and ``to_local(shard, ids)``
(global → dense local row, so every shard stores exactly its share of
rows, not a full-capacity table).
"""
from __future__ import annotations

import numpy as np

from ..ops.hashing import fmix32_np

_GOLDEN = np.uint32(0x9E3779B1)
_SHARD_SALT = np.uint32(0x85EBCA6B)


def mesh_row_block(capacity: int, n_devices: int, *, window: int = 8) -> int:
    """Rows one mesh device owns when a ``capacity``-row table is
    row-block sharded over ``n_devices`` — the same arithmetic as
    :meth:`~..core.store.StoreSpec.rows_per_shard` (ceil split, then
    rounded up to the pallas 8-row ``window``).  This is the unit
    shard boundaries must land on for a range partition to coincide
    with the device layout (see :meth:`RangePartitioner.block_aligned`)."""
    if n_devices < 1:
        raise ValueError(f"n_devices={n_devices}: must be >= 1")
    per = -(-int(capacity) // int(n_devices))  # ceil
    return -(-per // int(window)) * int(window)


class Partitioner:
    """Common surface of the two maps (duck-typed; this base holds the
    local-id machinery both share)."""

    capacity: int
    num_shards: int

    def shard_of(self, ids) -> np.ndarray:
        raise NotImplementedError

    # -- derived -----------------------------------------------------------
    def owned_ids(self, shard: int) -> np.ndarray:
        """ASCENDING global ids owned by ``shard`` (the shard's local
        row order: local row ``j`` holds global id ``owned_ids(s)[j]``)."""
        self._check_shard(shard)
        all_ids = np.arange(self.capacity, dtype=np.int64)
        return all_ids[self.shard_of(all_ids) == shard]

    def shard_capacity(self, shard: int) -> int:
        return len(self.owned_ids(shard))

    def to_local(self, shard: int, ids) -> np.ndarray:
        """Global ids → dense local rows on ``shard``.  Ids the shard
        does not own raise — a mis-routed request is a protocol bug,
        never something to absorb silently."""
        self._check_shard(shard)
        owned = self._owned_cache(shard)
        ids = np.asarray(ids, np.int64)
        local = np.searchsorted(owned, ids)
        ok = (local < len(owned)) & (owned[np.minimum(local, len(owned) - 1)] == ids)
        if not ok.all():
            bad = ids[~ok]
            raise KeyError(
                f"ids {bad[:8].tolist()} not owned by shard {shard} "
                f"(mis-routed request)"
            )
        return local.astype(np.int64)

    def to_global(self, shard: int, local_ids) -> np.ndarray:
        """Dense local rows on ``shard`` → global ids (inverse of
        :meth:`to_local`)."""
        owned = self._owned_cache(shard)
        return owned[np.asarray(local_ids, np.int64)]

    # -- plumbing ----------------------------------------------------------
    def _check_shard(self, shard: int) -> None:
        if not 0 <= shard < self.num_shards:
            raise ValueError(
                f"shard {shard} out of range [0, {self.num_shards})"
            )

    def _owned_cache(self, shard: int) -> np.ndarray:
        cache = getattr(self, "_owned", None)
        if cache is None:
            cache = self._owned = {}
        if shard not in cache:
            cache[shard] = self.owned_ids(shard)
        return cache[shard]


class RangePartitioner(Partitioner):
    """Contiguous ranges: shard ``i`` owns ``[i·rows, (i+1)·rows)`` with
    ``rows = ceil(capacity / num_shards)`` — exactly the row-block split
    :meth:`~..core.store.StoreSpec.rows_per_shard` gives the mesh-sharded
    table, so range-clustered shards ARE the sharded store's blocks."""

    def __init__(self, capacity: int, num_shards: int):
        if capacity < 1:
            raise ValueError(f"capacity={capacity}: must be >= 1")
        if not 1 <= num_shards <= capacity:
            raise ValueError(
                f"num_shards={num_shards}: must be in [1, capacity={capacity}]"
            )
        self.capacity = int(capacity)
        self.num_shards = int(num_shards)
        self.rows_per_shard = -(-self.capacity // self.num_shards)  # ceil

    def shard_of(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        if ((ids < 0) | (ids >= self.capacity)).any():
            raise ValueError(
                f"ids outside [0, {self.capacity}) cannot be routed"
            )
        return (ids // self.rows_per_shard).astype(np.int32)

    def owned_ids(self, shard: int) -> np.ndarray:
        self._check_shard(shard)
        lo = shard * self.rows_per_shard
        hi = min(lo + self.rows_per_shard, self.capacity)
        return np.arange(lo, hi, dtype=np.int64)

    def to_local(self, shard: int, ids) -> np.ndarray:
        self._check_shard(shard)
        ids = np.asarray(ids, np.int64)
        lo = shard * self.rows_per_shard
        hi = min(lo + self.rows_per_shard, self.capacity)
        if ((ids < lo) | (ids >= hi)).any():
            bad = ids[(ids < lo) | (ids >= hi)]
            raise KeyError(
                f"ids {bad[:8].tolist()} not owned by shard {shard} "
                f"(range [{lo}, {hi}))"
            )
        return ids - lo

    def block_aligned(
        self, n_devices: int, *, window: int = 8
    ) -> "RangePartitioner":
        """The same map with ``rows_per_shard`` rounded UP so every
        shard boundary is a multiple of the mesh row-block
        (:func:`mesh_row_block`) a ``n_devices``-way device mesh gives
        this capacity.  Until now that alignment held only by
        convention (pick num_shards dividing the device count and hope)
        — a misaligned table silently forces a resharding gather on
        every pull, because a shard's rows then straddle two devices'
        blocks.

        The total padded extent ``rows_per_shard * num_shards`` stays
        a whole number of row-blocks, so the mesh table the store
        builds over this map needs no extra padding.  Growing the rows
        can leave TRAILING shards short (or, for extreme
        capacity/shard/device combinations, empty) — harmless for the
        mesh backend, where the partitioner is layout arithmetic
        rather than a socket address, and ``shard_of``/``owned_ids``
        stay total and disjoint either way."""
        block = mesh_row_block(self.capacity, n_devices, window=window)
        aligned = RangePartitioner(self.capacity, self.num_shards)
        aligned.rows_per_shard = -(-self.rows_per_shard // block) * block
        aligned.aligned_block = block
        return aligned


class ConsistentHashPartitioner(Partitioner):
    """Rendezvous (HRW) hashing — the consistent-hash family with the
    strongest stability guarantee: ``shard_of`` is ``argmax`` over
    per-shard scores ``fmix32(key·golden ^ salt(shard, seed))``, so
    adding shard ``N`` only ever RAISES the max toward the new shard;
    keys whose argmax was an existing shard keep it (property-tested)."""

    def __init__(self, capacity: int, num_shards: int, *, seed: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity={capacity}: must be >= 1")
        if num_shards < 1:
            raise ValueError(f"num_shards={num_shards}: must be >= 1")
        self.capacity = int(capacity)
        self.num_shards = int(num_shards)
        self.seed = int(seed)
        # per-shard salts, deterministic in (shard index, seed): both
        # ends of the wire recompute these — no placement table ships
        with np.errstate(over="ignore"):
            idx = np.arange(self.num_shards, dtype=np.uint32)
            self._salts = fmix32_np(
                (idx + np.uint32(1)) * _SHARD_SALT
                + np.uint32(self.seed & 0xFFFFFFFF)
            )

    def shard_of(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        if ((ids < 0) | (ids >= self.capacity)).any():
            raise ValueError(
                f"ids outside [0, {self.capacity}) cannot be routed"
            )
        with np.errstate(over="ignore"):
            k = (ids.astype(np.uint32) * _GOLDEN)[..., None]
            scores = fmix32_np(k ^ self._salts)
        return np.argmax(scores, axis=-1).astype(np.int32)

    def grown(self, num_shards: int) -> "ConsistentHashPartitioner":
        """The same map with more shards (same seed) — what a scale-out
        deploys; existing keys move only onto the new shards."""
        if num_shards < self.num_shards:
            raise ValueError(
                f"grown({num_shards}) must not shrink below "
                f"{self.num_shards}; use shrunk() to scale in"
            )
        return ConsistentHashPartitioner(
            self.capacity, num_shards, seed=self.seed
        )

    def shrunk(self, num_shards: int) -> "ConsistentHashPartitioner":
        """The same map with the HIGHEST-indexed shards removed (same
        seed) — the scale-in inverse of :meth:`grown`.  Rendezvous
        scoring makes this exactly symmetric: dropping the last salt
        only ever LOWERS a key's argmax back onto a survivor, so keys
        move only OFF the retired shards; every surviving shard keeps
        exactly its old keys plus inherited ones (the drain-and-retire
        property migration relies on)."""
        if not 1 <= num_shards <= self.num_shards:
            raise ValueError(
                f"shrunk({num_shards}) must be in [1, {self.num_shards}]"
            )
        return ConsistentHashPartitioner(
            self.capacity, num_shards, seed=self.seed
        )


__all__ = [
    "Partitioner",
    "RangePartitioner",
    "ConsistentHashPartitioner",
    "mesh_row_block",
]
