"""Shard worker processes — the GIL escape (ROADMAP item 1).

PR 12's soak capacity curve was flat-to-inverted in shard count
because every in-process shard thread shares ONE interpreter lock with
every worker thread: adding shards added lock convoy, not capacity
(``results/cpu/soak_capacity.md``).  This module runs each
:class:`~.shard.ShardServer` in its OWN spawned process — its own
interpreter, its own GIL, its own selectors event loop — so shard-side
scatter/parse work runs in real OS-level parallelism with the workers
and with each other on multi-core hosts.

Design points:

  * **spawn, not fork** — a fork would duplicate jax/XLA runtime state
    and every live thread's locks; spawn starts clean.  The child sets
    ``JAX_PLATFORMS=cpu`` defensively but never actually imports jax:
    shards run the ``store_backend="numpy"`` slice
    (:class:`~.shard._NumpyStore`), whose in-place fp32 scatter-add is
    both bitwise-comparable to the jax path over client-deduplicated
    ids and ~1000× cheaper to dispatch than an XLA call per push.
  * **readiness over a pipe** — the child reports ``(host, port)``
    after binding, and the parent's :meth:`ShardProcess.wait_ready`
    blocks on it.  The first dial can still race a RESPAWN, which is
    why :class:`~.client.ClusterClient` retries refused dials inside
    its ``spawn_grace_s`` window instead of spending storm-class
    retry budget (the ``_await_retry`` interaction fix).
  * **durability is the WAL's job, by design** — a killed shard
    process loses its in-memory slice only; the WAL dir, telemetry
    export, and supervised restart already treat process death as the
    ordinary failure (``docs/resilience.md``), so a respawned
    :class:`ShardProcess` over the same ``wal_dir`` rebuilds bitwise.

``init`` specs are small picklable dicts (``{"kind": "zeros"}`` /
``{"kind": "hashed_uniform", "scale": s, "seed": k}``) rather than
closures — a spawned child can't unpickle a lambda, and deterministic
per-id init is exactly what makes a shard slice equal the global
table's rows.  :func:`as_jax_init` renders the same spec for an
in-process (thread-backed) driver, which is how the proc-vs-thread
parity test pins both arms to one table.

The standard library's spawn caveat applies: a SCRIPT that creates
shard processes must guard its entry point with
``if __name__ == "__main__":`` — spawn re-imports ``__main__`` in the
child, and unguarded top-level code would recursively re-run the
whole script (the stdlib raises the usual "bootstrapping phase"
RuntimeError).  Library/pytest imports are unaffected.
"""
from __future__ import annotations

import dataclasses
import json
import multiprocessing
import os
from typing import Optional, Tuple

import numpy as np

_CTX = multiprocessing.get_context("spawn")


# -- deterministic picklable init specs --------------------------------------


def resolve_init(init: Optional[dict]):
    """``init`` spec → a numpy ``f(ids) -> rows`` (or None for the
    zeros default).  Deterministic per id — the contract every shard
    rebuild and parity check rides on."""
    if init is None:
        return None
    kind = init.get("kind", "zeros")
    if kind == "zeros":
        return None
    if kind == "hashed_uniform":
        scale = float(init.get("scale", 0.1))
        seed = int(init.get("seed", 0))

        def f(ids: np.ndarray, _scale=scale, _seed=seed):
            from ..ops.hashing import fmix32_np

            ids = np.asarray(ids, np.int64)
            width = int(init.get("width", 0))
            cols = []
            for j in range(max(1, width)):
                h = fmix32_np(ids * np.int64(2654435761) + j + _seed)
                cols.append(
                    (h.astype(np.float64) / 2**32 - 0.5) * 2 * _scale
                )
            out = np.stack(cols, axis=-1).astype(np.float32)
            return out if width else out[..., 0]

        return f
    raise ValueError(
        f"init kind {kind!r}: 'zeros' | 'hashed_uniform'"
    )


def as_jax_init(init: Optional[dict], value_shape: Tuple[int, ...]):
    """The SAME init spec as a jax ``init_fn`` for an in-process
    driver — proc and thread arms then start from one table."""
    init = dict(init or {"kind": "zeros"})
    width = 1
    for s in value_shape:
        width *= int(s)
    init.setdefault("width", width)
    f = resolve_init(init)
    if f is None:
        return None

    def init_fn(ids):
        import jax.numpy as jnp

        rows = f(np.asarray(ids)).reshape(
            (-1,) + tuple(value_shape)
        )
        return jnp.asarray(rows)

    return init_fn


@dataclasses.dataclass
class ShardProcSpec:
    """Everything a shard worker process needs, picklable."""

    shard_id: int
    partition: str  # "range" | "hash"
    capacity: int
    num_shards: int
    value_shape: Tuple[int, ...] = ()
    wal_dir: Optional[str] = None
    init: Optional[dict] = None
    supervised: bool = True
    host: str = "127.0.0.1"
    max_line_bytes: int = 64 << 20
    # advertise the shared-memory transport (shmem/): a co-located
    # client's "hello shm" hands the data plane to a ring pair — the
    # proc-shard case is exactly what shm exists for (same host,
    # different interpreters, no kernel socket between them)
    shm: bool = True


def _build_partitioner(spec: dict):
    from .partition import ConsistentHashPartitioner, RangePartitioner

    if spec["partition"] == "range":
        return RangePartitioner(spec["capacity"], spec["num_shards"])
    if spec["partition"] == "hash":
        return ConsistentHashPartitioner(
            spec["capacity"], spec["num_shards"]
        )
    raise ValueError(f"partition={spec['partition']!r}: 'range' | 'hash'")


def _shard_proc_main(spec: dict, pipe) -> None:
    """The child: build the numpy-backed shard + its server, report
    the bound address, serve until told to stop (or until the parent
    dies — the pipe EOF).  The WAL dir is the durable half; losing
    this process is the ordinary failure the stack already absorbs."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        from .shard import ParamShard, ShardServer

        init_spec = dict(spec.get("init") or {"kind": "zeros"})
        width = 1
        for s in spec["value_shape"]:
            width *= int(s)
        init_spec.setdefault("width", width)
        base = resolve_init(init_spec)
        init_fn = None
        if base is not None:
            def init_fn(ids):
                return base(np.asarray(ids)).reshape(
                    (-1,) + tuple(spec["value_shape"])
                )
        shard = ParamShard(
            spec["shard_id"],
            _build_partitioner(spec),
            spec["value_shape"],
            init_fn=init_fn,
            wal_dir=spec["wal_dir"],
            store_backend="numpy",
        )
        server = ShardServer(
            shard, spec["host"], 0,
            supervised=spec["supervised"],
            max_line_bytes=spec["max_line_bytes"],
            enable_shm=bool(spec.get("shm", True)),
        ).start()
    except Exception as e:  # noqa: BLE001 — reported to the parent
        try:
            pipe.send(("error", f"{type(e).__name__}: {e}", 0))
        except (OSError, BrokenPipeError):
            pass
        return
    try:
        # 4th element advertises shm willingness (older parents index
        # only [1]/[2]; newer parents read it defensively)
        pipe.send(
            ("ready", server.host, server.port, bool(server.shm_enabled))
        )
        while True:
            if pipe.poll(0.25):
                msg = pipe.recv()
                if msg == "stop":
                    break
    except (EOFError, OSError, BrokenPipeError):
        pass  # parent gone: exit; the WAL dir is the durable half
    finally:
        try:
            server.stop()
            shard.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        try:
            pipe.send(("stopped",))
        except (OSError, BrokenPipeError):
            pass


class ShardProcess:
    """Parent-side handle on one spawned shard server process.

    Presents the server façade the drivers expect (``host`` / ``port``
    / ``running`` / ``stop()``), so a proc-backed topology publishes
    addresses exactly like a thread-backed one."""

    def __init__(self, spec: ShardProcSpec):
        self.spec = spec
        self._pipe, child = _CTX.Pipe()
        self.proc = _CTX.Process(
            target=_shard_proc_main,
            args=(dataclasses.asdict(spec), child),
            name=f"fps-shard-{spec.shard_id}",
            daemon=True,
        )
        self.proc.start()
        child.close()
        self.host: Optional[str] = None
        self.port: Optional[int] = None
        self.shm = False  # set from the ready message (wait_ready)

    def wait_ready(self, timeout: float = 60.0) -> "ShardProcess":
        """Block until the child reports its bound address (or died
        trying).  Clients may still dial before THIS returns on a
        respawn path — the client-side spawn grace window covers it."""
        if self.port is not None:
            return self
        if not self._pipe.poll(timeout):
            self.stop()
            raise TimeoutError(
                f"shard {self.spec.shard_id} process not ready after "
                f"{timeout}s"
            )
        try:
            msg = self._pipe.recv()
        except (EOFError, OSError):
            self.stop()
            raise RuntimeError(
                f"shard {self.spec.shard_id} process died before "
                f"reporting ready (exitcode="
                f"{self.proc.exitcode})"
            ) from None
        if msg[0] != "ready":
            self.stop()
            raise RuntimeError(
                f"shard {self.spec.shard_id} process failed: {msg[1]}"
            )
        self.host, self.port = msg[1], int(msg[2])
        # shm advertisement (absent from pre-shmem children)
        self.shm = bool(msg[3]) if len(msg) > 3 else False
        return self

    @property
    def running(self) -> bool:
        return self.proc.is_alive()

    def stop(self, timeout: float = 10.0) -> None:
        """Graceful stop (the child drains + closes its WAL), with a
        terminate fallback — the kill path IS a supported failure."""
        try:
            self._pipe.send("stop")
        except (OSError, BrokenPipeError):
            pass
        self.proc.join(timeout)
        if self.proc.is_alive():
            self.proc.terminate()
            self.proc.join(5)
        try:
            self._pipe.close()
        except OSError:
            pass

    def kill(self) -> None:
        """The chaos path: SIGKILL, no drain — what a real shard-host
        death looks like.  A fresh :class:`ShardProcess` over the same
        ``wal_dir`` rebuilds the slice bitwise."""
        self.proc.kill()
        self.proc.join(5)


class RemoteShardStub:
    """The driver-side stand-in for an in-process :class:`ParamShard`
    when the shard lives in another process: the few read surfaces the
    driver touches (``stats``) go over the wire; lifecycle is the
    process handle's job."""

    def __init__(self, proc: ShardProcess, timeout: float = 10.0):
        self._proc = proc
        self._timeout = float(timeout)
        self.shard_id = proc.spec.shard_id

    def stats(self) -> dict:
        from ..utils.net import request_lines

        resp = request_lines(
            self._proc.host, self._proc.port, ["stats"],
            timeout=self._timeout,
        )[0]
        if not resp.startswith("ok "):
            raise RuntimeError(
                f"shard {self.shard_id} stats failed: {resp}"
            )
        return json.loads(resp[3:])

    def close(self) -> None:
        """The process handle owns teardown; nothing in-process."""


__all__ = [
    "RemoteShardStub",
    "ShardProcSpec",
    "ShardProcess",
    "as_jax_init",
    "resolve_init",
]
