"""A parameter-server shard: one partition slice, served over TCP.

This is the reference's PS subtask made a real process boundary: shard
``s`` owns exactly the rows ``partitioner.owned_ids(s)`` as a dense
local :class:`~..core.store.ShardedParamStore` slice, and answers
PULL / PUSH / FLUSH over the same newline-delimited TCP idiom as the
serving plane (``serving/server.py``) and the ingest edge
(``data/socket.py``) — the socket skeleton itself comes from
:class:`~..utils.net.LineServer`.

Two framings, one protocol (docs/cluster.md "Binary framing"): the
line protocol below is the bootstrap and compat surface, and a client
may negotiate the LENGTH-PREFIXED BINARY framing per connection with
a first ``hello bin v=1`` line — every verb, option token, and error
reason then maps one-for-one onto ``utils/frames.py`` frames (ids as
raw ``<i8``, rows as raw ``<f4``/bf16 received zero-copy, options as
TLVs, ``err <reason>`` as status bytes), dispatched by
:meth:`ShardServer.respond_frame`.  An old server answers the hello
with ``err bad-request`` and the connection stays on lines.

Wire protocol (one request line → one response line, in order, per
connection).  Every verb accepts trailing ``key=value`` options;
``e=<epoch>`` tags the frame with the client's partition-map epoch,
``pid=<token>`` makes a push idempotent (exactly-once across retries —
see below), and ``t=<trace>:<span>`` carries the distributed-trace
context (telemetry/distributed.py; servers without a tracer — and
PR-5-era servers — parse and ignore it, the protocol-versioning
contract for observability options)::

    pull <id1,id2,...> [text|b64] [e=<n>] [t=<tok>]  # ids + answer format
    push <id1,id2,...> <payload> [pid=<t>] [e=<n>] [t=<tok>]  # deltas
    lease <id1,id2,...> [text|b64] sess=<s> [ttl=<r>] [e=<n>]
                                             # atomic read + lease grant
                                             # (hotcache/, docs/hotcache.md)
    revoke <id1,id2,...|all> sess=<s>        # client releases its leases
    xfer <id1,id2,...> [t=<tok>]             # atomic (rows, seq) snapshot
    load <id1,id2,...> <payload>             # row ASSIGNMENT (migration)
    repl <b64-frame> [head=<n>]              # ship one WAL record to a
                                             # follower (replication/)
    replstate                                # one-line JSON repl state
    flush                                    # fsync the WAL, ack counters
    stats                                    # one-line JSON shard stats
    conns                                    # live connection ledger
                                             # (psctl conns)

    ok n=<k> <payload>                    # pull answer
    ok applied=<k> seq=<n>                # push answer
    ok n=<k> seq=<q> ttl=<r> <payload>    # lease answer (rows as-of seq)
    ok revoked=<k>                        # revoke answer
    ok n=<k> seq=<s> <payload>            # xfer answer (always b64)
    ok loaded=<k> seq=<n>                 # load answer
    ok acked seg=<s> seq=<n>              # repl answer (the follower ack:
                                          # durable segment + end seq)
    ok pushes=<n> wal_records=<m>         # flush answer
    err <reason>      # bad-request | crashed | stale-epoch | frozen
                      # | lagging | not-primary | overloaded | internal

Overload shedding (loadgen/overload.py, docs/loadgen.md): with an
``OverloadGuard`` attached to the server, frames may be answered
``err overloaded`` BEFORE parsing once the live request depth passes
the guard's thresholds — serving/lease reads shed first, training
pushes never (by default).  Frames may carry a ``pr=<n>`` priority
option (0 critical, 1 normal, 2 sheddable); old servers parse and
ignore it, the same trailing-token contract as ``sess=``/``t=``.

Epoch fencing (the elastic/ membership protocol, docs/elastic.md): a
shard pins the partition-map epoch it serves.  A push whose frame
epoch is OLDER than the shard's is rejected with ``err stale-epoch``
— a map flip can therefore never mix routings: the client refreshes
its membership view and replays the frame against the new map.  A
frame from a NEWER epoch is accepted when its ids route here under
either map (the flip is mid-flight; ownership under the new map is a
subset of what this shard already holds), and answered
``err stale-epoch`` when they don't.  During a key migration the
moving range is FROZEN: pushes touching it get ``err frozen`` (retry
shortly — the flip is imminent); pulls and pushes of non-moving keys
never block.

Hot-key leases (hotcache/, docs/hotcache.md): a frame carrying
``sess=<token>`` declares a lease-capable client session.  ``lease``
is an atomic read + grant (the answered rows are exactly the state at
the answered ``seq``); a later push by any OTHER session to a leased
key queues an invalidation which **piggybacks** on the next response
to the holder as a trailing ``inv=<id1,id2,...>`` token (``inv=*`` =
drop everything — epoch flips and restarts).  Old servers parse and
ignore the ``sess=`` option (the PR-6 trailing-token contract) and
old clients never send it, so neither side ever sees a token it
cannot handle.  The lease board is in-memory and best-effort by
design: the CLIENT enforces the staleness bound locally, so a lost
invalidation costs freshness inside the bound, never a violation.

Exactly-once pushes: a frame carrying ``pid=<token>`` is deduplicated
per ``(pid, id)`` against a bounded window that survives crashes (the
pairs ride the WAL records and the install-epoch snapshot, and
migration hands the moving range's pairs to the new owner), so a
client retry after a lost ack — shard died AFTER applying, BEFORE
answering — is acked without double-applying.

Row payloads come in two self-describing encodings, both EXACT (a
pulled row is bitwise the stored fp32 row — what lets a bound-0
cluster land allclose-tight against the single-process table):

  * text — ``;``-separated rows of ``,``-separated ``repr()`` floats
    (``repr`` round-trips the fp32 value exactly); the idiom of the
    serving plane and the one a human types into ``nc``;
  * ``b64:<base64>`` — little-endian fp32 row-major bytes, base64'd.
    ~100× cheaper to encode/decode than per-float text (measured:
    37 ms → 0.3 ms for a 2048×16 payload), which on a thread-backed
    single-host cluster is the difference between measuring the
    runtime and measuring ``repr()``.  The client's default.

Durability + supervised restart (the resilience wiring): every push is
appended to a per-shard :class:`~..resilience.wal.UpdateWAL` BEFORE it
is applied, keyed by the shard's monotone push sequence (idempotent on
replay).  A crash — real, or injected via :meth:`ParamShard.crash` —
loses the in-memory slice only: :class:`ShardServer` classifies the
failure, backs off per :class:`~..resilience.recovery.RestartPolicy`,
rebuilds the slice from its deterministic init, replays the WAL, and
re-serves the request that found the shard dead.  The recovered slice
is bitwise the pre-crash one (init is deterministic per id; replay
re-applies the exact logged deltas in order).

Per-shard telemetry (``component=cluster``, ``shard=<i>`` labels):
pull/push counters, a live in-flight request-depth gauge, and a
restarts counter — scrapeable mid-run through the shared
``/metrics`` endpoint.
"""
from __future__ import annotations

import base64
import json
import threading
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from ..utils import frames as binf
from ..utils.net import LineServer
from .partition import Partitioner

_MAX_IDS_PER_REQUEST = 1 << 16  # frames stay line-sized; clients chunk


class ShardCrashed(RuntimeError):
    """The shard's in-memory slice is gone (chaos-injected or real);
    tagged so :func:`~..resilience.recovery.classify_failure` routes it
    down the DEVICE branch."""

    failure_class = "device"


class StaleEpoch(RuntimeError):
    """Frame epoch vs shard epoch disagree in a way that cannot be
    served (an old-epoch write, or ids this shard does not own under a
    mixed-flight flip).  Carries the shard's current epoch so the wire
    answer tells the client what to catch up to."""

    def __init__(self, shard_epoch: int, detail: str = ""):
        super().__init__(
            f"stale epoch (shard at {shard_epoch}){': ' + detail if detail else ''}"
        )
        self.shard_epoch = int(shard_epoch)


class FrozenKeys(RuntimeError):
    """The push touches a key range frozen for migration — retry
    shortly; the epoch flip that re-homes the range is imminent."""


class NotPrimary(RuntimeError):
    """A write landed on a replica-chain follower.  Followers absorb
    reads only; the client must route writes to the primary
    (``err not-primary`` on the wire)."""


class FollowerLagging(RuntimeError):
    """A follower's applied state trails the primary's head past the
    read-staleness bound, so serving this read would violate the SSP
    contract — the client falls back to the primary
    (``err lagging lag=<n>`` on the wire)."""

    def __init__(self, lag: int):
        super().__init__(
            f"follower is {lag} records behind the primary head "
            f"(past the staleness bound)"
        )
        self.lag = int(lag)


def format_rows(rows: np.ndarray, encoding: str = "text") -> str:
    """Encode fp32 rows for the wire (see module docstring): ``text``
    uses per-float ``repr`` (exact, human-readable), ``b64`` base64s
    the raw little-endian fp32 bytes (exact, ~100× cheaper)."""
    if encoding == "b64":
        arr = np.ascontiguousarray(np.asarray(rows, "<f4"))
        return "b64:" + base64.b64encode(arr.tobytes()).decode("ascii")
    if encoding != "text":
        raise ValueError(f"encoding={encoding!r}: 'text' | 'b64'")
    rows = np.asarray(rows, np.float64)
    rows = rows.reshape(rows.shape[0], -1) if rows.ndim > 1 else rows.reshape(-1, 1)
    return ";".join(",".join(repr(float(v)) for v in row) for row in rows)


def parse_rows(body: str, value_shape: Tuple[int, ...]) -> np.ndarray:
    """Inverse of :func:`format_rows` (either encoding, self-described
    by the ``b64:`` prefix): ``(n, *value_shape)`` float32."""
    width = 1
    for s in value_shape:
        width *= int(s)
    if body.startswith("b64:"):
        raw = base64.b64decode(body[4:].encode("ascii"))
        flat = np.frombuffer(raw, "<f4")
        if width == 0 or flat.size % width:
            raise ValueError(
                f"b64 payload of {flat.size} floats does not tile value "
                f"shape {value_shape}"
            )
        return flat.reshape((flat.size // width,) + tuple(value_shape)).copy()
    rows = [
        [float(v) for v in row.split(",") if v]
        for row in body.split(";")
        if row
    ]
    arr = np.asarray(rows, np.float32)
    if arr.ndim != 2 or arr.shape[1] != width:
        raise ValueError(
            f"rows of width {arr.shape[1] if arr.ndim == 2 else '?'} do not "
            f"match value shape {value_shape}"
        )
    return arr.reshape((arr.shape[0],) + tuple(value_shape))


def parse_ids(tok: str) -> np.ndarray:
    ids = np.asarray(
        [int(t) for t in tok.split(",") if t.strip()], np.int64
    )
    if ids.size == 0:
        raise ValueError("need at least one id")
    if ids.size > _MAX_IDS_PER_REQUEST:
        raise ValueError(
            f"{ids.size} ids in one request (max {_MAX_IDS_PER_REQUEST}); "
            f"chunk the batch"
        )
    return ids


class _NumpyStore:
    """A jax-free stand-in for :class:`~..core.store.ShardedParamStore`
    with the surface :class:`ParamShard` touches (``from_values`` /
    ``values`` / ``push``) — the store backend shard WORKER PROCESSES
    run (cluster/procs.py): a spawned shard must not pay a jax import
    (seconds) or a per-push XLA dispatch (~ms) for a µs scatter-add.
    Single-owner under the shard lock, so ``push`` mutates in place;
    padding lanes (id −1) and out-of-range ids are dropped, matching
    ``ShardedParamStore.push``'s sentinel routing."""

    __slots__ = ("_v",)

    def __init__(self, values: np.ndarray):
        v = np.asarray(values)
        if not v.flags.writeable:
            # np.asarray over a jax-rendered init is a zero-copy
            # READ-ONLY view; push mutates in place
            v = v.copy()
        self._v = v

    @classmethod
    def from_values(cls, values) -> "_NumpyStore":
        return cls(np.array(values, np.float32))

    def values(self) -> np.ndarray:
        return self._v

    def push(self, local_ids, deltas) -> "_NumpyStore":
        ids = np.asarray(local_ids, np.int64)
        ok = (ids >= 0) & (ids < len(self._v))
        if not ok.all():
            ids = ids[ok]
            deltas = np.asarray(deltas)[ok]
        np.add.at(self._v, ids, np.asarray(deltas, self._v.dtype))
        return self


class ParamShard:
    """One shard's state: the local store slice + per-shard WAL.

    Thread-safe: one lock serializes pulls/pushes/restarts (a shard is
    a single logical owner of its rows — the reference's per-subtask
    ``HashMap`` had the same serial discipline, enforced by Flink's
    operator model there and by this lock here).

    ``store_backend`` picks the slice's array runtime: ``"jax"`` (the
    default — the mesh-sharded store path every in-process topology
    uses), ``"numpy"`` (plain host arrays; what shard worker
    PROCESSES run — see :class:`_NumpyStore`), or ``"tiered"`` (hot
    rows dense, cold rows in an mmap slab, absent rows recomputed
    from the deterministic init — :mod:`~..tierstore`, the
    bounded-RSS backend for tables that don't fit RAM).  All apply
    identical fp32 scatter-adds over client-deduplicated ids, so the
    slices stay bitwise-comparable.
    """

    def __init__(
        self,
        shard_id: int,
        partitioner: Partitioner,
        value_shape: Sequence[int] = (),
        *,
        init_fn=None,
        dtype=None,
        wal_dir: Optional[str] = None,
        wal_fsync_every: int = 0,
        registry=None,
        hotkeys=None,
        profiler=None,
        store_backend: str = "jax",
        tier_hot_rows: int = 65536,
        tier_slab_dir: Optional[str] = None,
        tier_decay_window: int = 0,
    ):
        if store_backend not in ("jax", "numpy", "tiered"):
            raise ValueError(
                f"store_backend={store_backend!r}: "
                f"'jax' | 'numpy' | 'tiered'"
            )
        if store_backend == "tiered" and dtype is not None:
            raise ValueError(
                "store_backend='tiered' is fp32-only (the tiers must "
                "stay bitwise-comparable with the dense backends)"
            )
        self._backend = store_backend
        self._tier_hot_rows = int(tier_hot_rows)
        self._tier_slab_dir = tier_slab_dir
        self._tier_decay_window = int(tier_decay_window)
        self.shard_id = int(shard_id)
        self.partitioner = partitioner
        self.value_shape = tuple(int(s) for s in value_shape)
        # replica-chain role (replication/): a primary absorbs writes
        # and may ship its WAL records to followers via an attached
        # sink; followers override the write surface (see
        # replication/follower.ReplicaShard)
        self.role = "primary"
        self._repl_sink = None
        self._init_fn = init_fn
        self._dtype = dtype
        self.owned = partitioner.owned_ids(self.shard_id)
        self._lock = threading.RLock()
        self._wal = None
        if wal_dir is not None:
            from ..resilience.wal import UpdateWAL

            # fsync cadence 0 by default: shard durability here is about
            # surviving a shard RESTART (process alive, slice lost), the
            # chaos mode tests exercise; page-cache durability suffices
            # and per-push fsyncs would dominate small-push latency
            self._wal = UpdateWAL(wal_dir, fsync_every=wal_fsync_every)
        # hot-key analytics (telemetry/hotkeys.py): with a sketch
        # attached, every pulled/pushed id batch is observed — the
        # Zipf-skew measurement gating the serving hot-key tier
        self.hotkeys = hotkeys
        # hot-key lease board (hotcache/leases.py): grants per client
        # session + the piggybacked invalidation queues.  In-memory and
        # best-effort — the client-side staleness bound is the safety
        # net (docs/hotcache.md)
        from ..hotcache.leases import LeaseBoard

        self.leases = LeaseBoard(shard=self.shard_id, registry=registry)
        # latency-budget phases (telemetry/profiler.py): lock wait =
        # server_queue_wait (concurrent connections serialize on this
        # shard's lock), WAL append, scatter/apply — the server side of
        # the per-round budget.  registry=False implies profiling off.
        from ..telemetry.profiler import NULL_PROFILER, resolve_profiler

        self._profiler = (
            NULL_PROFILER if registry is False and profiler is None
            else resolve_profiler(profiler)
        )
        self.pushes_applied = 0
        self.pulls_served = 0
        self.restarts = 0
        self.rows_applied = 0  # delta rows actually applied (post-dedupe)
        self.loads_applied = 0  # rows assigned via load (migration)
        self._push_seq = 0
        # elastic state: the partition-map epoch this shard serves, the
        # key range frozen for an in-flight migration, rows staged for
        # keys this shard will own only after the NEXT epoch flip, and
        # the bounded exactly-once (pid, id) dedupe window
        self.epoch = 0
        self._frozen: Optional[np.ndarray] = None
        self._staged: dict = {}
        self._applied_pairs: dict = {}  # insertion-ordered set w/ cap
        self.pid_window = 1 << 16
        self.store = None
        # host-side read mirror of the slice, rebuilt lazily after each
        # push: pulls are then one numpy fancy-index instead of an
        # eager jax gather + transfer per request (~2 ms → ~µs on the
        # thread-backed CPU topology)
        self._host_mirror: Optional[np.ndarray] = None
        self._build()
        if self._wal is not None and self._wal.last_step_logged is not None:
            # fresh process over an existing WAL dir: the restart path
            self._replay()
        # unified plane: per-shard instruments under component=cluster.
        # The request-depth counter is bumped by EVERY connection's
        # handler thread; += on an attribute is not atomic, so it gets
        # its own tiny lock (fpsanalyze S001) — never nested with
        # self._lock, so no ordering edge
        self._active_requests = 0
        self._depth_lock = threading.Lock()
        if registry is not False:
            from ..telemetry.registry import get_registry

            reg = registry if registry is not None else get_registry()
            sid = str(self.shard_id)
            self._c_pulls = reg.counter(
                "cluster_pulls_total", component="cluster", shard=sid
            )
            self._c_pushes = reg.counter(
                "cluster_pushes_total", component="cluster", shard=sid
            )
            self._c_restarts = reg.counter(
                "cluster_shard_restarts_total", component="cluster",
                shard=sid,
            )
            reg.gauge(
                "cluster_shard_queue_depth", component="cluster", shard=sid,
                fn=lambda: self._active_requests,
            )
        else:
            self._c_pulls = self._c_pushes = self._c_restarts = None
        if self._backend == "tiered":
            from ..tierstore import metrics as tier_metrics

            tier_metrics.register_store(self._tier_label(), self.tier_stats)
            if registry is not False:
                tier_metrics.register_instruments(
                    reg, str(self.shard_id), self.tier_stats
                )

    # -- the tiered backend (tierstore/, docs/tierstore.md) ------------------
    def _tier_label(self) -> str:
        """The shard's name on the process-wide ``tiers`` snapshot
        registry; followers append their chain index."""
        fidx = getattr(self, "follower_idx", None)
        label = f"shard-{self.shard_id}"
        return label if fidx is None else f"{label}-f{fidx}"

    def _tier_row_init(self, local_ids: np.ndarray) -> np.ndarray:
        """Deterministic init for LOCAL rows — row j is the global
        table's row ``owned[j]``, same per-id contract as
        :meth:`_build`, so a recomputed cold miss is bitwise the row a
        dense backend would have materialised."""
        gids = np.asarray(self.owned)[np.asarray(local_ids, np.int64)]
        if self._init_fn is None:
            return np.zeros(gids.shape + self.value_shape, np.float32)
        return np.asarray(self._init_fn(gids), np.float32)

    def _tier_pinned_local(self) -> np.ndarray:
        """Local ids the tier must never evict: keys frozen for an
        in-flight migration plus every currently-leased key (a lease
        is an invalidation promise — the row is about to be read or
        written again).  Runs under the shard lock during eviction
        scans; the lease board's lock nests strictly under it."""
        gids = self.leases.leased_ids()
        if self._frozen is not None:
            gids = np.union1d(gids, self._frozen)
        if gids.size == 0:
            return gids
        gids = gids[self.partitioner.shard_of(gids) == self.shard_id]
        if gids.size == 0:
            return gids
        return self.partitioner.to_local(self.shard_id, gids)

    def _make_tier_store(self):
        from ..tierstore.store import TieredStore

        return TieredStore(
            len(self.owned),
            self.value_shape,
            row_init=self._tier_row_init,
            hot_rows=self._tier_hot_rows,
            slab_dir=self._tier_slab_dir,
            decay_window=self._tier_decay_window,
            pinned_fn=self._tier_pinned_local,
            name_hint=self._tier_label(),
        )

    def tier_stats(self):
        """The tier's instrument snapshot (``None`` on non-tiered
        backends or while crashed) — the ``component=tierstore`` gauge
        source and the TelemetryServer ``tiers`` path payload."""
        with self._lock:
            if self._backend != "tiered" or self.store is None:
                return None
            st = self.store.stats()
            st["shard"] = self.shard_id
            st["role"] = self.role
            return st

    # -- construction / recovery -------------------------------------------
    def _store_from_values(self, values):
        """Build a store of the configured backend over ``values`` —
        the one seam every slice (re)materialisation goes through, so
        the jax/numpy choice lives in exactly one place."""
        if self._backend == "tiered":
            # snapshot-restore / epoch-install: seed a FRESH tier from
            # the dense rows (only rows differing from init hit the
            # slab) and retire the old slab file
            old = self.store
            st = self._make_tier_store()
            st.seed_dense(np.asarray(values, np.float32))
            if old is not None and hasattr(old, "close"):
                old.close()
            return st
        if self._backend == "numpy":
            return _NumpyStore.from_values(np.asarray(values))
        import jax.numpy as jnp

        from ..core.store import ShardedParamStore

        return ShardedParamStore.from_values(jnp.asarray(values))

    # fpsanalyze: allow[S001] _build writes run under self._lock at every call site (__init__ construction, restart) — the lock is the caller's
    def _build(self) -> None:
        """(Re)materialise the local slice from the deterministic init:
        local row j = init(owned[j]) — observationally the global
        table's row ``owned[j]`` (same per-id init contract as
        :func:`~..core.store.create_table`).  Under the numpy backend
        ``init_fn`` receives (and must return) host arrays — shard
        worker processes never import jax."""
        if self._backend == "tiered":
            # NO dense materialisation: the whole point of the tier is
            # that init is recomputable per id — the store starts empty
            # and rows appear as traffic (or WAL replay) touches them
            if self.store is not None and hasattr(self.store, "close"):
                self.store.close()
            self.store = self._make_tier_store()
            self._host_mirror = None
            return
        if self._backend == "numpy":
            ids = np.asarray(self.owned, np.int64)
            if self._init_fn is not None:
                values = np.asarray(self._init_fn(ids), np.float32)
            else:
                values = np.zeros(
                    ids.shape + self.value_shape, np.float32
                )
            self.store = _NumpyStore(values)
            self._host_mirror = None
            return
        import jax.numpy as jnp

        from ..core.store import ShardedParamStore

        ids = jnp.asarray(self.owned, jnp.int32)
        if self._init_fn is not None:
            values = self._init_fn(ids)
        else:
            dtype = self._dtype if self._dtype is not None else jnp.float32
            values = jnp.zeros(ids.shape + self.value_shape, dtype)
        if self._dtype is not None:
            values = values.astype(self._dtype)
        self.store = ShardedParamStore.from_values(values)
        self._host_mirror = None

    def _replay(self) -> int:
        """Re-apply every intact WAL record in sequence order; returns
        the number replayed.  Replay bypasses the WAL append (the
        records are already durable) but goes through the same
        scatter-add, so the rebuilt slice is bitwise the logged one.

        Records come in three kinds: ``push`` (delta rows — the
        default), ``load`` (row assignments from a migration), and
        ``snapshot`` (the full owned slice, written at each epoch
        flip).  A snapshot SUPERSEDES everything before it — replay
        starts at the newest one, which is also what makes replay safe
        across reshardings: pre-flip records may reference ids this
        shard no longer owns, and the snapshot barrier keeps them out
        of the replay window."""
        records = self._wal.replay()
        start = 0
        for i, rec in enumerate(records):
            p = rec.payload
            if isinstance(p, dict) and p.get("kind") == "snapshot":
                start = i
        n = 0
        for rec in records[start:]:
            p = rec.payload
            kind = p.get("kind", "push")
            if kind == "snapshot":
                self._restore_snapshot(p)
            elif kind == "load":
                self._assign(
                    np.asarray(p["ids"], np.int64),
                    np.asarray(p["values"], np.float32),
                )
            else:
                ids = np.asarray(p["ids"], np.int64)
                # record_deltas: plain f32 records and quantized
                # (qdeltas+scales) records — a promoted follower's log
                # holds the latter when its leg shipped compressed
                # (compression/quantizers.py) — replay identically
                from ..compression.quantizers import record_deltas

                self._apply(ids, record_deltas(p))
                if p.get("pid") is not None:
                    self._remember_pairs(p["pid"], ids)
            self._push_seq = rec.end_step
            n += 1
        return n

    def _restore_snapshot(self, payload: dict) -> None:
        """Rebuild the slice from an epoch-flip snapshot record: the
        logged ids must be exactly the partitioner's owned set for this
        shard (the shard was reconstructed with the post-flip map)."""
        ids = np.asarray(payload["ids"], np.int64)
        if not np.array_equal(ids, self.owned):
            raise RuntimeError(
                f"shard {self.shard_id}: WAL snapshot owns {len(ids)} "
                f"rows but the partitioner assigns {len(self.owned)} — "
                f"replaying with a different map than the one the "
                f"snapshot was taken under"
            )
        values = np.asarray(payload["values"], np.float32)
        self.store = self._store_from_values(values)
        self._host_mirror = None
        for pair in payload.get("pairs", ()):
            self._applied_pairs[(pair[0], int(pair[1]))] = None
        self._trim_pairs()

    def _apply(self, global_ids: np.ndarray, deltas: np.ndarray) -> None:
        local = self.partitioner.to_local(self.shard_id, global_ids)
        if self._backend in ("numpy", "tiered"):
            # host scatter-add in place: no shape-specialised kernels,
            # so no pow2 bucketing either — padding existed for XLA's
            # compile cache, and numpy has none to warm.  (The tiered
            # push ensures residency first; rows the hot tier cannot
            # take write through to the slab.)
            self.store.push(local, deltas)
            self._host_mirror = None
            self.pushes_applied += 1
            return
        import jax.numpy as jnp

        # Pad to a pow2 bucket BEFORE the scatter: the per-round unique
        # -id count varies, and jax compiles one scatter kernel per
        # shape — unquantised, every push is a fresh ~100 ms XLA
        # compile (measured: 500 ms/round at 4 shards) instead of a
        # ~1 ms apply.  Padding lanes carry id −1, which store.push
        # routes to the out-of-range sentinel and drops.
        n = len(local)
        bucket = 1 << max(0, int(n - 1).bit_length())
        if bucket > n:
            local = np.concatenate(
                [local, np.full(bucket - n, -1, np.int64)]
            )
            deltas = np.concatenate(
                [deltas, np.zeros((bucket - n,) + deltas.shape[1:],
                                  deltas.dtype)]
            )
        self.store = self.store.push(
            jnp.asarray(local, jnp.int32), jnp.asarray(deltas)
        )
        self._host_mirror = None  # mirror is stale past this point
        self.pushes_applied += 1

    def _assign(self, global_ids: np.ndarray, values: np.ndarray) -> None:
        """Row ASSIGNMENT (the migration load path): owned ids are set
        bitwise in the local slice; ids this shard will own only after
        the next epoch flip are STAGED and folded in at
        :meth:`install_epoch` (scale-in hands a survivor rows it cannot
        address under the pre-flip map)."""
        ids = np.asarray(global_ids, np.int64)
        values = np.asarray(values, np.float32)
        mine = self.partitioner.shard_of(ids) == self.shard_id
        for gid, row in zip(ids[~mine], values[~mine]):
            self._staged[int(gid)] = np.array(row, np.float32)
        if mine.any():
            local = self.partitioner.to_local(self.shard_id, ids[mine])
            if self._backend == "tiered":
                # in-place tier write: resident rows update hot (and
                # dirty), cold rows go straight to the slab — a bulk
                # migration load must not thrash the hot tier or
                # materialise the dense table
                self.store.assign(local, values[mine])
                return
            # assign through the host mirror: a bulk load arrives in
            # many chunks, and a device round trip per chunk would
            # dominate migration wall time; jnp.asarray copies the
            # mirror to the device, so the mirror stays valid after.
            # (np.array, not asarray: the zero-copy view of a jax
            # buffer is read-only)
            if (
                self._host_mirror is None
                or not self._host_mirror.flags.writeable
            ):
                self._host_mirror = np.array(self.store.values())
            self._host_mirror[local] = values[mine].astype(
                self._host_mirror.dtype
            )
            self.store = self._store_from_values(self._host_mirror)

    def _remember_pairs(self, pid: str, ids: np.ndarray) -> None:
        for gid in ids:
            self._applied_pairs[(pid, int(gid))] = None
        self._trim_pairs()

    def _trim_pairs(self) -> None:
        while len(self._applied_pairs) > self.pid_window:
            self._applied_pairs.pop(next(iter(self._applied_pairs)))

    def _check_alive(self) -> None:
        if self.store is None:
            raise ShardCrashed(f"shard {self.shard_id} has no live slice")

    def _rows(self, local: np.ndarray) -> np.ndarray:
        """Read rows by LOCAL index — the pull-side table access.
        Dense backends go through the lazily-rebuilt host mirror (one
        fancy-index per request); the tiered backend gathers through
        the hot tier (misses promote from slab/init) and must NEVER
        materialise the dense mirror — that allocation is exactly the
        RSS the tier exists to avoid."""
        if self._backend == "tiered":
            return self.store.gather(local)
        if self._host_mirror is None:
            self._host_mirror = np.asarray(self.store.values())
        return self._host_mirror[local]

    def _route(self, ids: np.ndarray, epoch: Optional[int]) -> np.ndarray:
        """``to_local`` with epoch-aware failure: a routing miss under a
        mismatched frame epoch is the mixed-flight flip, not a protocol
        bug — answer stale-epoch so the client refreshes and replays."""
        try:
            return self.partitioner.to_local(self.shard_id, ids)
        except KeyError:
            if epoch is not None and epoch != self.epoch:
                raise StaleEpoch(
                    self.epoch, "ids not owned under the frame's map"
                ) from None
            raise

    # -- the shard protocol ------------------------------------------------
    def pull(
        self, global_ids: np.ndarray, *, epoch: Optional[int] = None
    ) -> np.ndarray:
        prof = self._profiler
        t_wait = time.perf_counter()
        with self._lock:
            prof.observe(
                "pull", "server_queue_wait",
                time.perf_counter() - t_wait,
            )
            self._check_alive()
            ids = np.asarray(global_ids, np.int64)
            local = self._route(ids, epoch)
            with prof.timer("pull", "scatter_apply"):
                # the pull-side table access: host-mirror fancy-index
                # (dense backends) or a tier gather (see _rows)
                vals = self._rows(local)
            self.pulls_served += 1
            if self.hotkeys is not None:
                self.hotkeys.observe(ids)
            if self._c_pulls is not None:
                self._c_pulls.inc()
            return vals

    # -- hot-key leases (hotcache/, docs/hotcache.md) -------------------------
    def lease_rows(
        self,
        global_ids: np.ndarray,
        sess: str,
        *,
        epoch: Optional[int] = None,
        ttl: Optional[int] = None,
    ) -> Tuple[np.ndarray, int, int]:
        """ATOMIC read + lease grant (the ``lease`` verb): the returned
        ``(rows, seq, ttl)`` rows are exactly the state at push
        sequence ``seq``, and from this moment any OTHER session's
        write to these keys queues a piggybacked invalidation for
        ``sess``.  One lock acquisition covers read + grant, so a write
        can never slip between them unobserved.  ``ttl`` is advisory
        (capped server-side); the client's staleness bound is the
        enforced contract."""
        if not sess:
            raise ValueError("lease needs a sess=<token> option")
        granted_ttl = min(int(ttl), 256) if ttl else 16
        if granted_ttl < 1:
            raise ValueError(f"ttl={ttl}: must be >= 1")
        prof = self._profiler
        t_wait = time.perf_counter()
        with self._lock:
            prof.observe(
                "pull", "server_queue_wait",
                time.perf_counter() - t_wait,
            )
            self._check_alive()
            ids = np.asarray(global_ids, np.int64)
            local = self._route(ids, epoch)
            with prof.timer("pull", "scatter_apply"):
                vals = self._rows(local).copy()
            self.pulls_served += 1
            if self.hotkeys is not None:
                self.hotkeys.observe(ids)
            self.leases.grant(sess, ids)
            if self._c_pulls is not None:
                self._c_pulls.inc()
            return vals, self._push_seq, granted_ttl

    def revoke_leases(self, sess: str, global_ids=None) -> int:
        """Client-requested release (the ``revoke`` verb); ``None`` ids
        releases the whole session (client shutdown)."""
        if not sess:
            raise ValueError("revoke needs a sess=<token> option")
        return self.leases.revoke(sess, global_ids)

    def push(
        self,
        global_ids: np.ndarray,
        deltas: np.ndarray,
        *,
        epoch: Optional[int] = None,
        pid: Optional[str] = None,
        sess: Optional[str] = None,
    ) -> int:
        """WRITE-AHEAD then apply; returns the shard's push sequence
        number after this push.  ``epoch`` fences against stale maps
        (old-epoch writes are rejected, never absorbed); ``pid`` makes
        the push idempotent per ``(pid, id)`` — the already-applied
        subset of a retried frame is acked without re-applying.
        ``sess`` names the writer's lease session so its own leases are
        not invalidation-queued (it invalidated locally at push time;
        every OTHER holder of a written key gets a piggybacked
        ``inv=``)."""
        prof = self._profiler
        t_wait = time.perf_counter()
        with self._lock:
            prof.observe(
                "push", "server_queue_wait",
                time.perf_counter() - t_wait,
            )
            self._check_alive()
            if epoch is not None and epoch < self.epoch:
                raise StaleEpoch(self.epoch, "old-epoch write rejected")
            ids = np.asarray(global_ids, np.int64)
            deltas = np.asarray(deltas, np.float32)
            if self._frozen is not None and np.isin(
                ids, self._frozen
            ).any():
                raise FrozenKeys(
                    f"shard {self.shard_id}: push touches a key range "
                    f"frozen for migration"
                )
            # route check first: a mis-routed id must fail the request
            # BEFORE it is logged (replaying a bad frame would re-raise
            # forever)
            self._route(ids, epoch)
            if self.hotkeys is not None:
                self.hotkeys.observe(ids)
            if pid is not None:
                fresh = np.asarray(
                    [(pid, int(g)) not in self._applied_pairs for g in ids]
                )
                if not fresh.any():
                    return self._push_seq  # full duplicate: ack only
                ids, deltas = ids[fresh], deltas[fresh]
            if self._wal is not None:
                payload = {"ids": ids, "deltas": deltas}
                if pid is not None:
                    payload["pid"] = pid
                with prof.timer("push", "wal_append"):
                    self._wal.append(self._push_seq, 1, payload)
                self._repl_offer(self._push_seq, 1, payload)
            self._push_seq += 1
            with prof.timer("push", "scatter_apply"):
                self._apply(ids, deltas)
            self.rows_applied += int(len(ids))
            # lease invalidation rides the write path: every other
            # session holding a lease on a written key gets an inv=
            # queued (board lock nests strictly under the shard lock)
            self.leases.note_write(ids, writer=sess)
            if pid is not None:
                self._remember_pairs(pid, ids)
            if self._c_pushes is not None:
                self._c_pushes.inc()
            return self._push_seq

    def flush(self) -> dict:
        """Make the log durable (fsync) and ack the counters — the wire
        protocol's explicit durability point.

        The fsync runs OUTSIDE the shard lock (fpsanalyze B001 fix):
        the WAL serializes appends/syncs internally, so holding the
        shard lock across the disk wait only stalled every concurrent
        pull/push behind the platter.  Every push appended before this
        call's lock window is covered by the sync; a push that slips in
        after the release is made durable EARLY — never lost."""
        with self._lock:
            wal = self._wal
            pushes = self.pushes_applied
        wal_records = 0
        if wal is not None:
            wal.sync()
            wal_records = wal.records_appended
        return {"pushes": pushes, "wal_records": wal_records}

    def values(self) -> np.ndarray:
        """The local slice, rows ordered by :attr:`owned` (ascending
        global id) — the shard's contribution to a model dump."""
        with self._lock:
            if self.store is None:
                raise ShardCrashed(f"shard {self.shard_id} has no live slice")
            return np.asarray(self.store.values())

    # -- elastic membership / migration (docs/elastic.md) --------------------
    def snapshot_rows(
        self, global_ids: np.ndarray
    ) -> Tuple[np.ndarray, int]:
        """ATOMIC ``(rows, seq)`` read for migration: the returned rows
        reflect exactly the pushes with sequence ≤ ``seq`` — the WAL
        tail ``> seq`` is precisely what the new owner still needs
        (``xfer`` on the wire).  One lock acquisition covers both
        reads; rows are a copy."""
        with self._lock:
            self._check_alive()
            local = self.partitioner.to_local(
                self.shard_id, np.asarray(global_ids, np.int64)
            )
            return self._rows(local).copy(), self._push_seq

    def assign_rows(
        self, global_ids: np.ndarray, values: np.ndarray
    ) -> int:
        """WAL-logged row ASSIGNMENT (the ``load`` verb): migrated rows
        land bitwise-equal — no delta arithmetic touches them — and the
        log record (kind=``load``) replays the assignment on a crash.
        Ids this shard only owns under the NEXT map are staged (see
        :meth:`_assign`); returns the shard's sequence number after."""
        with self._lock:
            self._check_alive()
            ids = np.asarray(global_ids, np.int64)
            values = np.asarray(values, np.float32)
            if len(ids) != len(values):
                raise ValueError(
                    f"{len(ids)} ids but {len(values)} value rows"
                )
            if self._wal is not None:
                payload = {"kind": "load", "ids": ids, "values": values}
                self._wal.append(self._push_seq, 1, payload)
                self._repl_offer(self._push_seq, 1, payload)
            self._push_seq += 1
            self._assign(ids, values)
            self.loads_applied += int(len(ids))
            # a migration load rewrites rows out-of-band of push: any
            # lease on them is now serving a superseded value
            self.leases.note_write(ids)
            return self._push_seq

    def freeze(self, global_ids) -> None:
        """Freeze a moving key range: pushes touching it raise
        :class:`FrozenKeys` until :meth:`install_epoch` (or
        :meth:`unfreeze`).  Pulls, and pushes of every other key, are
        untouched — non-moving keys never block."""
        with self._lock:
            ids = np.unique(np.asarray(global_ids, np.int64))
            self._frozen = (
                ids if self._frozen is None
                else np.union1d(self._frozen, ids)
            )

    def unfreeze(self) -> None:
        with self._lock:
            self._frozen = None

    def install_epoch(self, epoch: int, partitioner: Partitioner) -> None:
        """The flip: adopt the new partition map at ``epoch``.  The
        slice is compacted to the new owned set — rows kept bitwise,
        staged rows (scale-in inheritance) folded in — the freeze
        lifts, and a ``snapshot`` barrier record makes the post-flip
        WAL self-contained (replay never crosses a resharding)."""
        with self._lock:
            self._check_alive()
            if int(epoch) <= self.epoch:
                raise ValueError(
                    f"install_epoch({epoch}): shard {self.shard_id} "
                    f"already at epoch {self.epoch} (epochs are monotone)"
                )
            new_owned = partitioner.owned_ids(self.shard_id)
            mirror = np.asarray(self.store.values())
            pos = np.searchsorted(self.owned, new_owned)
            have = (pos < len(self.owned)) & (
                self.owned[np.minimum(pos, len(self.owned) - 1)]
                == new_owned
            ) if len(self.owned) else np.zeros(len(new_owned), bool)
            rows = np.empty(
                (len(new_owned),) + mirror.shape[1:], mirror.dtype
            )
            rows[have] = mirror[pos[have]]
            for j in np.nonzero(~have)[0]:
                gid = int(new_owned[j])
                if gid not in self._staged:
                    raise KeyError(
                        f"shard {self.shard_id}: epoch {epoch} assigns "
                        f"id {gid} here but no row was migrated in"
                    )
                rows[j] = self._staged[gid]
            self.partitioner = partitioner
            self.owned = new_owned
            self.store = self._store_from_values(rows)
            self._host_mirror = None
            self._staged = {}
            self._frozen = None
            self.epoch = int(epoch)
            # a resharding may re-home leased keys: queue drop-all for
            # every session (clients also clear on membership refresh)
            self.leases.drop_all()
            if self._wal is not None:
                barrier = self._push_seq
                payload = {
                    "kind": "snapshot",
                    "ids": new_owned,
                    "values": rows,
                    "pairs": list(self._applied_pairs),
                }
                self._wal.append(barrier, 1, payload)
                self._repl_offer(barrier, 1, payload)
                self._push_seq += 1
                # older segments are fully superseded by the barrier —
                # best-effort bound on the log (whole segments only)
                self._wal.truncate_through(barrier)

    def retire(self, epoch: int) -> None:
        """Drain-and-retire terminal state: the shard stops accepting
        writes (everything frozen, epoch bumped so old-epoch frames
        answer stale-epoch) but keeps serving reads until its server is
        stopped — in-flight old-map pulls drain instead of erroring."""
        with self._lock:
            self.epoch = int(epoch)
            self._frozen = np.asarray(self.owned, np.int64)

    def applied_pairs_for(self, global_ids) -> list:
        """The exactly-once ``(pid, id)`` pairs covering the given ids
        — migration hands these to the new owner so a retried push of a
        moved key stays deduplicated across the flip."""
        with self._lock:
            wanted = set(int(g) for g in np.asarray(global_ids).reshape(-1))
            return [
                pair for pair in self._applied_pairs if pair[1] in wanted
            ]

    def merge_applied_pairs(self, pairs) -> None:
        with self._lock:
            for pid, gid in pairs:
                self._applied_pairs[(pid, int(gid))] = None
            self._trim_pairs()

    def peek_rows(self, global_ids) -> np.ndarray:
        """Read rows for migration verification regardless of where
        they live: owned rows from the slice, incoming rows from the
        staging area — the pre-flip view of what :meth:`install_epoch`
        will own."""
        with self._lock:
            self._check_alive()
            ids = np.asarray(global_ids, np.int64)
            mine = self.partitioner.shard_of(ids) == self.shard_id
            if self._backend == "tiered":
                out = np.empty(
                    (len(ids),) + self.value_shape, np.float32
                )
            else:
                if self._host_mirror is None:
                    self._host_mirror = np.asarray(self.store.values())
                out = np.empty(
                    (len(ids),) + self._host_mirror.shape[1:],
                    self._host_mirror.dtype,
                )
            if mine.any():
                local = self.partitioner.to_local(self.shard_id, ids[mine])
                out[mine] = self._rows(local)
            for j in np.nonzero(~mine)[0]:
                gid = int(ids[j])
                if gid not in self._staged:
                    raise KeyError(
                        f"shard {self.shard_id}: id {gid} neither owned "
                        f"nor staged"
                    )
                out[j] = self._staged[gid]
            return out

    def wal_tail(self, after_seq: int, global_ids=None) -> list:
        """The shard's WAL records after ``after_seq`` (push-sequence
        space), keyed-filtered to ``global_ids`` — the migration tail
        (:meth:`~..resilience.wal.UpdateWAL.replay_range`).  Empty when
        the shard runs without a WAL."""
        if self._wal is None:
            return []
        return self._wal.replay_range(after_seq, global_ids)

    # -- replica chains (replication/, docs/elastic.md) ----------------------
    def attach_repl_sink(self, sink) -> None:
        """Attach the replication fan-out: every WAL record this shard
        appends from here on is also handed to ``sink.offer(start,
        n_steps, payload)`` — the primary half of the ``repl`` stream.
        The sink must be non-blocking (it is called under the shard
        lock); the :class:`~..replication.shipper.ReplHub` queues and
        lets shipper threads do the socket work."""
        with self._lock:
            self._repl_sink = sink

    def detach_repl_sink(self) -> None:
        with self._lock:
            self._repl_sink = None

    def _repl_offer(self, start_step: int, n_steps: int, payload) -> None:
        sink = self._repl_sink
        if sink is not None:
            try:
                sink.offer(start_step, n_steps, payload)
            except Exception:  # replication must never fail a write
                pass

    def head_seq(self) -> int:
        """The primary's current push-sequence head — what a follower's
        lag is measured against (rides ``repl`` frames as ``head=``)."""
        with self._lock:
            return self._push_seq

    def repl_backlog(self, after_seq: int) -> list:
        """The shippable WAL tail: records with ``end_step >
        after_seq``, starting no earlier than the newest snapshot
        barrier (a snapshot supersedes everything before it — shipping
        pre-barrier records to a follower built under the current map
        would reference ids it cannot route).  The shipper's resync
        path: bootstrap (``after_seq=-1``) and reconnect both land
        here."""
        if self._wal is None:
            return []
        records = self._wal.replay()
        start = 0
        for i, rec in enumerate(records):
            p = rec.payload
            if isinstance(p, dict) and p.get("kind") == "snapshot":
                start = i
        return [r for r in records[start:] if r.end_step > after_seq]

    def apply_repl(self, record, head=None) -> dict:
        """Receive one shipped WAL record (the ``repl`` verb).  Only a
        follower accepts the stream; the base (primary) shard rejects
        it as a routing error — see
        :class:`~..replication.follower.ReplicaShard`."""
        raise ValueError(
            f"shard {self.shard_id} is a {self.role}, not a replication "
            f"follower — repl frames route to followers only"
        )

    def repl_state(self) -> dict:
        """One-line replication state (the ``replstate`` verb): role +
        the sequence cursors a failover decision reads.  Followers
        override with their lag figures."""
        with self._lock:
            return {
                "shard": self.shard_id,
                "role": self.role,
                "seq": self._push_seq,
                "epoch": self.epoch,
            }

    # -- failure / recovery -------------------------------------------------
    def crash(self) -> None:
        """Chaos hook: drop the in-memory slice (the WAL survives — it
        is the durable part).  Every subsequent request raises
        :class:`ShardCrashed` until :meth:`restart`."""
        with self._lock:
            if self._backend == "tiered" and self.store is not None:
                # the slab is part of the slice (a cache, not a
                # durability plane) — a crash loses it with the hot
                # rows, and replay repopulates the mutated set
                self.store.close()
            self.store = None
            self._host_mirror = None

    def restart(self) -> int:
        """Rebuild init + replay the WAL; returns records replayed."""
        with self._lock:
            self._push_seq = 0
            self.pushes_applied = 0
            self._build()
            replayed = self._replay() if self._wal is not None else 0
            # the board did not see writes replayed from the WAL —
            # conservatively drop every remembered session's leases
            # (holders fall back to their local staleness bound)
            self.leases.drop_all()
            self.restarts += 1
            if self._c_restarts is not None:
                self._c_restarts.inc()
            return replayed

    def stats(self) -> dict:
        with self._lock:
            out = {
                "shard": self.shard_id,
                "role": self.role,
                "rows": int(len(self.owned)),
                "pulls": self.pulls_served,
                "pushes": self.pushes_applied,
                "push_seq": self._push_seq,
                "restarts": self.restarts,
                "alive": self.store is not None,
                "epoch": self.epoch,
                "rows_applied": self.rows_applied,
                "loads_applied": self.loads_applied,
                "frozen": (
                    0 if self._frozen is None else int(len(self._frozen))
                ),
                "staged": len(self._staged),
                # live depth figures the psctl stats view reads: WAL
                # records durably appended and the exactly-once dedupe
                # window's current size (bounded by pid_window)
                "wal_records": (
                    0 if self._wal is None else self._wal.records_appended
                ),
                "dedupe_pairs": len(self._applied_pairs),
                # hot-key lease board depth (hotcache/, psctl hot)
                "lease_sessions": self.leases.sessions(),
                "leases_active": self.leases.active_leases(),
            }
            if self._backend == "tiered" and self.store is not None:
                out["tier"] = self.store.stats()
            return out

    def close(self) -> None:
        if self._backend == "tiered":
            from ..tierstore import metrics as tier_metrics

            tier_metrics.unregister_store(self._tier_label())
            with self._lock:
                if self.store is not None:
                    self.store.close()
                    self.store = None
        if self._wal is not None:
            self._wal.close()


class ShardServer(LineServer):
    """TCP front end + restart supervisor for one :class:`ParamShard`.

    The supervisor loop is the shard-side analogue of
    :class:`~..resilience.recovery.RecoveringDriver`: a request that
    finds the slice dead triggers backoff (capped exponential, jittered
    per :class:`~..resilience.recovery.RestartPolicy`) + rebuild-and-
    replay, then the request is served from the recovered slice — the
    CLIENT never sees the crash, only latency.  ``supervised=False``
    turns the same condition into an ``err crashed`` response (the
    client-visible failure mode).
    """

    def __init__(
        self,
        shard: ParamShard,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        supervised: bool = True,
        restart_policy=None,
        max_line_bytes: int = 64 << 20,
        tracer=None,
        profiler=None,
        overload=None,
        enable_shm: bool = True,
    ):
        super().__init__(
            host, port, name=f"shard-{shard.shard_id}",
            max_line_bytes=max_line_bytes,
        )
        # accept "hello shm v=1" (shmem/): co-located clients hand the
        # data plane to a shared-memory ring pair; False answers the
        # downgrade err and every client falls back to binary TCP
        self.shm_enabled = bool(enable_shm)
        self.shard = shard
        self.supervised = supervised
        # overload-plane admission (loadgen/overload.OverloadGuard):
        # with a guard attached, sheddable frames are answered
        # ``err overloaded`` BEFORE parse/lock/apply once the live
        # request depth passes the guard's thresholds — serving/lease
        # reads shed first, training pushes never (by default).  None
        # = admit everything (the pre-overload behaviour).
        self.overload = overload
        # latency-budget phases (telemetry/profiler.py): whole-request
        # server wall (the "wire" residual's subtrahend), inbound parse
        # and response serialize — default to the shard's profiler so
        # client+server phases land in one budget
        from ..telemetry.profiler import resolve_profiler

        self.profiler = (
            shard._profiler if profiler is None
            else resolve_profiler(profiler)
        )
        # server-side spans (telemetry/distributed.py): each request is
        # wrapped in a span tagged with the inbound t=<trace>:<span>
        # context, so this process's ring can be merged into the
        # client's trace by the TraceCollector
        self.tracer = tracer
        if restart_policy is None:
            from ..resilience.recovery import RestartPolicy

            # tight backoff: a shard restart is rebuild+replay, not a
            # process respawn; tests and thread-backed clusters should
            # not serialize on seconds of sleep
            restart_policy = RestartPolicy(
                max_restarts=3, backoff_base_s=0.01, backoff_cap_s=0.5,
                seed=shard.shard_id,
            )
        self.policy = restart_policy
        self._rng = np.random.default_rng(self.policy.seed)

    # -- the protocol ------------------------------------------------------
    @staticmethod
    def _frame_priority(toks) -> Optional[int]:
        """The ``pr=<n>`` priority token from a frame's trailing
        options (scanned from the end, same discipline as
        :meth:`_inbound_trace`: payload tokens stop the scan).
        Malformed values yield None — priority must never be able to
        fail a request."""
        for t in reversed(toks[1:]):
            k, sep, v = t.partition("=")
            if not sep or not k.isalnum():
                break
            if k == "pr":
                try:
                    return int(v)
                except ValueError:
                    return None
        return None

    def respond(self, line: str) -> str:
        with self.shard._depth_lock:
            self.shard._active_requests += 1
            depth = self.shard._active_requests
        verb = line.split(None, 1)[0].lower() if line else ""
        t0 = time.perf_counter()
        try:
            guard = self.overload
            if guard is not None and not guard.admit(
                verb, self._frame_priority(line.split()), depth
            ):
                # typed shed (docs/loadgen.md): rejected before the
                # request pays parse/lock/apply — overload must make
                # rejection the CHEAPEST path through the server
                return "err overloaded"
            return self._respond_supervised(line)
        finally:
            with self.shard._depth_lock:
                self.shard._active_requests -= 1
            if verb in ("pull", "push"):
                # the whole-request server wall: what the client's RTT
                # minus this equals is the wire cost (profiler budget)
                self.profiler.observe(
                    verb, "server_total", time.perf_counter() - t0
                )

    def _respond_supervised(self, line: str) -> str:
        attempt = 0
        while True:
            try:
                return self._dispatch(line)
            except ShardCrashed:
                if not self.supervised:
                    return "err crashed"
                attempt += 1
                if attempt > self.policy.max_restarts:
                    return "err crashed: restart budget exhausted"
                time.sleep(self.policy.backoff_s(attempt, self._rng))
                self.shard.restart()
            except StaleEpoch as e:
                return f"err stale-epoch epoch={e.shard_epoch}"
            except FrozenKeys:
                return "err frozen"
            except FollowerLagging as e:
                return f"err lagging lag={e.lag}"
            except NotPrimary:
                return "err not-primary"
            except (ValueError, KeyError) as e:
                return f"err bad-request: {e}"
            except Exception as e:  # noqa: BLE001 — protocol boundary
                return f"err internal: {type(e).__name__}: {e}"

    @staticmethod
    def _parse_opts(toks) -> dict:
        """Trailing ``key=value`` option tokens (``e=<epoch>``,
        ``pid=<token>``)."""
        opts = {}
        for t in toks:
            k, sep, v = t.partition("=")
            if not sep or not k:
                raise ValueError(f"bad option token {t!r} (key=value)")
            opts[k] = v
        epoch = opts.pop("e", None)
        if epoch is not None:
            try:
                opts["e"] = int(epoch)
            except ValueError:
                raise ValueError(f"e={epoch!r}: epoch must be an integer")
        return opts

    @staticmethod
    def _inbound_trace(toks):
        """The ``t=<trace>:<span>`` token from a frame's trailing
        options (scanned from the end; payload tokens — which may
        contain base64 ``=`` padding behind their ``b64:`` prefix —
        stop the scan).  Malformed tokens yield None, never an error:
        tracing must not be able to fail a request."""
        from ..telemetry.distributed import parse_token

        for t in reversed(toks[1:]):
            k, sep, v = t.partition("=")
            if not sep or not k.isalnum():
                break
            if k == "t":
                return parse_token(v)
        return None

    def _dispatch(self, line: str) -> str:
        tr = self.tracer
        if tr is None or not tr.enabled:
            return self._execute(line)
        toks = line.split()
        cmd = toks[0].lower() if toks else "empty"
        ctx = self._inbound_trace(toks)
        kwargs = (
            {"trace_id": ctx.trace_id, "parent_id": ctx.span_id}
            if ctx is not None else {}
        )
        with tr.span(f"shard.{cmd}", "cluster", **kwargs):
            return self._execute(line)

    def _with_inv(self, resp: str, opts: dict) -> str:
        """Piggyback pending lease invalidations for the frame's
        session as a trailing ``inv=`` token (docs/hotcache.md).  Only
        frames that declared ``sess=`` ever get one, so pre-hotcache
        clients never see a token they cannot parse."""
        sess = opts.get("sess")
        if sess is None:
            return resp
        inv = self.shard.leases.take_invalidations(sess)
        if inv:
            resp += f" inv={inv}"
        return resp

    def _execute(self, line: str) -> str:
        toks = line.split()
        cmd = toks[0].lower()
        if cmd == "hello":
            # binary-framing negotiation (docs/cluster.md "Binary
            # framing", utils/frames.py): "hello bin v=1" → "ok
            # proto=bin v=1", and the connection accepts binary frames
            # from then on (the net layer flips the conn ledger's
            # proto on this exact answer).  Old servers reach their
            # unknown-command ValueError instead — "err bad-request"
            # — and the client stays on the line protocol: the PR-6
            # versioning contract covering the whole framing.
            if len(toks) >= 2 and toks[1].lower() == "bin":
                # the answer advertises the quantized-encoding
                # vocabulary (enc=bf16,q8 — docs/compression.md): old
                # clients check the "ok proto=bin" prefix only, new
                # clients downgrade unadvertised encodings to f32
                return binf.hello_ok_line()
            # "hello shm" lands here only when shm is DISABLED (the
            # enabled path is intercepted in LineServer._serve_one) —
            # the err answer is what drives the client's TCP fallback
            raise ValueError(
                f"unknown protocol {' '.join(toks[1:])!r} (try: bin)"
            )
        if cmd == "pull":
            if len(toks) < 2:
                raise ValueError(
                    "usage: pull <id1,id2,...> [text|b64] [e=<epoch>]"
                )
            rest = toks[2:]
            enc = "text"
            if rest and rest[0].lower() in ("text", "b64"):
                enc = rest[0].lower()
                rest = rest[1:]
            elif rest and "=" not in rest[0]:
                raise ValueError(f"pull format {rest[0]!r}: 'text' | 'b64'")
            opts = self._parse_opts(rest)
            with self.profiler.timer("pull", "server_parse"):
                ids = parse_ids(toks[1])
            vals = self.shard.pull(ids, epoch=opts.get("e"))
            with self.profiler.timer("pull", "response_serialize"):
                body = format_rows(vals, enc)
            return self._with_inv(f"ok n={len(ids)} {body}", opts)
        if cmd == "push":
            if len(toks) < 3:
                raise ValueError(
                    "usage: push <id1,id2,...> <row1;row2;...> "
                    "[pid=<token>] [e=<epoch>]"
                )
            with self.profiler.timer("push", "server_parse"):
                ids = parse_ids(toks[1])
                deltas = parse_rows(toks[2], self.shard.value_shape)
            if len(deltas) != len(ids):
                raise ValueError(
                    f"{len(ids)} ids but {len(deltas)} delta rows"
                )
            opts = self._parse_opts(toks[3:])
            seq = self.shard.push(
                ids, deltas, epoch=opts.get("e"), pid=opts.get("pid"),
                sess=opts.get("sess"),
            )
            return self._with_inv(f"ok applied={len(ids)} seq={seq}", opts)
        if cmd == "lease":
            # atomic read + lease grant (hotcache/, docs/hotcache.md):
            # answered rows are exactly the state at the answered seq
            if len(toks) < 2:
                raise ValueError(
                    "usage: lease <id1,id2,...> [text|b64] sess=<token> "
                    "[ttl=<rounds>] [e=<epoch>]"
                )
            rest = toks[2:]
            enc = "text"
            if rest and rest[0].lower() in ("text", "b64"):
                enc = rest[0].lower()
                rest = rest[1:]
            elif rest and "=" not in rest[0]:
                raise ValueError(
                    f"lease format {rest[0]!r}: 'text' | 'b64'"
                )
            opts = self._parse_opts(rest)
            ids = parse_ids(toks[1])
            ttl = opts.get("ttl")
            if ttl is not None:
                try:
                    ttl = int(ttl)
                except ValueError:
                    raise ValueError(
                        f"ttl={ttl!r}: must be an integer"
                    ) from None
            vals, seq, ttl = self.shard.lease_rows(
                ids, opts.get("sess"), epoch=opts.get("e"), ttl=ttl,
            )
            body = format_rows(vals, enc)
            return self._with_inv(
                f"ok n={len(ids)} seq={seq} ttl={ttl} {body}", opts
            )
        if cmd == "revoke":
            if len(toks) < 2:
                raise ValueError(
                    "usage: revoke <id1,id2,...|all> sess=<token>"
                )
            opts = self._parse_opts(toks[2:])
            ids = None if toks[1].lower() == "all" else parse_ids(toks[1])
            n = self.shard.revoke_leases(opts.get("sess"), ids)
            return f"ok revoked={n}"
        if cmd == "xfer":
            if len(toks) < 2:
                raise ValueError("usage: xfer <id1,id2,...> [t=<token>]")
            ids = parse_ids(toks[1])
            self._parse_opts(toks[2:])  # trace token etc.; validated only
            vals, seq = self.shard.snapshot_rows(ids)
            return f"ok n={len(ids)} seq={seq} {format_rows(vals, 'b64')}"
        if cmd == "load":
            if len(toks) < 3:
                raise ValueError("usage: load <id1,id2,...> <payload>")
            ids = parse_ids(toks[1])
            vals = parse_rows(toks[2], self.shard.value_shape)
            if len(vals) != len(ids):
                raise ValueError(
                    f"{len(ids)} ids but {len(vals)} value rows"
                )
            self._parse_opts(toks[3:])  # validate; load is controller-driven
            seq = self.shard.assign_rows(ids, vals)
            return f"ok loaded={len(ids)} seq={seq}"
        if cmd == "repl":
            # the replication stream (replication/shipper.py): one WAL
            # record, CRC-framed exactly as on disk, applied by a
            # follower; the response line IS the (segment, seq) ack
            if len(toks) < 2:
                raise ValueError("usage: repl <b64-frame> [head=<n>]")
            from ..resilience.wal import decode_frame

            opts = self._parse_opts(toks[2:])
            head = opts.get("head")
            if head is not None:
                try:
                    head = int(head)
                except ValueError:
                    raise ValueError(
                        f"head={head!r}: must be an integer"
                    ) from None
            rec = decode_frame(toks[1])
            ack = self.shard.apply_repl(rec, head=head)
            return (
                f"ok acked seg={ack['seg']} seq={ack['seq']} "
                f"applied={ack['applied']}"
            )
        if cmd == "replstate":
            return "ok " + json.dumps(self.shard.repl_state())
        if cmd == "flush":
            f = self.shard.flush()
            return f"ok pushes={f['pushes']} wal_records={f['wal_records']}"
        if cmd == "stats":
            return "ok " + json.dumps(self.shard.stats())
        if cmd == "conns":
            # psctl debug verb: the live per-connection wire ledger
            # (utils/net.py ConnStats) of THIS shard's front end
            return "ok " + json.dumps(self.conn_table())
        raise ValueError(
            f"unknown command {cmd!r} (pull|push|lease|revoke|xfer|load"
            f"|repl|replstate|flush|stats|conns)"
        )

    # -- the binary frame protocol (utils/frames.py) -------------------------
    def respond_frame(self, data: bytes) -> bytes:
        """One binary request frame → one encoded response frame —
        the binary twin of :meth:`respond`.  The overload guard admits
        or sheds on the HEADER alone (verb id + priority byte), before
        any TLV/id/payload work: under pressure, rejection stays the
        cheapest path through the server, now without even a text
        parse in front of it."""
        with self.shard._depth_lock:
            self.shard._active_requests += 1
            depth = self.shard._active_requests
        verb = "other"
        t0 = time.perf_counter()
        try:
            try:
                verb_id, _enc, prio, _total = binf.peek_header(data)
            except binf.FrameError as e:
                return binf.error_response(
                    0, binf.STATUS_BAD_REQUEST, str(e)
                )
            verb = binf.VERB_NAMES.get(verb_id, "other")
            guard = self.overload
            if guard is not None and not guard.admit(
                verb,
                None if prio == binf.NO_PRIORITY else int(prio),
                depth,
            ):
                return binf.error_response(
                    verb_id, binf.STATUS_OVERLOADED
                )
            return self._respond_frame_supervised(data, verb_id, verb)
        finally:
            with self.shard._depth_lock:
                self.shard._active_requests -= 1
            if verb in ("pull", "push"):
                self.profiler.observe(
                    verb, "server_total", time.perf_counter() - t0
                )

    def _respond_frame_supervised(
        self, data: bytes, verb_id: int, verb: str
    ) -> bytes:
        attempt = 0
        while True:
            try:
                req = binf.decode(data, kind="request")
                return self._dispatch_frame(req)
            except ShardCrashed:
                if not self.supervised:
                    return binf.error_response(
                        verb_id, binf.STATUS_CRASHED
                    )
                attempt += 1
                if attempt > self.policy.max_restarts:
                    return binf.error_response(
                        verb_id, binf.STATUS_CRASHED,
                        "restart budget exhausted",
                    )
                time.sleep(self.policy.backoff_s(attempt, self._rng))
                self.shard.restart()
            except StaleEpoch as e:
                return binf.error_response(
                    verb_id, binf.STATUS_STALE_EPOCH,
                    tlvs=[(binf.T_EPOCH, str(e.shard_epoch).encode())],
                )
            except FrozenKeys:
                return binf.error_response(verb_id, binf.STATUS_FROZEN)
            except FollowerLagging as e:
                return binf.error_response(
                    verb_id, binf.STATUS_LAGGING,
                    tlvs=[(binf.T_LAG, str(e.lag).encode())],
                )
            except NotPrimary:
                return binf.error_response(
                    verb_id, binf.STATUS_NOT_PRIMARY
                )
            except (binf.FrameError, ValueError, KeyError) as e:
                return binf.error_response(
                    verb_id, binf.STATUS_BAD_REQUEST, str(e)
                )
            except Exception as e:  # noqa: BLE001 — protocol boundary
                return binf.error_response(
                    verb_id, binf.STATUS_INTERNAL,
                    f"{type(e).__name__}: {e}",
                )

    def _dispatch_frame(self, req) -> bytes:
        tr = self.tracer
        if tr is None or not tr.enabled:
            return self._execute_frame(req)
        from ..telemetry.distributed import parse_token

        tok = req.tlv_str(binf.T_TRACE)
        ctx = parse_token(tok) if tok else None
        kwargs = (
            {"trace_id": ctx.trace_id, "parent_id": ctx.span_id}
            if ctx is not None else {}
        )
        with tr.span(f"shard.{req.verb_name}", "cluster", **kwargs):
            return self._execute_frame(req)

    @staticmethod
    def _frame_ids(req) -> np.ndarray:
        """The request's id section with the line protocol's bounds
        (at least one id, frames stay bounded) — ZERO-COPY ``<i8``
        over the receive buffer."""
        ids = req.ids
        if ids is None or ids.size == 0:
            raise ValueError("need at least one id")
        if ids.size > _MAX_IDS_PER_REQUEST:
            raise ValueError(
                f"{ids.size} ids in one request (max "
                f"{_MAX_IDS_PER_REQUEST}); chunk the batch"
            )
        return ids

    @staticmethod
    def _row_enc(req) -> int:
        """The row encoding the answer should use — the request's own
        (fp32 default; bf16 when the client asked for it)."""
        return (
            req.enc if req.enc in (binf.ENC_F32, binf.ENC_BF16)
            else binf.ENC_F32
        )

    def _inv_tlvs(self, sess: Optional[str]) -> list:
        """Piggybacked lease invalidations as a response TLV — only
        for frames that declared a session, exactly like the line
        protocol's trailing ``inv=`` token (docs/hotcache.md)."""
        if sess is None:
            return []
        inv = self.shard.leases.take_invalidations(sess)
        return [] if not inv else [(binf.T_INV, inv.encode())]

    def _execute_frame(self, req) -> bytes:
        """The binary dispatch: same verbs, same shard methods, no
        text — ids arrive as raw ``<i8``, rows as raw ``<f4``/bf16
        (zero-copy views; the scatter path copies as it pads), and the
        answer's rows leave as raw bytes again."""
        shard = self.shard
        verb = req.verb
        epoch = None if req.aux == binf.NO_EPOCH else int(req.aux)
        sess = req.tlv_str(binf.T_SESS)
        if verb == binf.VERB_IDS["pull"]:
            with self.profiler.timer("pull", "server_parse"):
                ids = self._frame_ids(req)
            vals = shard.pull(ids, epoch=epoch)
            enc = self._row_enc(req)
            with self.profiler.timer("pull", "response_serialize"):
                resp = binf.encode_response(
                    verb, n=int(ids.size), enc=enc,
                    payload=binf.rows_to_payload(vals, enc),
                    tlvs=self._inv_tlvs(sess),
                )
            return resp
        if verb == binf.VERB_IDS["push"]:
            with self.profiler.timer("push", "server_parse"):
                ids = self._frame_ids(req)
                if req.enc == binf.ENC_Q8:
                    # per-row-scaled int8 deltas (the quantized push
                    # path, docs/compression.md): int8 payload + f32
                    # scales in the T_SCALE TLV, dequantized host-side
                    # — the applied rows are exactly the dq values the
                    # client computed its residual against
                    from ..compression.quantizers import q8_from_payload

                    deltas = q8_from_payload(
                        req.payload, req.tlvs.get(binf.T_SCALE),
                        shard.value_shape,
                    )
                else:
                    deltas = binf.rows_from_payload(
                        req.payload, shard.value_shape, req.enc
                    )
            if len(deltas) != len(ids):
                raise ValueError(
                    f"{len(ids)} ids but {len(deltas)} delta rows"
                )
            seq = shard.push(
                ids, deltas, epoch=epoch,
                pid=req.tlv_str(binf.T_PID), sess=sess,
            )
            with self.profiler.timer("push", "response_serialize"):
                resp = binf.encode_response(
                    verb, aux=seq, n=int(ids.size), enc=binf.ENC_RAW,
                    tlvs=self._inv_tlvs(sess),
                )
            return resp
        if verb == binf.VERB_IDS["lease"]:
            ids = self._frame_ids(req)
            vals, seq, ttl = shard.lease_rows(
                ids, sess, epoch=epoch, ttl=req.tlv_int(binf.T_TTL),
            )
            enc = self._row_enc(req)
            return binf.encode_response(
                verb, aux=seq, n=int(ids.size), enc=enc,
                payload=binf.rows_to_payload(vals, enc),
                tlvs=[(binf.T_TTL, str(ttl).encode())]
                + self._inv_tlvs(sess),
            )
        if verb == binf.VERB_IDS["revoke"]:
            ids = None if req.n == 0 else self._frame_ids(req)
            n = shard.revoke_leases(sess, ids)
            return binf.encode_response(verb, n=n, enc=binf.ENC_RAW)
        if verb == binf.VERB_IDS["xfer"]:
            ids = self._frame_ids(req)
            vals, seq = shard.snapshot_rows(ids)
            return binf.encode_response(
                verb, aux=seq, n=int(ids.size), enc=binf.ENC_F32,
                payload=binf.rows_to_payload(vals, binf.ENC_F32),
            )
        if verb == binf.VERB_IDS["load"]:
            ids = self._frame_ids(req)
            vals = binf.rows_from_payload(
                req.payload, shard.value_shape, req.enc
            )
            if len(vals) != len(ids):
                raise ValueError(
                    f"{len(ids)} ids but {len(vals)} value rows"
                )
            seq = shard.assign_rows(ids, vals)
            return binf.encode_response(
                verb, aux=seq, n=int(ids.size), enc=binf.ENC_RAW
            )
        if verb == binf.VERB_IDS["repl"]:
            # the replication stream: the payload IS the on-disk CRC
            # record — raw bytes, no base64 (replication/shipper.py)
            from ..resilience.wal import decode_frame_bytes

            rec = decode_frame_bytes(bytes(req.payload))
            ack = shard.apply_repl(rec, head=req.tlv_int(binf.T_HEAD))
            return binf.encode_response(
                verb, aux=int(ack["seq"]), n=int(ack["applied"]),
                enc=binf.ENC_RAW,
                tlvs=[(binf.T_SEG, str(ack["seg"]).encode())],
            )
        if verb == binf.VERB_IDS["replstate"]:
            return binf.encode_response(
                verb, enc=binf.ENC_RAW,
                payload=json.dumps(shard.repl_state()).encode(),
            )
        if verb == binf.VERB_IDS["flush"]:
            f = shard.flush()
            return binf.encode_response(
                verb, n=int(f["pushes"]), enc=binf.ENC_RAW,
                tlvs=[(binf.T_WALREC, str(f["wal_records"]).encode())],
            )
        if verb == binf.VERB_IDS["stats"]:
            return binf.encode_response(
                verb, enc=binf.ENC_RAW,
                payload=json.dumps(shard.stats()).encode(),
            )
        if verb == binf.VERB_IDS["conns"]:
            return binf.encode_response(
                verb, enc=binf.ENC_RAW,
                payload=json.dumps(self.conn_table()).encode(),
            )
        raise ValueError(f"unknown verb id {verb}")


__all__ = [
    "ParamShard",
    "ShardServer",
    "ShardCrashed",
    "StaleEpoch",
    "FrozenKeys",
    "NotPrimary",
    "FollowerLagging",
    "format_rows",
    "parse_rows",
    "parse_ids",
]
