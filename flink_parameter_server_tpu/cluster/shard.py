"""A parameter-server shard: one partition slice, served over TCP.

This is the reference's PS subtask made a real process boundary: shard
``s`` owns exactly the rows ``partitioner.owned_ids(s)`` as a dense
local :class:`~..core.store.ShardedParamStore` slice, and answers
PULL / PUSH / FLUSH over the same newline-delimited TCP idiom as the
serving plane (``serving/server.py``) and the ingest edge
(``data/socket.py``) — the socket skeleton itself comes from
:class:`~..utils.net.LineServer`.

Wire protocol (one request line → one response line, in order, per
connection)::

    pull <id1,id2,...> [text|b64]         # global ids + answer format
    push <id1,id2,...> <payload>          # deltas, one row per id
    flush                                 # fsync the WAL, ack counters
    stats                                 # one-line JSON shard stats

    ok n=<k> <payload>                    # pull answer
    ok applied=<k> seq=<n>                # push answer
    ok pushes=<n> wal_records=<m>         # flush answer
    err <reason>                          # bad-request | crashed | internal

Row payloads come in two self-describing encodings, both EXACT (a
pulled row is bitwise the stored fp32 row — what lets a bound-0
cluster land allclose-tight against the single-process table):

  * text — ``;``-separated rows of ``,``-separated ``repr()`` floats
    (``repr`` round-trips the fp32 value exactly); the idiom of the
    serving plane and the one a human types into ``nc``;
  * ``b64:<base64>`` — little-endian fp32 row-major bytes, base64'd.
    ~100× cheaper to encode/decode than per-float text (measured:
    37 ms → 0.3 ms for a 2048×16 payload), which on a thread-backed
    single-host cluster is the difference between measuring the
    runtime and measuring ``repr()``.  The client's default.

Durability + supervised restart (the resilience wiring): every push is
appended to a per-shard :class:`~..resilience.wal.UpdateWAL` BEFORE it
is applied, keyed by the shard's monotone push sequence (idempotent on
replay).  A crash — real, or injected via :meth:`ParamShard.crash` —
loses the in-memory slice only: :class:`ShardServer` classifies the
failure, backs off per :class:`~..resilience.recovery.RestartPolicy`,
rebuilds the slice from its deterministic init, replays the WAL, and
re-serves the request that found the shard dead.  The recovered slice
is bitwise the pre-crash one (init is deterministic per id; replay
re-applies the exact logged deltas in order).

Per-shard telemetry (``component=cluster``, ``shard=<i>`` labels):
pull/push counters, a live in-flight request-depth gauge, and a
restarts counter — scrapeable mid-run through the shared
``/metrics`` endpoint.
"""
from __future__ import annotations

import base64
import json
import threading
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from ..utils.net import LineServer
from .partition import Partitioner

_MAX_IDS_PER_REQUEST = 1 << 16  # frames stay line-sized; clients chunk


class ShardCrashed(RuntimeError):
    """The shard's in-memory slice is gone (chaos-injected or real);
    tagged so :func:`~..resilience.recovery.classify_failure` routes it
    down the DEVICE branch."""

    failure_class = "device"


def format_rows(rows: np.ndarray, encoding: str = "text") -> str:
    """Encode fp32 rows for the wire (see module docstring): ``text``
    uses per-float ``repr`` (exact, human-readable), ``b64`` base64s
    the raw little-endian fp32 bytes (exact, ~100× cheaper)."""
    if encoding == "b64":
        arr = np.ascontiguousarray(np.asarray(rows, "<f4"))
        return "b64:" + base64.b64encode(arr.tobytes()).decode("ascii")
    if encoding != "text":
        raise ValueError(f"encoding={encoding!r}: 'text' | 'b64'")
    rows = np.asarray(rows, np.float64)
    rows = rows.reshape(rows.shape[0], -1) if rows.ndim > 1 else rows.reshape(-1, 1)
    return ";".join(",".join(repr(float(v)) for v in row) for row in rows)


def parse_rows(body: str, value_shape: Tuple[int, ...]) -> np.ndarray:
    """Inverse of :func:`format_rows` (either encoding, self-described
    by the ``b64:`` prefix): ``(n, *value_shape)`` float32."""
    width = 1
    for s in value_shape:
        width *= int(s)
    if body.startswith("b64:"):
        raw = base64.b64decode(body[4:].encode("ascii"))
        flat = np.frombuffer(raw, "<f4")
        if width == 0 or flat.size % width:
            raise ValueError(
                f"b64 payload of {flat.size} floats does not tile value "
                f"shape {value_shape}"
            )
        return flat.reshape((flat.size // width,) + tuple(value_shape)).copy()
    rows = [
        [float(v) for v in row.split(",") if v]
        for row in body.split(";")
        if row
    ]
    arr = np.asarray(rows, np.float32)
    if arr.ndim != 2 or arr.shape[1] != width:
        raise ValueError(
            f"rows of width {arr.shape[1] if arr.ndim == 2 else '?'} do not "
            f"match value shape {value_shape}"
        )
    return arr.reshape((arr.shape[0],) + tuple(value_shape))


def parse_ids(tok: str) -> np.ndarray:
    ids = np.asarray(
        [int(t) for t in tok.split(",") if t.strip()], np.int64
    )
    if ids.size == 0:
        raise ValueError("need at least one id")
    if ids.size > _MAX_IDS_PER_REQUEST:
        raise ValueError(
            f"{ids.size} ids in one request (max {_MAX_IDS_PER_REQUEST}); "
            f"chunk the batch"
        )
    return ids


class ParamShard:
    """One shard's state: the local store slice + per-shard WAL.

    Thread-safe: one lock serializes pulls/pushes/restarts (a shard is
    a single logical owner of its rows — the reference's per-subtask
    ``HashMap`` had the same serial discipline, enforced by Flink's
    operator model there and by this lock here).
    """

    def __init__(
        self,
        shard_id: int,
        partitioner: Partitioner,
        value_shape: Sequence[int] = (),
        *,
        init_fn=None,
        dtype=None,
        wal_dir: Optional[str] = None,
        wal_fsync_every: int = 0,
        registry=None,
    ):
        self.shard_id = int(shard_id)
        self.partitioner = partitioner
        self.value_shape = tuple(int(s) for s in value_shape)
        self._init_fn = init_fn
        self._dtype = dtype
        self.owned = partitioner.owned_ids(self.shard_id)
        self._lock = threading.RLock()
        self._wal = None
        if wal_dir is not None:
            from ..resilience.wal import UpdateWAL

            # fsync cadence 0 by default: shard durability here is about
            # surviving a shard RESTART (process alive, slice lost), the
            # chaos mode tests exercise; page-cache durability suffices
            # and per-push fsyncs would dominate small-push latency
            self._wal = UpdateWAL(wal_dir, fsync_every=wal_fsync_every)
        self.pushes_applied = 0
        self.pulls_served = 0
        self.restarts = 0
        self._push_seq = 0
        self.store = None
        # host-side read mirror of the slice, rebuilt lazily after each
        # push: pulls are then one numpy fancy-index instead of an
        # eager jax gather + transfer per request (~2 ms → ~µs on the
        # thread-backed CPU topology)
        self._host_mirror: Optional[np.ndarray] = None
        self._build()
        if self._wal is not None and self._wal.last_step_logged is not None:
            # fresh process over an existing WAL dir: the restart path
            self._replay()
        # unified plane: per-shard instruments under component=cluster
        self._active_requests = 0
        if registry is not False:
            from ..telemetry.registry import get_registry

            reg = registry if registry is not None else get_registry()
            sid = str(self.shard_id)
            self._c_pulls = reg.counter(
                "cluster_pulls_total", component="cluster", shard=sid
            )
            self._c_pushes = reg.counter(
                "cluster_pushes_total", component="cluster", shard=sid
            )
            self._c_restarts = reg.counter(
                "cluster_shard_restarts_total", component="cluster",
                shard=sid,
            )
            reg.gauge(
                "cluster_shard_queue_depth", component="cluster", shard=sid,
                fn=lambda: self._active_requests,
            )
        else:
            self._c_pulls = self._c_pushes = self._c_restarts = None

    # -- construction / recovery -------------------------------------------
    def _build(self) -> None:
        """(Re)materialise the local slice from the deterministic init:
        local row j = init(owned[j]) — observationally the global
        table's row ``owned[j]`` (same per-id init contract as
        :func:`~..core.store.create_table`)."""
        import jax.numpy as jnp

        from ..core.store import ShardedParamStore

        ids = jnp.asarray(self.owned, jnp.int32)
        if self._init_fn is not None:
            values = self._init_fn(ids)
        else:
            dtype = self._dtype if self._dtype is not None else jnp.float32
            values = jnp.zeros(ids.shape + self.value_shape, dtype)
        if self._dtype is not None:
            values = values.astype(self._dtype)
        self.store = ShardedParamStore.from_values(values)
        self._host_mirror = None

    def _replay(self) -> int:
        """Re-apply every intact WAL record in sequence order; returns
        the number replayed.  Replay bypasses the WAL append (the
        records are already durable) but goes through the same
        scatter-add, so the rebuilt slice is bitwise the logged one."""
        n = 0
        for rec in self._wal.replay():
            payload = rec.payload
            self._apply(
                np.asarray(payload["ids"], np.int64),
                np.asarray(payload["deltas"], np.float32),
            )
            self._push_seq = rec.end_step
            n += 1
        return n

    def _apply(self, global_ids: np.ndarray, deltas: np.ndarray) -> None:
        import jax.numpy as jnp

        local = self.partitioner.to_local(self.shard_id, global_ids)
        # Pad to a pow2 bucket BEFORE the scatter: the per-round unique
        # -id count varies, and jax compiles one scatter kernel per
        # shape — unquantised, every push is a fresh ~100 ms XLA
        # compile (measured: 500 ms/round at 4 shards) instead of a
        # ~1 ms apply.  Padding lanes carry id −1, which store.push
        # routes to the out-of-range sentinel and drops.
        n = len(local)
        bucket = 1 << max(0, int(n - 1).bit_length())
        if bucket > n:
            local = np.concatenate(
                [local, np.full(bucket - n, -1, np.int64)]
            )
            deltas = np.concatenate(
                [deltas, np.zeros((bucket - n,) + deltas.shape[1:],
                                  deltas.dtype)]
            )
        self.store = self.store.push(
            jnp.asarray(local, jnp.int32), jnp.asarray(deltas)
        )
        self._host_mirror = None  # mirror is stale past this point
        self.pushes_applied += 1

    # -- the shard protocol ------------------------------------------------
    def pull(self, global_ids: np.ndarray) -> np.ndarray:
        with self._lock:
            if self.store is None:
                raise ShardCrashed(f"shard {self.shard_id} has no live slice")
            local = self.partitioner.to_local(self.shard_id, global_ids)
            if self._host_mirror is None:
                self._host_mirror = np.asarray(self.store.values())
            vals = self._host_mirror[local]
            self.pulls_served += 1
            if self._c_pulls is not None:
                self._c_pulls.inc()
            return vals

    def push(self, global_ids: np.ndarray, deltas: np.ndarray) -> int:
        """WRITE-AHEAD then apply; returns the shard's push sequence
        number after this push."""
        with self._lock:
            if self.store is None:
                raise ShardCrashed(f"shard {self.shard_id} has no live slice")
            # route check first: a mis-routed id must fail the request
            # BEFORE it is logged (replaying a bad frame would re-raise
            # forever)
            self.partitioner.to_local(self.shard_id, global_ids)
            if self._wal is not None:
                self._wal.append(
                    self._push_seq, 1,
                    {
                        "ids": np.asarray(global_ids, np.int64),
                        "deltas": np.asarray(deltas, np.float32),
                    },
                )
            self._push_seq += 1
            self._apply(global_ids, deltas)
            if self._c_pushes is not None:
                self._c_pushes.inc()
            return self._push_seq

    def flush(self) -> dict:
        """Make the log durable (fsync) and ack the counters — the wire
        protocol's explicit durability point."""
        with self._lock:
            wal_records = 0
            if self._wal is not None:
                self._wal.sync()
                wal_records = self._wal.records_appended
            return {
                "pushes": self.pushes_applied,
                "wal_records": wal_records,
            }

    def values(self) -> np.ndarray:
        """The local slice, rows ordered by :attr:`owned` (ascending
        global id) — the shard's contribution to a model dump."""
        with self._lock:
            if self.store is None:
                raise ShardCrashed(f"shard {self.shard_id} has no live slice")
            return np.asarray(self.store.values())

    # -- failure / recovery -------------------------------------------------
    def crash(self) -> None:
        """Chaos hook: drop the in-memory slice (the WAL survives — it
        is the durable part).  Every subsequent request raises
        :class:`ShardCrashed` until :meth:`restart`."""
        with self._lock:
            self.store = None
            self._host_mirror = None

    def restart(self) -> int:
        """Rebuild init + replay the WAL; returns records replayed."""
        with self._lock:
            self._push_seq = 0
            self.pushes_applied = 0
            self._build()
            replayed = self._replay() if self._wal is not None else 0
            self.restarts += 1
            if self._c_restarts is not None:
                self._c_restarts.inc()
            return replayed

    def stats(self) -> dict:
        with self._lock:
            return {
                "shard": self.shard_id,
                "rows": int(len(self.owned)),
                "pulls": self.pulls_served,
                "pushes": self.pushes_applied,
                "push_seq": self._push_seq,
                "restarts": self.restarts,
                "alive": self.store is not None,
            }

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()


class ShardServer(LineServer):
    """TCP front end + restart supervisor for one :class:`ParamShard`.

    The supervisor loop is the shard-side analogue of
    :class:`~..resilience.recovery.RecoveringDriver`: a request that
    finds the slice dead triggers backoff (capped exponential, jittered
    per :class:`~..resilience.recovery.RestartPolicy`) + rebuild-and-
    replay, then the request is served from the recovered slice — the
    CLIENT never sees the crash, only latency.  ``supervised=False``
    turns the same condition into an ``err crashed`` response (the
    client-visible failure mode).
    """

    def __init__(
        self,
        shard: ParamShard,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        supervised: bool = True,
        restart_policy=None,
        max_line_bytes: int = 64 << 20,
    ):
        super().__init__(
            host, port, name=f"shard-{shard.shard_id}",
            max_line_bytes=max_line_bytes,
        )
        self.shard = shard
        self.supervised = supervised
        if restart_policy is None:
            from ..resilience.recovery import RestartPolicy

            # tight backoff: a shard restart is rebuild+replay, not a
            # process respawn; tests and thread-backed clusters should
            # not serialize on seconds of sleep
            restart_policy = RestartPolicy(
                max_restarts=3, backoff_base_s=0.01, backoff_cap_s=0.5,
                seed=shard.shard_id,
            )
        self.policy = restart_policy
        self._rng = np.random.default_rng(self.policy.seed)

    # -- the protocol ------------------------------------------------------
    def respond(self, line: str) -> str:
        self.shard._active_requests += 1
        try:
            return self._respond_supervised(line)
        finally:
            self.shard._active_requests -= 1

    def _respond_supervised(self, line: str) -> str:
        attempt = 0
        while True:
            try:
                return self._dispatch(line)
            except ShardCrashed:
                if not self.supervised:
                    return "err crashed"
                attempt += 1
                if attempt > self.policy.max_restarts:
                    return "err crashed: restart budget exhausted"
                time.sleep(self.policy.backoff_s(attempt, self._rng))
                self.shard.restart()
            except (ValueError, KeyError) as e:
                return f"err bad-request: {e}"
            except Exception as e:  # noqa: BLE001 — protocol boundary
                return f"err internal: {type(e).__name__}: {e}"

    def _dispatch(self, line: str) -> str:
        parts = line.split(None, 2)
        cmd = parts[0].lower()
        if cmd == "pull":
            if len(parts) not in (2, 3):
                raise ValueError("usage: pull <id1,id2,...> [text|b64]")
            enc = parts[2].strip().lower() if len(parts) == 3 else "text"
            if enc not in ("text", "b64"):
                raise ValueError(f"pull format {enc!r}: 'text' | 'b64'")
            ids = parse_ids(parts[1])
            vals = self.shard.pull(ids)
            return f"ok n={len(ids)} {format_rows(vals, enc)}"
        if cmd == "push":
            if len(parts) != 3:
                raise ValueError("usage: push <id1,id2,...> <row1;row2;...>")
            ids = parse_ids(parts[1])
            deltas = parse_rows(parts[2], self.shard.value_shape)
            if len(deltas) != len(ids):
                raise ValueError(
                    f"{len(ids)} ids but {len(deltas)} delta rows"
                )
            seq = self.shard.push(ids, deltas)
            return f"ok applied={len(ids)} seq={seq}"
        if cmd == "flush":
            f = self.shard.flush()
            return f"ok pushes={f['pushes']} wal_records={f['wal_records']}"
        if cmd == "stats":
            return "ok " + json.dumps(self.shard.stats())
        raise ValueError(f"unknown command {cmd!r} (pull|push|flush|stats)")


__all__ = [
    "ParamShard",
    "ShardServer",
    "ShardCrashed",
    "format_rows",
    "parse_rows",
    "parse_ids",
]
