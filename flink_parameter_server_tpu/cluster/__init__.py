"""cluster/ — the multi-shard parameter-server runtime.

The source paper's defining topology, made real: several PS shard
processes holding key-partitioned state (:mod:`.shard`), workers
exchanging asynchronous pull/push messages against them over TCP
(:mod:`.client`), deterministic key→shard maps (:mod:`.partition`),
and a bounded-staleness clock spanning BSP → SSP → fully-async
(:mod:`.clock`).  :class:`~.driver.ClusterDriver` wires a topology
around any :class:`~..core.batched.BatchedWorkerLogic` and trains the
same jobs the single-process :class:`~..training.driver.StreamingDriver`
runs.  See docs/cluster.md.
"""
from .client import ClusterClient, ShardConnection
from .clock import StalenessClock
from .driver import ClusterConfig, ClusterDriver, ClusterResult
from .partition import (
    ConsistentHashPartitioner,
    Partitioner,
    RangePartitioner,
)
from .procs import RemoteShardStub, ShardProcess, ShardProcSpec
from .shard import (
    FollowerLagging,
    FrozenKeys,
    NotPrimary,
    ParamShard,
    ShardCrashed,
    ShardServer,
    StaleEpoch,
)

__all__ = [
    "ClusterClient",
    "ClusterConfig",
    "ClusterDriver",
    "ClusterResult",
    "ConsistentHashPartitioner",
    "FollowerLagging",
    "FrozenKeys",
    "NotPrimary",
    "ParamShard",
    "Partitioner",
    "RangePartitioner",
    "RemoteShardStub",
    "ShardConnection",
    "ShardProcSpec",
    "ShardProcess",
    "ShardCrashed",
    "ShardServer",
    "StaleEpoch",
    "StalenessClock",
]
