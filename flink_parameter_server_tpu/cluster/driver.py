"""ClusterDriver — N parameter-server shards × M workers, one job.

The multi-process shape of the source paper, finally runnable: shard
processes own key-partitioned state (:class:`~.shard.ParamShard` behind
:class:`~.shard.ShardServer` TCP front ends), workers exchange
asynchronous pull/push traffic against them
(:class:`~.client.ClusterClient`), and a bounded-staleness clock
(:class:`~.clock.StalenessClock`) dials the consistency between BSP
(``staleness_bound=0``), SSP (``k``) and fully async (``None``).

Execution model (per round ``t``, per worker ``w``):

  1. ``clock.wait_for_turn(w)`` — the SSP gate;
  2. mask the global microbatch down to the rows ``w`` owns (rows are
     routed by a stable hash of the ``worker_key`` column, so an
     entity's updates always land on one worker — the reference's
     keyBy-user worker partitioning);
  3. pull the batch's param rows from the shards (coalesced,
     pipelined, shard-parallel);
  4. run the SAME jitted :meth:`~..core.batched.BatchedWorkerLogic.step`
     the single-process driver compiles — worker state (e.g. MF user
     factors) stays worker-local;
  5. push the masked deltas back (aggregated per id);
  6. ``clock.tick(w)``.

With ``staleness_bound=0`` an extra intra-round barrier separates the
pull and push phases, so every round-``t`` read sees exactly the
post-round-``t−1`` table — which is why a bound-0 cluster run lands
allclose-equal (fp32) to :class:`~..training.driver.StreamingDriver`
on the same stream (tests/test_cluster.py BSP parity).  With a bound
``k`` the fast workers run up to ``k`` rounds ahead and the staleness
gauge (``cluster_staleness_steps``) shows the spread live on
``/metrics``.

Everything is thread-backed and sleep-free on the happy path — the
whole topology runs inside one pytest-tier process — but every byte
still crosses a real TCP socket, so the wire protocol, coalescing and
pipelining are exercised for real.

``ClusterConfig(store_backend="mesh")`` swaps the socket topology for
the device-mesh store (meshstore/, docs/meshstore.md): the same round
loop, the same clock and barrier, the same workload contract — but
pulls and pushes lower to jitted sharded gather / scatter-add over one
global table instead of TCP frames.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.batched import BatchedWorkerLogic
from ..ops.hashing import fmix32_np
from .client import ClusterClient
from .clock import StalenessClock
from .partition import ConsistentHashPartitioner, Partitioner, RangePartitioner
from .shard import ParamShard, ShardServer


@dataclasses.dataclass
class ClusterConfig:
    """Topology + consistency knobs for a cluster run."""

    num_shards: int = 2
    num_workers: int = 1
    # which store fronts the table (docs/meshstore.md): "socket" = N
    # ParamShard slices behind TCP servers (every knob below applies);
    # "mesh" = ONE mesh-sharded device array (meshstore/), pull/push
    # lowered to jitted gather/scatter-add — the wire knobs (window,
    # chunk, wire_format, wire_proto, spawn_grace_s, host, timeouts)
    # are then inert, and num_shards becomes layout arithmetic (the
    # block-aligned range partition) rather than a server count;
    # "tiered" = the socket topology with each shard's slice on the
    # two-tier hot/cold store (tierstore/, docs/tierstore.md) — hot
    # rows dense, cold mutated rows in an mmap slab, absent rows
    # recomputed from the deterministic init, RSS bounded by
    # tier_hot_rows instead of the table size
    store_backend: str = "socket"
    # tiered-store knobs (read only when store_backend="tiered"):
    # hot-tier capacity per shard in rows; the slab scratch dir (None
    # = the platform tmpdir — the slab is a cache, never a durability
    # plane, so it does NOT belong beside the WAL); the sketch decay
    # window in observed ids (0 derives 8 × tier_hot_rows)
    tier_hot_rows: int = 65536
    tier_slab_dir: Optional[str] = None
    tier_decay_window: int = 0
    # 0 = BSP (parity with the single-process driver), k > 0 = SSP,
    # None = fully asynchronous (never block)
    staleness_bound: Optional[int] = 0
    partition: str = "range"  # "range" | "hash" (see cluster/partition.py)
    # which batch column routes rows to workers (entity affinity: one
    # entity's updates always land on one worker)
    worker_key: str = "user"
    # client knobs: pipelining window (outstanding frames per shard
    # connection), ids per frame, payload encoding (shard.py: "b64"
    # exact+fast, "text" exact+debuggable, "bf16" half-bytes +
    # error-feedback residuals, "q8" per-row-scaled int8 deltas +
    # residuals — compression/, docs/compression.md).  BSP carve-out:
    # bound-0 WORKER clients always get exact fp32 regardless (a
    # quantized write would break read-your-last-round bitwise parity;
    # enforced in _make_client, the same discipline as hot_cache).
    window: int = 8
    chunk: int = 512
    wire_format: str = "b64"
    # push semantics of the workload's deltas (docs/workloads.md):
    # "delta" = fp32 gradient-style deltas (the default — quantized
    # encodings apply when configured); "increment" = integer counter
    # increments (streaming sketches), where a quantized write would
    # break integer-exact counts, so q8/bf16 downgrade to exact fp32
    # in _make_client — the same enforcement point as the BSP
    # carve-out.  Integer increments are exact in fp32 up to 2^24.
    push_semantics: str = "delta"
    # the registered workload driving this topology (workloads/
    # registry.py); set by the workload runtime so per-workload rates
    # (workload_updates_total{workload=}) land on /metrics and the
    # psctl `workloads` table
    workload: Optional[str] = None
    # two-level aggregation tree (compression/aggregator.py): workers
    # rendezvous per round and a combiner issues ONE merged push per
    # shard (its own client, its own pid space — the exactly-once
    # ledger balances on the uplink).  Trades per-round lockstep on
    # the PUSH side for a num_workers× cut in push frames.
    push_aggregate: bool = False
    # transport framing (utils/frames.py, docs/cluster.md "Binary
    # framing"): "auto" negotiates the length-prefixed binary frame
    # per connection (one hello round trip; old servers answer err
    # bad-request and the connection stays on the line protocol);
    # "line" never negotiates — the pre-binary client, byte for byte;
    # "shm" additionally attempts the shared-memory ring transport
    # (shmem/, docs/shmem.md) against co-located shards, falling back
    # per connection to binary TCP (then lines) for non-local peers,
    # old servers, or a proxied path
    wire_proto: str = "auto"
    # shard worker PROCESSES (cluster/procs.py): each shard server in
    # its own spawned process — its own GIL — with the numpy store
    # backend.  Base ClusterDriver topologies only (the elastic /
    # replication control planes drive in-process shard handles).
    shard_procs: bool = False
    # deterministic picklable init for proc shards ({"kind": ...},
    # procs.resolve_init); ignored by the in-process path, which takes
    # init_fn callables directly
    proc_init: Optional[dict] = None
    # how long a client retries a REFUSED dial before treating it as a
    # conn-class failure: a freshly (re)spawned shard process races
    # its bind against the first dial (procs.py; the _await_retry
    # interaction fix — dial retries here never spend retry budget)
    spawn_grace_s: float = 3.0
    # per-shard WALs under <wal_dir>/shard-<i>; None = no durability
    wal_dir: Optional[str] = None
    supervised: bool = True  # ShardServer restart supervision
    host: str = "127.0.0.1"
    request_timeout: float = 30.0
    # dial deadline, separate from the read deadline above: failure
    # detection (elastic replacement, replica failover) must not sit
    # behind a 30 s connect to a dead address
    connect_timeout: float = 5.0
    # distributed tracing (telemetry/distributed.py): one SpanTracer
    # ring per shard server + one for the clients, pull/push frames
    # stamped with t=<trace>:<span> tokens; collect the rings with
    # driver.trace_rings() and merge via TraceCollector
    trace: bool = False
    # hot-key analytics (telemetry/hotkeys.py): per-shard count-min +
    # space-saving sketches over pull/push key traffic, merged across
    # shards on /metrics and in run_report
    hot_keys: bool = False
    hot_key_k: int = 32
    # hot-key lease cache (hotcache/, docs/hotcache.md): per-worker
    # client-edge caches whose lease grants the live sketches drive
    # (hot_cache=True implies hot_keys).  BSP carve-out: bound-0
    # worker clients NEVER get a cache — reads must see every
    # previous-round write, and the driver enforces it here rather
    # than trusting each call site.
    hot_cache: bool = False
    hot_cache_capacity: int = 1024
    # max cached-entry age in ticks (1 tick = 1 pull_batch = 1 worker
    # round); None derives it: the SSP staleness bound, or 8 for async
    hot_cache_bound: Optional[int] = None
    hot_cache_top_n: int = 32
    hot_cache_lease_ttl: int = 16
    # latency-budget profiler (telemetry/profiler.py): per-phase cost
    # attribution on every pull/push round (client serialize → wire →
    # queue wait → WAL → scatter → serialize → parse).  On by default —
    # measured within the ≤3% telemetry overhead bar; False switches
    # every phase timer to the shared no-op.
    profile: bool = True
    # straggler-adaptive runtime (adaptive/, docs/adaptive.md) — the
    # kill switch.  When True the driver builds an AdaptiveClock
    # (per-worker staleness allowances, widened for flagged stragglers
    # up to adaptive_bound_ceiling and never below staleness_bound)
    # and honors self.work_router in _worker_mask; elastic drivers
    # additionally attach a PushHedger to worker clients when
    # adaptive_push_hedge_after_s is set.  False = stock StalenessClock
    # and identity routing — byte-for-byte the non-adaptive driver.
    adaptive: bool = False
    # hard cap on any worker's widened allowance; None = 2*bound + 1
    # (one full extra SSP window), see adaptive/bounds.py
    adaptive_bound_ceiling: Optional[int] = None
    # push-hedge deferral (seconds); None = push hedging off.  Only
    # effective on membership-backed clients (pid-carrying pushes).
    adaptive_push_hedge_after_s: Optional[float] = None


@dataclasses.dataclass
class ClusterResult:
    """What a cluster run hands back (the TransformResult analogue)."""

    values: np.ndarray  # final global table, assembled from the shards
    worker_outputs: List[Any]
    worker_states: List[Any]
    rounds: int
    events: int
    wall_s: float
    clock: Dict[str, Any]
    shard_stats: List[dict]

    @property
    def updates_per_sec(self) -> float:
        return self.events / self.wall_s if self.wall_s > 0 else 0.0


class ClusterDriver:
    """Own the topology: build it, run a job through it, tear it down.

    ``logic`` is any :class:`~..core.batched.BatchedWorkerLogic` —
    the same object the single-process :class:`StreamingDriver` runs;
    ``capacity``/``value_shape``/``init_fn`` describe the global table
    exactly as :meth:`ShardedParamStore.create` would (deterministic
    per-id init is what makes shard slices equal the global table's
    rows).
    """

    def __init__(
        self,
        logic: BatchedWorkerLogic,
        *,
        capacity: int,
        value_shape: Sequence[int] = (),
        init_fn=None,
        config: Optional[ClusterConfig] = None,
        partitioner: Optional[Partitioner] = None,
        rng=None,
        registry=None,
    ):
        self.logic = logic
        self.capacity = int(capacity)
        self.value_shape = tuple(int(s) for s in value_shape)
        self.config = config if config is not None else ClusterConfig()
        cfg = self.config
        if cfg.store_backend not in ("socket", "mesh", "tiered"):
            raise ValueError(
                f"store_backend={cfg.store_backend!r}: "
                f"'socket' | 'mesh' | 'tiered'"
            )
        if cfg.store_backend == "tiered" and cfg.shard_procs:
            raise ValueError(
                "store_backend='tiered' with shard_procs=True: shard "
                "worker processes run the jax-free numpy slice "
                "(cluster/procs.py); tiered shards are in-process"
            )
        if cfg.store_backend == "mesh":
            # the mesh backend slots under the BASE driver's contracts
            # only (the same discipline as shard_procs): the elastic /
            # replication control planes re-partition and promote
            # per-shard SERVERS, while a mesh resize is a device-count
            # change — re-laying-out one global array, a different
            # operation parked for the TPU window (docs/meshstore.md)
            if type(self) is not ClusterDriver:
                raise NotImplementedError(
                    f"store_backend='mesh' supports the base "
                    f"ClusterDriver only (got {type(self).__name__}: "
                    f"elastic/replication control planes operate on "
                    f"socket-fronted shard handles; a mesh resize is a "
                    f"device-mesh relayout, parked for the TPU window)"
                )
            if cfg.shard_procs:
                raise ValueError(
                    "store_backend='mesh' with shard_procs=True: the "
                    "mesh table lives in THIS process's devices — "
                    "there is no shard process to spawn"
                )
            if cfg.hot_cache:
                raise ValueError(
                    "store_backend='mesh' with hot_cache=True: mesh "
                    "reads are device-fresh gathers with no wire to "
                    "save — a host-side row cache would only add a "
                    "staleness surface"
                )
            if cfg.partition != "range":
                raise ValueError(
                    f"store_backend='mesh' requires partition='range' "
                    f"(got {cfg.partition!r}): the mesh table is "
                    f"row-block sharded, and only contiguous ranges "
                    f"can align to it (docs/meshstore.md)"
                )
        if partitioner is not None:
            self.partitioner = partitioner
        elif cfg.partition == "range":
            self.partitioner = RangePartitioner(capacity, cfg.num_shards)
        elif cfg.partition == "hash":
            self.partitioner = ConsistentHashPartitioner(
                capacity, cfg.num_shards
            )
        else:
            raise ValueError(
                f"partition={cfg.partition!r}: 'range' | 'hash'"
            )
        self._init_fn = init_fn
        if (
            init_fn is None
            and self.config.proc_init is not None
            and not self.config.shard_procs
        ):
            # one init spec drives BOTH arms: proc children resolve it
            # numpy-side, the in-process path renders the same rows
            # through jax — the proc-vs-thread parity contract
            from .procs import as_jax_init

            self._init_fn = as_jax_init(
                self.config.proc_init, self.value_shape
            )
        self._rng = rng
        if registry is not False:
            from ..telemetry.registry import get_registry

            self.registry = registry if registry is not None else get_registry()
        else:
            self.registry = None
        self.shards: List[ParamShard] = []
        self.servers: List[ShardServer] = []
        self.mesh_store = None  # MeshParamStore when store_backend="mesh"
        self.clock: Optional[StalenessClock] = None
        # adaptive work re-routing (adaptive/rebalance.py): when set
        # (and cfg.adaptive), _worker_mask consults it instead of the
        # static hash route; None = identity (stock routing)
        self.work_router = None
        self._clients: List[ClusterClient] = []
        self._started = False
        self._step_fn = None
        # observability plumbing (both off by default — zero overhead)
        self.client_tracer = None
        self.shard_tracers: List = []
        self._hotkey_labels: List[str] = []
        self._hotcache_labels: List[str] = []
        # hot_cache lease grants are sketch-driven: without the
        # measurement there is nothing to lease
        if self.config.hot_cache:
            self.config.hot_keys = True

    # -- lifecycle ---------------------------------------------------------
    def _wal_dir_for(self, shard_id: int) -> Optional[str]:
        cfg = self.config
        return (
            None if cfg.wal_dir is None
            else f"{cfg.wal_dir}/shard-{shard_id}"
        )

    def _build_shard(
        self, shard_id: int, partitioner: Optional[Partitioner] = None
    ) -> Tuple[ParamShard, ShardServer]:
        """One shard + its TCP front end (the elastic driver reuses
        this for scale-out spin-up and dead-shard replacement)."""
        cfg = self.config
        if cfg.shard_procs:
            # shard worker processes (cluster/procs.py): the GIL
            # escape.  Only the base driver's static topology — the
            # elastic/replication control planes operate on in-process
            # shard handles (freeze/install_epoch/promote are
            # deliberately wire-less, docs/cluster.md).
            if type(self) is not ClusterDriver:
                raise NotImplementedError(
                    f"shard_procs=True supports the base ClusterDriver "
                    f"only (got {type(self).__name__}: the elastic "
                    f"control plane drives in-process shard handles)"
                )
            if self._init_fn is not None and cfg.proc_init is None:
                raise ValueError(
                    "shard_procs=True cannot pickle an arbitrary "
                    "init_fn into the child — describe the init with "
                    "ClusterConfig.proc_init (procs.resolve_init) "
                    "and build the matching in-process init with "
                    "procs.as_jax_init"
                )
            from .procs import (
                RemoteShardStub,
                ShardProcSpec,
                ShardProcess,
            )

            proc = ShardProcess(ShardProcSpec(
                shard_id=shard_id,
                partition=cfg.partition,
                capacity=self.capacity,
                num_shards=cfg.num_shards,
                value_shape=self.value_shape,
                wal_dir=self._wal_dir_for(shard_id),
                init=cfg.proc_init,
                supervised=cfg.supervised,
                host=cfg.host,
            )).wait_ready()
            return RemoteShardStub(proc), proc
        hotkeys = None
        if cfg.hot_keys:
            from ..telemetry.hotkeys import HotKeySketch, get_aggregator

            hotkeys = HotKeySketch(cfg.hot_key_k)
            label = f"shard-{shard_id}"
            # re-registering (shard replacement) starts a fresh window
            get_aggregator().register(label, hotkeys)
            if label not in self._hotkey_labels:
                self._hotkey_labels.append(label)
        tracer = None
        if cfg.trace:
            from ..telemetry.spans import SpanTracer

            tracer = SpanTracer(process=f"shard-{shard_id}")
            self.shard_tracers.append(tracer)
        shard = ParamShard(
            shard_id,
            partitioner if partitioner is not None else self.partitioner,
            self.value_shape,
            init_fn=self._init_fn,
            wal_dir=self._wal_dir_for(shard_id),
            registry=self.registry if self.registry is not None else False,
            hotkeys=hotkeys,
            profiler=None if cfg.profile else False,
            # the "tiered" cluster backend IS the socket topology with
            # tiered slices — elastic scale-out and replacement shards
            # built here inherit the tier automatically
            store_backend=(
                "tiered" if cfg.store_backend == "tiered" else "jax"
            ),
            tier_hot_rows=cfg.tier_hot_rows,
            tier_slab_dir=cfg.tier_slab_dir,
            tier_decay_window=cfg.tier_decay_window,
        )
        server = ShardServer(
            shard, cfg.host, 0, supervised=cfg.supervised, tracer=tracer
        ).start()
        return shard, server

    def _on_servers_started(self) -> None:
        """Hook between shard spin-up and client construction (the
        elastic driver creates its membership service here)."""

    def _make_clock(self) -> StalenessClock:
        """One construction point for the SSP clock so the adaptive
        kill switch swaps in per-worker allowances everywhere (start()
        both topologies + the fresh-clock-per-run() site)."""
        cfg = self.config
        if getattr(cfg, "adaptive", False):
            from ..adaptive.bounds import AdaptiveClock

            bound = cfg.staleness_bound
            ceiling = getattr(cfg, "adaptive_bound_ceiling", None)
            if ceiling is None and bound is not None:
                ceiling = 2 * bound + 1
            return AdaptiveClock(
                cfg.num_workers, bound, bound_ceiling=ceiling
            )
        return StalenessClock(cfg.num_workers, cfg.staleness_bound)

    def _start_mesh(self) -> None:
        """The mesh topology: no servers to bind — align the range
        partition to the device row-blocks, materialise the ONE global
        table, and hand every worker a :class:`~..meshstore.MeshClient`
        over it.  Durability (when configured) journals at
        ``<wal_dir>/mesh``, beside where the socket topology's
        ``shard-<i>`` directories would sit."""
        import jax

        from ..meshstore import MeshClient, MeshParamStore

        cfg = self.config
        self.partitioner = self.partitioner.block_aligned(
            len(jax.devices())
        )
        self.mesh_store = MeshParamStore(
            self.capacity,
            self.value_shape,
            init_fn=self._init_fn,
            partitioner=self.partitioner,
            wal_dir=(
                None if cfg.wal_dir is None else f"{cfg.wal_dir}/mesh"
            ),
            registry=self.registry if self.registry is not None else False,
        )
        if self.registry is not None:
            # a mesh run's table lives in device memory — expose the
            # per-device bytes_in_use/peak probes (training/tracing.py)
            # on the same /metrics surface the meshstore_* gauges use,
            # so an HBM blow-up is visible live, not post-OOM
            from ..training.tracing import register_device_memory_gauges

            register_device_memory_gauges(self.registry)

    def start(self) -> "ClusterDriver":
        if self._started:
            return self
        cfg = self.config
        if cfg.store_backend == "mesh":
            self._start_mesh()
            self._clients = [
                self._make_client(worker=str(w))
                for w in range(cfg.num_workers)
            ]
            self.clock = self._make_clock()
            if self.registry is not None:
                self.registry.gauge(
                    "cluster_staleness_steps", component="cluster",
                    fn=lambda: (
                        self.clock.staleness()
                        if self.clock is not None else None
                    ),
                )
            self._started = True
            return self
        if cfg.trace and self.client_tracer is None:
            from ..telemetry.spans import SpanTracer

            self.client_tracer = SpanTracer(process="client")
        for s in range(cfg.num_shards):
            shard, server = self._build_shard(s)
            self.shards.append(shard)
            self.servers.append(server)
        self._on_servers_started()
        self._clients = [
            self._make_client(worker=str(w))
            for w in range(cfg.num_workers)
        ]
        self.clock = self._make_clock()
        if self.registry is not None:
            self.registry.gauge(
                "cluster_staleness_steps", component="cluster",
                fn=lambda: (
                    self.clock.staleness() if self.clock is not None else None
                ),
            )
        self._started = True
        return self

    def _make_client(self, worker: Optional[str] = None) -> ClusterClient:
        cfg = self.config
        if cfg.store_backend == "mesh":
            # the BSP / increment carve-outs below guard WIRE encodings;
            # the mesh path has no wire — every read and write is exact
            # fp32 on device, so both carve-outs hold vacuously
            from ..meshstore import MeshClient

            return MeshClient(self.mesh_store, worker=worker)
        # BSP carve-out (docs/compression.md): a bound-0 worker's reads
        # must see every previous-round write bitwise, so quantized
        # delta encodings downgrade to exact fp32 here — parity is
        # pinned in tests/test_compression.py, the same enforcement
        # point as the hot-cache bypass below
        wire_format = cfg.wire_format
        if cfg.staleness_bound == 0 and wire_format in ("q8", "bf16"):
            wire_format = "b64"
        # increment-semantics carve-out (docs/workloads.md): sketch
        # pushes are integer bucket increments — quantizing them would
        # deliver within-a-granule counts instead of exact ones, so
        # the q8/bf16 paths are bypassed for every client of an
        # increment workload (integer-exactness is pinned in
        # tests/test_workloads.py)
        if cfg.push_semantics == "increment" and wire_format in (
            "q8", "bf16"
        ):
            wire_format = "b64"
        client = ClusterClient(
            [(srv.host, srv.port) for srv in self.servers],
            self.partitioner,
            self.value_shape,
            window=cfg.window,
            chunk=cfg.chunk,
            timeout=cfg.request_timeout,
            connect_timeout=cfg.connect_timeout,
            wire_format=wire_format,
            wire_proto=cfg.wire_proto,
            spawn_grace_s=(
                cfg.spawn_grace_s if cfg.shard_procs else 0.0
            ),
            registry=self.registry if self.registry is not None else False,
            worker=worker,
            tracer=self.client_tracer,
            profiler=None if cfg.profile else False,
        )
        self._attach_hot_cache(client, worker)
        return client

    def _attach_hot_cache(self, client, worker: Optional[str]) -> None:
        """Attach the hot-key lease cache to a worker client — UNLESS
        the clock is BSP (bound 0): a cached read of any age > 0 would
        miss previous-round writes and break the parity guarantee, so
        bound-0 clients always bypass (the carve-out table in
        docs/hotcache.md)."""
        cfg = self.config
        if not cfg.hot_cache or cfg.staleness_bound == 0:
            return
        from ..hotcache import (
            HotRowCache,
            LeasePolicy,
            register_cache,
        )
        from ..telemetry.hotkeys import get_aggregator

        bound = cfg.hot_cache_bound
        if bound is None:
            bound = (
                cfg.staleness_bound
                if cfg.staleness_bound is not None else 8
            )
        cache = HotRowCache(
            bound,
            capacity=cfg.hot_cache_capacity,
            registry=self.registry if self.registry is not None else False,
            worker=worker,
        )
        client.attach_hotcache(
            cache,
            LeasePolicy(get_aggregator(), top_n=cfg.hot_cache_top_n),
            lease_ttl=cfg.hot_cache_lease_ttl,
        )
        label = f"worker-{worker}" if worker is not None else "client"
        register_cache(label, cache)
        if label not in self._hotcache_labels:
            self._hotcache_labels.append(label)

    def trace_rings(self) -> List:
        """Every per-process span ring this topology records into
        (client first, then shards) — feed them to a
        :class:`~..telemetry.distributed.TraceCollector`."""
        rings = []
        if self.client_tracer is not None:
            rings.append(self.client_tracer)
        rings.extend(self.shard_tracers)
        return rings

    def stop(self) -> None:
        for c in self._clients:
            c.close()
        self._clients = []
        for srv in self.servers:
            srv.stop()
        for shard in self.shards:
            shard.close()
        self.servers = []
        self.shards = []
        if self.mesh_store is not None:
            self.mesh_store.close()
            self.mesh_store = None
        self._started = False
        if self._hotkey_labels:
            from ..telemetry.hotkeys import get_aggregator

            agg = get_aggregator()
            for label in self._hotkey_labels:
                agg.unregister(label)
            self._hotkey_labels = []
        if self._hotcache_labels:
            from ..hotcache import unregister_cache

            for label in self._hotcache_labels:
                unregister_cache(label)
            self._hotcache_labels = []

    def __enter__(self) -> "ClusterDriver":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the job ------------------------------------------------------------
    def _worker_mask(
        self, batch: dict, worker: int, round_idx: int = 0
    ) -> np.ndarray:
        cfg = self.config
        base = np.asarray(
            batch.get("mask", np.ones(self._batch_len(batch), bool))
        ).astype(bool)
        if cfg.num_workers == 1:
            return base
        if cfg.worker_key not in batch:
            raise ValueError(
                f"num_workers={cfg.num_workers} needs batch column "
                f"{cfg.worker_key!r} to route rows (set "
                f"ClusterConfig.worker_key)"
            )
        keys = np.asarray(batch[cfg.worker_key], np.int64)
        router = self.work_router
        if router is not None and getattr(cfg, "adaptive", False):
            # adaptive re-routing (adaptive/rebalance.py): ownership is
            # a pure function of (key, round) and every worker asks
            # about the same round, so exactly-once per row per round
            # is preserved even while groups migrate
            return base & router.owner_mask(keys, worker, round_idx)
        owner = fmix32_np(keys) % np.uint32(cfg.num_workers)
        return base & (owner == np.uint32(worker))

    @staticmethod
    def _batch_len(batch: dict) -> int:
        return len(next(iter(batch.values())))

    def run(
        self,
        batches,
        *,
        collect_outputs: bool = False,
        round_hook: Optional[Callable[[int, int], None]] = None,
        timeout: float = 300.0,
        deadline_s: Optional[float] = None,
    ) -> ClusterResult:
        """Train over ``batches`` (a finite iterable of microbatch
        dicts); every worker walks the full sequence with its ownership
        mask applied.  ``round_hook(worker, round)`` fires at each round
        start on the worker's thread — the straggler-injection point
        the SSP tests use.  ``deadline_s`` turns the run time-bounded:
        each worker stops at the first round boundary past the
        deadline (goodput benchmarking — under a fixed wall budget the
        work completed IS the metric, whereas on a fixed workload the
        wall clock is floored by the straggler in every arm).  Returns
        the assembled final table."""
        import jax

        if not self._started:
            self.start()
        cfg = self.config
        batches = list(batches)
        if self._step_fn is None:
            self._step_fn = jax.jit(self.logic.step)
        rng = (
            self._rng if self._rng is not None else jax.random.PRNGKey(0)
        )
        # fresh clock per run: the previous run's workers deactivated
        # themselves at stream end (frozen counters must not gate a new
        # job); the staleness gauge reads self.clock so it follows
        clock = self.clock = self._make_clock()
        # bound-0 intra-round barrier: reads of round t must not see
        # round-t writes (see module docstring)
        pull_barrier = (
            threading.Barrier(cfg.num_workers)
            if cfg.staleness_bound == 0 and cfg.num_workers > 1
            else None
        )
        # aggregation tree (compression/aggregator.py): one combiner
        # uplink per run, workers rendezvous per round and the shards
        # see ONE merged push — fresh per run (a broken barrier must
        # not leak into the next job)
        if deadline_s is not None and cfg.push_aggregate:
            raise ValueError(
                "deadline_s is incompatible with push_aggregate: a "
                "deadline-stopped worker would strand its siblings at "
                "the push rendezvous"
            )
        deadline_t = (
            time.perf_counter() + float(deadline_s)
            if deadline_s is not None else None
        )

        def past_deadline() -> bool:
            return (
                deadline_t is not None
                and time.perf_counter() >= deadline_t
            )

        push_agg = None
        if cfg.push_aggregate and cfg.num_workers > 1:
            from ..compression.aggregator import PushAggregator

            push_agg = PushAggregator(
                cfg.num_workers,
                self._make_client(worker="combiner"),
                registry=self.registry,
                timeout=timeout,
            )
        # exposed for post-run ledger audits (rows the uplink acked)
        self.last_push_aggregator = push_agg
        errors: List[BaseException] = []
        states: List[Any] = [None] * cfg.num_workers
        outputs: List[List[Any]] = [[] for _ in range(cfg.num_workers)]
        events = [0] * cfg.num_workers
        c_rounds = (
            self.registry.counter(
                "cluster_worker_rounds_total", component="cluster"
            )
            if self.registry is not None
            else None
        )
        # per-workload rate instrument (workloads/, docs/workloads.md):
        # the `workloads` telemetry path and psctl table read this
        c_updates = (
            self.registry.counter(
                "workload_updates_total", component="workloads",
                workload=cfg.workload,
            )
            if self.registry is not None and cfg.workload is not None
            else None
        )

        def worker_loop(w: int) -> None:
            import jax.numpy as jnp

            client = self._clients[w]
            state = self.logic.init_state(rng)
            try:
                for t, batch in enumerate(batches):
                    if errors:
                        break
                    if past_deadline():
                        # round-boundary stop: this worker's completed
                        # rounds stay counted, the aborted barrier
                        # releases any bound-0 sibling mid-round
                        if pull_barrier is not None:
                            pull_barrier.abort()
                        break
                    if round_hook is not None:
                        round_hook(w, t)
                    if not clock.wait_for_turn(w, timeout=timeout):
                        raise TimeoutError(
                            f"worker {w} starved at round {t} "
                            f"(bound={cfg.staleness_bound})"
                        )
                    wb = dict(batch)
                    wb["mask"] = self._worker_mask(batch, w, t)
                    ids = np.asarray(self.logic.keys(wb))
                    # multi-key workloads (PA's sparse (B, K) feature
                    # ids, a sketch's (B, depth) cells) pull several
                    # params per record: broadcast the per-record row
                    # mask over the trailing key lanes so coalescing
                    # sees one mask lane per key
                    kmask = np.asarray(wb["mask"])
                    if ids.ndim > kmask.ndim:
                        kmask = np.broadcast_to(
                            kmask.reshape(
                                kmask.shape
                                + (1,) * (ids.ndim - kmask.ndim)
                            ),
                            ids.shape,
                        )
                    if kmask.any():
                        pulled = client.pull_batch(ids, mask=kmask)
                    else:
                        # a fully masked round owns no rows — e.g. a
                        # drained straggler after adaptive re-routing
                        # (adaptive/rebalance.py) — and must cost no
                        # wire: coalesce_ids would otherwise pull one
                        # fill id.  Masked lanes are padding by the
                        # store contract, so zeros feed the step.
                        pulled = np.zeros(
                            ids.shape + tuple(self.value_shape),
                            np.float32,
                        )
                    if pull_barrier is not None:
                        try:
                            pull_barrier.wait(timeout=timeout)
                        except threading.BrokenBarrierError:
                            if past_deadline():
                                break  # a sibling deadline-stopped
                            raise
                    state, req, out = self._step_fn(
                        state, wb, jnp.asarray(pulled)
                    )
                    req_mask = (
                        None if req.mask is None else np.asarray(req.mask)
                    )
                    if push_agg is not None:
                        push_agg.push_batch(
                            w, np.asarray(req.ids),
                            np.asarray(req.deltas), req_mask,
                        )
                    else:
                        client.push_batch(
                            np.asarray(req.ids), np.asarray(req.deltas),
                            req_mask,
                        )
                    clock.tick(w)
                    events[w] += int(wb["mask"].sum())
                    if c_rounds is not None:
                        c_rounds.inc()
                    if c_updates is not None:
                        c_updates.inc(int(wb["mask"].sum()))
                    if collect_outputs:
                        outputs[w].append(jax.tree.map(np.asarray, out))
                states[w] = state
            except BaseException as e:  # noqa: BLE001 — joined below
                errors.append(e)
                if pull_barrier is not None:
                    pull_barrier.abort()
                if push_agg is not None:
                    # siblings parked at the push rendezvous must get
                    # BrokenBarrierError, not a hang
                    push_agg.abort()
            finally:
                clock.deactivate(w)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(
                target=worker_loop, args=(w,), name=f"cluster-worker-{w}",
                daemon=True,
            )
            for w in range(cfg.num_workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout)
        wall = time.perf_counter() - t0
        if push_agg is not None:
            push_agg.close()
        if errors:
            raise errors[0]
        return ClusterResult(
            values=self.final_values(),
            worker_outputs=(
                [o for outs in outputs for o in outs]
                if collect_outputs else []
            ),
            worker_states=states,
            rounds=len(batches),
            events=int(sum(events)),
            wall_s=wall,
            clock=clock.snapshot(),
            shard_stats=(
                [self.mesh_store.stats()]
                if self.mesh_store is not None
                else [s.stats() for s in self.shards]
            ),
        )

    def final_values(self) -> np.ndarray:
        """Assemble the global table from the shards (through the wire
        — the dump is itself a protocol exercise), rows in global-id
        order: the cluster analogue of
        :meth:`~..core.store.ShardedParamStore.values`."""
        client = self._clients[0] if self._clients else self._make_client()
        try:
            if client.hotcache is not None:
                # the dump is the table of record: drop any cached rows
                # so every id is read fresh from its shard (leases are
                # re-granted in passing, which is harmless)
                client.hotcache.clear()
            # np.asarray: the mesh client returns the device array
            return np.asarray(client.pull_batch(
                np.arange(self.capacity, dtype=np.int64)
            ))
        finally:
            if not self._clients:
                client.close()


__all__ = ["ClusterConfig", "ClusterDriver", "ClusterResult"]
