"""ClusterClient — the worker side of the multi-shard runtime.

Implements the :class:`~..core.api.ParameterServerClient` ABC against
real shard sockets, plus the batch surface the compiled path uses.
Three bandwidth levers from the reference's sender stack
(SURVEY.md §2 #6), rebuilt for the wire:

  * **request coalescing** — duplicate ids inside one microbatch
    collapse to one pull per id (:func:`~..ops.dedup.coalesce_ids`);
    a Zipf-hot item appearing 300× per batch costs one line, and the
    answer scatters back to every lane via the inverse map;
  * **delta aggregation** — duplicate-id push deltas are summed before
    the bytes move (:func:`~..ops.dedup.aggregate_deltas`) — exactly
    the store's intra-batch combine semantics, applied at the sender;
  * **pipelined pulls with an in-flight window** — each shard
    connection carries up to ``window`` outstanding request frames
    (responses come back in order, the line-protocol contract), so the
    client overlaps shard round trips instead of paying RTT per chunk.
    The live window usage is the ``inflight_pulls`` gauge
    (``component=cluster``) — the same observability the event API's
    pull limiter got (:func:`~..core.api.add_pull_limiter`).

Shards are contacted concurrently (one lightweight thread per shard
per batch call): a pull's wall time is the SLOWEST shard's round trip,
not the sum — which is what makes the 1→2→4-shard scaling benchmark
(``benchmarks/cluster_scaling.py``) a real scaling measurement.

Pull RTT lands in a ``cluster_pull_rtt_seconds`` histogram per client
(p99 is the benchmark's tail-latency column).
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.api import ParameterServerClient
from ..ops.dedup import aggregate_deltas, coalesce_ids
from .partition import Partitioner
from .shard import format_rows, parse_rows


class ShardConnection:
    """One pipelined line-protocol connection to one shard.

    ``request_many`` keeps up to ``window`` frames outstanding; the
    shard answers in order, so responses re-associate positionally.
    Not thread-safe — each worker owns its connections (the driver
    builds one client per worker).
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        window: int = 8,
        timeout: float = 30.0,
        connect_timeout: float = 10.0,
    ):
        if window < 1:
            raise ValueError(f"window={window}: must be >= 1")
        self.host, self.port = host, port
        self.window = int(window)
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout
        )
        self._sock.settimeout(timeout)
        try:
            # pipelined request frames must leave NOW, not after Nagle
            # pairs them with a delayed ACK (~40 ms/frame otherwise)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._rfile = self._sock.makefile("rb")
        self.inflight = 0
        self.requests_sent = 0

    def request_many(self, lines: Sequence[str]) -> List[str]:
        """Pipelined request/response: send up to ``window`` frames
        ahead of the reads, return one response line per request."""
        out: List[str] = []
        pending = 0
        it = iter(lines)
        sent = 0
        total = len(lines)
        while sent < total or pending:
            while pending < self.window and sent < total:
                line = next(it)
                self._sock.sendall(line.encode("utf-8") + b"\n")
                pending += 1
                sent += 1
                self.inflight = pending
                self.requests_sent += 1
            raw = self._rfile.readline()
            if not raw:
                raise ConnectionError(
                    f"shard {self.host}:{self.port} closed mid-pipeline "
                    f"({len(out)}/{total} responses)"
                )
            out.append(raw.decode("utf-8", "replace").rstrip("\n"))
            pending -= 1
            self.inflight = pending
        return out

    def request(self, line: str) -> str:
        return self.request_many([line])[0]

    def close(self) -> None:
        try:
            self._rfile.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def _check_ok(resp: str, what: str) -> str:
    if not resp.startswith("ok"):
        raise RuntimeError(f"{what} failed: {resp}")
    return resp


class ClusterClient(ParameterServerClient):
    """Worker-side handle over every shard.

    Batch surface (the compiled path): :meth:`pull_batch` /
    :meth:`push_batch` — coalesced, pipelined, shard-parallel.
    Event surface (the ABC): :meth:`pull` buffers the id, :meth:`push`
    buffers the delta; :meth:`drain` flushes both coalesced and
    delivers pull answers to a callback — the combination-sender
    semantics per worker.
    """

    def __init__(
        self,
        addresses: Sequence[Tuple[str, int]],
        partitioner: Partitioner,
        value_shape: Sequence[int] = (),
        *,
        window: int = 8,
        chunk: int = 512,
        timeout: float = 30.0,
        wire_format: str = "b64",
        registry=None,
        worker: Optional[str] = None,
    ):
        if len(addresses) != partitioner.num_shards:
            raise ValueError(
                f"{len(addresses)} shard addresses for a "
                f"{partitioner.num_shards}-shard partitioner"
            )
        if chunk < 1:
            raise ValueError(f"chunk={chunk}: must be >= 1")
        if wire_format not in ("text", "b64"):
            raise ValueError(f"wire_format={wire_format!r}: 'text' | 'b64'")
        self.partitioner = partitioner
        self.value_shape = tuple(int(s) for s in value_shape)
        self.chunk = int(chunk)
        # b64 (default): exact fp32 bytes, ~100x cheaper than per-float
        # text (shard.py module docstring); "text" for debuggability
        self.wire_format = wire_format
        self._conns = [
            ShardConnection(h, p, window=window, timeout=timeout)
            for h, p in addresses
        ]
        self.outputs: List[object] = []
        self._pending_pulls: List[int] = []
        self._pending_pushes: List[Tuple[int, np.ndarray]] = []
        self.pulls_coalesced = 0  # duplicate lanes saved from the wire
        self.pushes_coalesced = 0
        # unified plane (component=cluster): the pull RTT histogram and
        # the live in-flight window gauge
        if registry is not False:
            from ..telemetry.registry import get_registry

            reg = registry if registry is not None else get_registry()
            labels = {"worker": worker} if worker is not None else {}
            self._h_rtt = reg.histogram(
                "cluster_pull_rtt_seconds", component="cluster", **labels
            )
            reg.gauge(
                "inflight_pulls", component="cluster", fn=self.inflight,
                **labels,
            )
        else:
            self._h_rtt = None

    # -- observability ------------------------------------------------------
    def inflight(self) -> int:
        """Outstanding pull/push frames across every shard connection —
        the live pipelining depth (<= window × shards)."""
        return sum(c.inflight for c in self._conns)

    # -- the batch surface --------------------------------------------------
    def pull_batch(
        self, ids, mask=None, *, dtype=np.float32
    ) -> np.ndarray:
        """Pull values for ``ids`` (any shape); returns
        ``ids.shape + value_shape`` float32.  Duplicate ids cost one
        wire request; per-shard traffic runs concurrently."""
        ids_arr = np.asarray(ids)
        unique, inverse = coalesce_ids(ids_arr, mask)
        self.pulls_coalesced += int(ids_arr.size - unique.size)
        by_shard = self._split(unique)
        results: Dict[int, np.ndarray] = {}
        self._for_each_shard(
            by_shard,
            lambda s, sids: results.__setitem__(s, self._pull_shard(s, sids)),
        )
        width = int(np.prod(self.value_shape)) if self.value_shape else 1
        flat = np.empty((unique.size, width), dtype)
        for s, sids in by_shard.items():
            pos = np.searchsorted(unique, sids)
            flat[pos] = results[s].reshape(len(sids), width)
        out = flat.reshape(unique.shape + self.value_shape)
        return out[inverse]

    def push_batch(self, ids, deltas, mask=None) -> int:
        """Aggregate duplicate-id deltas, push each shard's share (in
        parallel, pipelined); returns unique ids pushed."""
        ids_arr = np.asarray(ids)
        unique, summed = aggregate_deltas(ids_arr, np.asarray(deltas), mask)
        if unique.size == 0:
            return 0
        self.pushes_coalesced += int(
            (ids_arr.size if mask is None else int(np.asarray(mask).sum()))
            - unique.size
        )
        by_shard = self._split(unique)
        self._for_each_shard(
            by_shard,
            lambda s, sids: self._push_shard(
                s, sids, summed[np.searchsorted(unique, sids)]
            ),
        )
        return int(unique.size)

    def flush(self) -> List[str]:
        """FLUSH every shard (WAL fsync + ack) — the explicit durability
        barrier a bound-0 round ends with when durability matters."""
        return [
            _check_ok(c.request("flush"), f"flush shard {s}")
            for s, c in enumerate(self._conns)
        ]

    def shard_stats(self) -> List[dict]:
        import json

        out = []
        for s, c in enumerate(self._conns):
            resp = _check_ok(c.request("stats"), f"stats shard {s}")
            out.append(json.loads(resp[3:]))
        return out

    # -- the event-API surface (ParameterServerClient) ----------------------
    def pull(self, param_id: int) -> None:
        """Buffer a pull; answers arrive at the next :meth:`drain` —
        the asynchronous contract of the ABC, with the microbatch as
        the combination buffer."""
        self._pending_pulls.append(int(param_id))

    def push(self, param_id: int, delta) -> None:
        self._pending_pushes.append((int(param_id), np.asarray(delta)))

    def output(self, w_out) -> None:
        self.outputs.append(w_out)

    def drain(self, on_pull_recv=None) -> int:
        """Flush buffered pushes (aggregated) and answer buffered pulls
        (coalesced); ``on_pull_recv(param_id, value, client)`` is
        invoked once per buffered pull, in buffering order.  Returns
        the number of answers delivered."""
        if self._pending_pushes:
            ids = np.asarray([i for i, _ in self._pending_pushes], np.int64)
            deltas = np.stack([d for _, d in self._pending_pushes])
            self._pending_pushes = []
            self.push_batch(ids, deltas)
        n = 0
        if self._pending_pulls:
            ids = np.asarray(self._pending_pulls, np.int64)
            self._pending_pulls = []
            values = self.pull_batch(ids)
            for i, pid in enumerate(ids):
                if on_pull_recv is not None:
                    on_pull_recv(int(pid), values[i], self)
                n += 1
        return n

    def close(self) -> None:
        for c in self._conns:
            c.close()

    # -- internals ----------------------------------------------------------
    def _split(self, unique_ids: np.ndarray) -> Dict[int, np.ndarray]:
        shards = self.partitioner.shard_of(unique_ids)
        return {
            int(s): unique_ids[shards == s] for s in np.unique(shards)
        }

    def _for_each_shard(self, by_shard: Dict[int, np.ndarray], fn) -> None:
        """Run ``fn(shard, ids)`` for every shard concurrently (one
        thread per contacted shard; errors propagate to the caller)."""
        items = list(by_shard.items())
        if len(items) == 1:
            fn(*items[0])
            return
        errors: List[BaseException] = []

        def run(s, sids):
            try:
                fn(s, sids)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(s, sids), daemon=True)
            for s, sids in items
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    def _pull_shard(self, shard: int, ids: np.ndarray) -> np.ndarray:
        conn = self._conns[shard]
        chunks = [
            ids[i: i + self.chunk] for i in range(0, len(ids), self.chunk)
        ]
        lines = [
            "pull " + ",".join(str(int(i)) for i in c)
            + (" b64" if self.wire_format == "b64" else "")
            for c in chunks
        ]
        t0 = time.perf_counter()
        resps = conn.request_many(lines)
        if self._h_rtt is not None:
            # one observation per chunk frame: the pipelined per-frame
            # turnaround, amortised (total wall / frames)
            per = (time.perf_counter() - t0) / max(1, len(lines))
            for _ in lines:
                self._h_rtt.observe(per)
        rows = []
        for resp, c in zip(resps, chunks):
            _check_ok(resp, f"pull shard {shard}")
            _, _, body = resp.partition(" ")
            _, _, body = body.partition(" ")  # strip "n=<k>"
            vals = parse_rows(body, self.value_shape)
            if len(vals) != len(c):
                raise RuntimeError(
                    f"shard {shard} answered {len(vals)} rows for "
                    f"{len(c)} ids"
                )
            rows.append(vals)
        return np.concatenate(rows) if rows else np.empty(
            (0,) + self.value_shape, np.float32
        )

    def _push_shard(
        self, shard: int, ids: np.ndarray, deltas: np.ndarray
    ) -> None:
        conn = self._conns[shard]
        lines = []
        for i in range(0, len(ids), self.chunk):
            c_ids = ids[i: i + self.chunk]
            c_del = deltas[i: i + self.chunk]
            lines.append(
                "push "
                + ",".join(str(int(x)) for x in c_ids)
                + " "
                + format_rows(c_del, self.wire_format)
            )
        for resp in conn.request_many(lines):
            _check_ok(resp, f"push shard {shard}")


__all__ = ["ClusterClient", "ShardConnection"]
