"""ClusterClient — the worker side of the multi-shard runtime.

Implements the :class:`~..core.api.ParameterServerClient` ABC against
real shard sockets, plus the batch surface the compiled path uses.
Three bandwidth levers from the reference's sender stack
(SURVEY.md §2 #6), rebuilt for the wire:

  * **request coalescing** — duplicate ids inside one microbatch
    collapse to one pull per id (:func:`~..ops.dedup.coalesce_ids`);
    a Zipf-hot item appearing 300× per batch costs one line, and the
    answer scatters back to every lane via the inverse map;
  * **delta aggregation** — duplicate-id push deltas are summed before
    the bytes move (:func:`~..ops.dedup.aggregate_deltas`) — exactly
    the store's intra-batch combine semantics, applied at the sender;
  * **pipelined pulls with an in-flight window** — each shard
    connection carries up to ``window`` outstanding request frames
    (responses come back in order, the line-protocol contract), so the
    client overlaps shard round trips instead of paying RTT per chunk.
    The live window usage is the ``inflight_pulls`` gauge
    (``component=cluster``) — the same observability the event API's
    pull limiter got (:func:`~..core.api.add_pull_limiter`).

Shards are contacted concurrently (persistent fan-out pool workers —
:class:`_FanoutPool`; nothing is spawned per batch): a pull's wall
time is the SLOWEST shard's round trip, not the sum — which is what
makes the 1→2→4-shard scaling benchmark
(``benchmarks/cluster_scaling.py``) a real scaling measurement.

Binary framing (``wire_proto="auto"``, the default — docs/cluster.md
"Binary framing"): each connection opens with the ``hello bin v=1``
handshake; against a binary-capable server the data plane then moves
raw ``<i8`` ids and raw fp32 (or opt-in bf16, ``wire_format="bf16"``)
rows in length-prefixed frames — no base64, no ``repr()`` — while an
old server's ``err bad-request`` leaves that connection on the line
protocol (``wire_proto="line"`` never negotiates: the compat
baseline).  Epoch fencing, ``pr=`` priority, ``pid=`` exactly-once
tokens, ``sess=`` lease sessions, ``t=`` trace tokens, and ``inv=``
piggybacks all ride the frames (header fields + TLVs); rejection
handling is framing-agnostic.  ``spawn_grace_s`` bounds a dial-retry
window for REFUSED connects — a just-(re)spawned shard process
(cluster/procs.py) racing its own bind is liveness, not the
conn-class failure the retry budget exists for.

Pull RTT lands in a ``cluster_pull_rtt_seconds`` histogram per client
(p99 is the benchmark's tail-latency column).

Elastic routing (docs/elastic.md): handed a ``membership`` view
(:class:`~..elastic.membership.MembershipService`), the client derives
its partitioner + shard addresses from the CURRENT epoch, tags every
pull/push frame with ``e=<epoch>``, and turns shard rejections into
retries instead of errors:

  * ``err stale-epoch`` — the map flipped under the frame: refresh the
    membership view (counted in ``elastic_epoch_refreshes_total``),
    re-route the frame's ids under the new map, replay;
  * ``err frozen`` — the frame touches a key range mid-migration:
    back off a few ms and replay (the flip that re-homes the range is
    imminent);
  * connection errors — a shard died or was replaced: drop the cached
    connection, refresh (the controller publishes the replacement's
    address under a new epoch), replay.  Pushes carry a per-batch
    ``pid`` token so a replay of a frame whose ack was lost is
    deduplicated shard-side — latency, never a double-apply.

A client without ``membership`` behaves exactly as before: static
addresses, no epoch tags, rejections raise.

``hedge=`` accepts a :class:`~..elastic.hedging.Hedger`: pull frames
race a budgeted backup connection against a slow shard — first answer
wins (pulls are idempotent; pushes are never hedged).

Hot-key lease cache (``hotcache=``, docs/hotcache.md): with a
:class:`~..hotcache.cache.HotRowCache` and a lease policy attached,
every ``pull_batch`` is one cache **tick**; rows the cache holds
within its staleness bound are served locally (zero wire), cold
misses take the normal pull path (hedged, replica-routed), and HOT
misses are read via the ``lease`` verb — an atomic read + grant that
makes the shard queue piggybacked ``inv=`` invalidations when any
other writer touches the key.  The client strips ``inv=`` tokens from
every response, invalidates its own pushed ids at push time, clears
the cache on a membership refresh, and best-effort ``revoke``\\ s its
session at close.  Leases always route to the PRIMARY and are never
hedged (the grant is a side effect; a race could double-grant
harmlessly but would waste budget).  Against a pre-hotcache server the
first ``err bad-request`` flips the client to plain pulls for good —
the protocol-versioning downgrade path.

Overload control (loadgen/overload.py, docs/loadgen.md): an attached
``retry_budget`` (token bucket) is spent one token per replay round
and refilled by successes — exhausted, the batch FAILS FAST with
``RetryBudgetExhausted`` instead of feeding a retry storm.  A
``breakers`` board keys one circuit breaker per shard: enough
transport/shed failures inside the window OPEN the circuit and this
client's frames to that shard become local rejects (no wire) until a
half-open probe succeeds.  A shard's ``err overloaded`` shed answer
raises the typed ``OverloadedError`` immediately — shed traffic is
badput to count, never a replay.  ``priority=`` tags every frame
``pr=<n>`` so the shard edge sheds serving reads before training
pushes.  Retry volume is visible on /metrics as
``client_retries_total{verb,reason}``.

Replica-chain read routing (replication/, docs/elastic.md): when the
membership view carries ``replicas`` (or a static ``replicas=`` is
passed), pulls round-robin across ``[primary] + followers`` per shard.
A follower that declines (``err lagging`` past its staleness bound,
``err not-primary`` after a promotion) or cannot be reached FALLS BACK
to the primary — counted in ``replication_follower_fallbacks_total``,
never an error and never a membership refresh.  With a hedger
attached, a replica read that stalls races its budgeted backup against
the PRIMARY.  Writes always route to the primary.  ``connect_timeout``
bounds the dial separately from the read deadline — failure detection
for failover must not sit behind a 30 s read.
"""
from __future__ import annotations

import contextlib
import itertools
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.api import ParameterServerClient
from ..loadgen.overload import OverloadedError, RetryBudgetExhausted
from ..ops.dedup import aggregate_deltas, coalesce_ids
from ..telemetry.distributed import TraceContext, new_trace
from ..telemetry.profiler import NULL_PROFILER, resolve_profiler
from ..telemetry.spans import gen_id
from ..utils import frames as binf
from ..utils.net import (
    PeerHalfClosed,
    _safe_verb,
    client_meter,
    count_half_closed,
)
from .partition import Partitioner
from .shard import format_rows, parse_rows

_NULL_CM = contextlib.nullcontext()


class ShardConnection:
    """One pipelined connection to one shard — line protocol, binary
    frames (utils/frames.py), or both mixed.

    ``request_many`` keeps up to ``window`` requests outstanding; the
    shard answers in order, so responses re-associate positionally.
    Each request is self-describing: a ``str`` goes out as a text line
    (answered by a text line), ``bytes`` as a binary frame (answered
    by a binary frame decoded into a :class:`~..utils.frames.Frame`) —
    which is what lets the data plane go binary while control verbs
    (``stats``/``flush``) stay greppable text on the SAME connection.

    ``negotiate=True`` sends the ``hello bin v=1`` handshake at dial
    time; :attr:`proto` is then ``"bin"`` against a binary-capable
    server and ``"line"`` against an old one (which answered ``err
    bad-request`` — the downgrade path, docs/cluster.md).  Callers
    must not send binary frames on a ``"line"`` connection.

    Not thread-safe — each worker owns its connections (the driver
    builds one client per worker).
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        window: int = 8,
        timeout: float = 30.0,
        connect_timeout: Optional[float] = None,
        negotiate: bool = False,
    ):
        # dial and read deadlines are separate levers (failover-grade
        # failure detection needs a tight dial even when reads may
        # legitimately wait); None inherits the read timeout, capped
        # at the old 10 s dial default
        if connect_timeout is None:
            connect_timeout = min(float(timeout), 10.0)
        if window < 1:
            raise ValueError(f"window={window}: must be >= 1")
        self.host, self.port = host, port
        self.window = int(window)
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout
        )
        self._sock.settimeout(timeout)
        try:
            # pipelined request frames must leave NOW, not after Nagle
            # pairs them with a delayed ACK (~40 ms/frame otherwise)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._rfile = self._sock.makefile("rb")
        self.inflight = 0
        self.requests_sent = 0
        self.proto = "line"
        # quantized encodings the peer advertised on its hello answer
        # (frames.hello_encs): empty until negotiated; a bin server
        # without the enc= token is assumed bf16-only (PR-13 era) and
        # q8 frames downgrade to exact f32 on this connection
        self.encs: frozenset = frozenset()
        # client-role wire ledger (utils/net.py): bytes/frames per
        # verb, each direction — the other endpoint of the shard
        # servers' accounting
        self._meter = client_meter()
        if negotiate:
            self._negotiate()

    def _negotiate(self) -> None:
        """The per-connection binary handshake: one text round trip at
        dial time.  ``ok proto=bin`` upgrades; anything else (an old
        server's ``err bad-request``) leaves the connection on the
        line protocol — never an error."""
        resp = self.request_many([binf.HELLO_LINE])[0]
        if isinstance(resp, str) and resp.startswith("ok proto=bin"):
            self.proto = "bin"
            self.encs = binf.hello_encs(resp)

    def _read_exact(self, n: int, what: str) -> bytes:
        """Exactly ``n`` bytes off the buffered reader, or
        :class:`PeerHalfClosed` — a short read at EOF is the binary
        twin of the torn line frame (the peer died mid-frame)."""
        data = self._rfile.read(n)
        if data is None:
            data = b""
        if len(data) != n:
            count_half_closed("client")
            raise PeerHalfClosed(
                f"shard {self.host}:{self.port} closed mid-{what} "
                f"({len(data)}/{n} bytes)"
            )
        return data

    def _read_bin_response(self):
        hdr = self._read_exact(binf.HEADER_SIZE, "frame header")
        total = binf.frame_length(hdr)
        body = self._read_exact(total - binf.HEADER_SIZE, "frame body")
        # decode_split keeps header and body separate — joining them
        # would copy the whole row payload just to view into it
        frame = binf.decode_split(hdr, body, kind="response")
        self._meter.count("in", frame.verb_name, total)
        return frame

    def request_many(self, lines: Sequence) -> List:
        """Pipelined request/response: send up to ``window`` requests
        ahead of the reads, return one response per request —
        positionally, ``str`` for text lines, decoded
        :class:`~..utils.frames.Frame` for binary frames."""
        out: List = []
        pending = 0
        pending_meta: List[Tuple[str, str]] = []  # (framing, verb)
        it = iter(lines)
        sent = 0
        total = len(lines)
        while sent < total or pending:
            while pending < self.window and sent < total:
                req = next(it)
                if isinstance(req, (bytes, bytearray, memoryview)):
                    data = bytes(req)
                    verb = binf.peek_verb_name(data)
                    framing = "bin"
                else:
                    data = req.encode("utf-8") + b"\n"
                    verb = _safe_verb(req)
                    framing = "line"
                self._sock.sendall(data)
                self._meter.count("out", verb, len(data))
                pending_meta.append((framing, verb))
                pending += 1
                sent += 1
                self.inflight = pending
                self.requests_sent += 1
            framing, verb = pending_meta.pop(0)
            if framing == "bin":
                out.append(self._read_bin_response())
                pending -= 1
                self.inflight = pending
                continue
            raw = self._rfile.readline()
            if not raw or not raw.endswith(b"\n"):
                # empty read = peer half-close: the shard is GONE (died,
                # was replaced, RST mid-frame), not merely slow — a slow
                # shard surfaces as socket.timeout from the readline.
                # A NON-EMPTY read without its newline is the same event
                # one packet earlier: the peer died MID-FRAME and
                # readline returned the torn prefix at EOF — treating
                # that prefix as a response line would hand a truncated
                # payload to the parser (or worse, a truncated "ok ..."
                # to _check_ok).  Distinct retryable type + counted, so
                # the elastic retry path (and the operator) can tell a
                # dead peer from a slow one.
                count_half_closed("client")
                raise PeerHalfClosed(
                    f"shard {self.host}:{self.port} closed mid-pipeline "
                    f"({len(out)}/{total} responses"
                    + (", torn frame" if raw else "") + ")"
                )
            self._meter.count("in", verb, len(raw))
            out.append(raw.decode("utf-8", "replace").rstrip("\n"))
            pending -= 1
            self.inflight = pending
        return out

    def request(self, line: str) -> str:
        return self.request_many([line])[0]

    def close(self) -> None:
        try:
            # a reader blocked in readline() holds the buffer lock;
            # rfile.close() would wait on it — shutdown() first makes
            # the reader return EOF and release it (the hedging path
            # closes connections whose racer thread is still draining)
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._rfile.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def _frame_status(resp) -> Optional[int]:
    """The binary status code of a response, or None for text lines —
    the one switch every classifier below branches on, so each check
    reads identically over both framings."""
    return resp.flag if isinstance(resp, binf.Frame) else None


def _describe(resp) -> str:
    if isinstance(resp, binf.Frame):
        detail = resp.tlv_str(binf.T_ERR) or ""
        return f"err {resp.status_name}" + (f": {detail}" if detail else "")
    return resp


def _check_ok(resp, what: str):
    status = _frame_status(resp)
    if status is not None:
        if status != binf.STATUS_OK:
            raise RuntimeError(f"{what} failed: {_describe(resp)}")
        return resp
    if not resp.startswith("ok"):
        raise RuntimeError(f"{what} failed: {resp}")
    return resp


def _is_reject(resp) -> bool:
    """A shard answer the elastic client treats as retry-after-refresh
    rather than an error: the map flipped (stale-epoch) or the keys are
    mid-migration (frozen)."""
    status = _frame_status(resp)
    if status is not None:
        return status in (binf.STATUS_STALE_EPOCH, binf.STATUS_FROZEN)
    return resp.startswith("err stale-epoch") or resp.startswith(
        "err frozen"
    )


def _reject_reason(resp) -> str:
    status = _frame_status(resp)
    if status is not None:
        return (
            "frozen" if status == binf.STATUS_FROZEN else "stale-epoch"
        )
    return (
        "frozen" if resp.startswith("err frozen") else "stale-epoch"
    )


def _is_overloaded(resp) -> bool:
    """The shard's typed shed answer (loadgen/overload.py
    ``OverloadGuard``): the request was REJECTED under load pressure,
    deliberately and cheaply.  The client fails fast with
    :class:`~..loadgen.overload.OverloadedError` — retrying a shed
    would feed exactly the storm the shed exists to stop."""
    status = _frame_status(resp)
    if status is not None:
        return status == binf.STATUS_OVERLOADED
    return resp.startswith("err overloaded")


def _is_follower_reject(resp) -> bool:
    """A replica-chain follower declining a read: lagging past the
    staleness bound, or no longer a follower at all.  The client falls
    back to the primary — NOT a membership refresh (the map is fine;
    this one replica is stale)."""
    status = _frame_status(resp)
    if status is not None:
        return status in (
            binf.STATUS_LAGGING, binf.STATUS_NOT_PRIMARY
        )
    return resp.startswith("err lagging") or resp.startswith(
        "err not-primary"
    )


def _is_bad_request(resp) -> bool:
    status = _frame_status(resp)
    if status is not None:
        return status == binf.STATUS_BAD_REQUEST
    return resp.startswith("err bad-request")


class _Rejected(Exception):
    """Internal: carries the ids a shard rejected (stale-epoch/frozen)
    or could not be reached for, so the batch loop replays exactly
    those under a refreshed map.  ``reason`` labels the retry counter
    (stale-epoch | frozen | conn | breaker_open)."""

    def __init__(self, ids: np.ndarray, reason: str = "reject"):
        super().__init__(f"{len(ids)} ids rejected ({reason})")
        self.ids = ids
        self.reason = reason


class _LeaseUnsupported(Exception):
    """Internal: the shard answered a ``lease`` frame with
    ``err bad-request`` — a pre-hotcache server.  The client downgrades
    to plain pulls for the rest of its life (the PR-6 versioning
    contract working in the other direction)."""


class _PoolWorker:
    """One persistent fan-out thread (see :class:`_FanoutPool`).
    Job hand-off state is guarded by ``_lock`` (the condition shares
    it — the :class:`~..replication.shipper._FollowerQueue` idiom)."""

    def __init__(self, name: str):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._job = None
        self._stopped = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    def submit(self, fn, errors, errors_lock) -> threading.Event:
        done = threading.Event()
        with self._lock:
            self._job = (fn, errors, errors_lock, done)
            self._cond.notify()
        return done

    def _loop(self) -> None:
        while True:
            with self._lock:
                while self._job is None and not self._stopped:
                    self._cond.wait(0.2)
                if self._stopped:
                    return
                fn, errors, errors_lock, done = self._job
                self._job = None
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — re-raised by run()
                with errors_lock:
                    errors.append(e)
            finally:
                done.set()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._cond.notify()
        self._thread.join(timeout=5)


class _FanoutPool:
    """Persistent threads for the client's per-shard fan-out.

    The batch surface used to SPAWN a fresh thread per contacted shard
    per ``pull_batch``/``push_batch`` call — ~100 µs of create/start
    per shard per round, paid thousands of times a second, plus a cold
    scheduler wakeup right on the latency path.  A client makes the
    same-shaped fan-out call every round of its life, so the threads
    are now long-lived: one fan-out runs ``len(jobs)-1`` jobs on pool
    workers and the LAST one inline on the calling thread (on a busy
    host that is one fewer handoff on the critical path).  Not
    thread-safe — owned by one client, which is itself single-caller
    by contract."""

    def __init__(self, name: str = "fps-fanout"):
        self._name = name
        self._workers: List[_PoolWorker] = []

    def run(self, jobs) -> None:
        if not jobs:
            return
        if len(jobs) == 1:
            jobs[0]()
            return
        errors: List[BaseException] = []
        lock = threading.Lock()
        while len(self._workers) < len(jobs) - 1:
            self._workers.append(_PoolWorker(
                f"{self._name}-{len(self._workers)}"
            ))
        waits = [
            w.submit(fn, errors, lock)
            for w, fn in zip(self._workers, jobs[:-1])
        ]
        try:
            jobs[-1]()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            with lock:
                errors.append(e)
        for done in waits:
            done.wait()
        if errors:
            raise errors[0]

    def close(self) -> None:
        """Join every worker — a closed client must leak no package
        threads (the nemesis ThreadLedger invariant)."""
        for w in self._workers:
            w.stop()
        self._workers = []


class ClusterClient(ParameterServerClient):
    """Worker-side handle over every shard.

    Batch surface (the compiled path): :meth:`pull_batch` /
    :meth:`push_batch` — coalesced, pipelined, shard-parallel.
    Event surface (the ABC): :meth:`pull` buffers the id, :meth:`push`
    buffers the delta; :meth:`drain` flushes both coalesced and
    delivers pull answers to a callback — the combination-sender
    semantics per worker.
    """

    def __init__(
        self,
        addresses: Optional[Sequence[Tuple[str, int]]] = None,
        partitioner: Optional[Partitioner] = None,
        value_shape: Sequence[int] = (),
        *,
        window: int = 8,
        chunk: int = 512,
        timeout: float = 30.0,
        connect_timeout: float = 5.0,
        wire_format: str = "b64",
        wire_proto: str = "auto",
        spawn_grace_s: float = 0.0,
        registry=None,
        worker: Optional[str] = None,
        membership=None,
        replicas=None,
        read_replicas: bool = True,
        hedge=None,
        push_hedge=None,
        hotcache=None,
        lease_policy=None,
        lease_ttl: int = 16,
        retry_timeout: float = 30.0,
        retry_sleep_s: float = 0.002,
        retry_sleep_cap_s: float = 0.05,
        retry_budget=None,
        breakers=None,
        priority: Optional[int] = None,
        tracer=None,
        flightrec=None,
        storm_threshold: int = 25,
        storm_window_s: float = 5.0,
        profiler=None,
    ):
        if membership is None:
            if addresses is None or partitioner is None:
                raise ValueError(
                    "static client needs addresses + partitioner "
                    "(or pass membership=)"
                )
            if len(addresses) != partitioner.num_shards:
                raise ValueError(
                    f"{len(addresses)} shard addresses for a "
                    f"{partitioner.num_shards}-shard partitioner"
                )
            self._epoch: Optional[int] = None
            self.partitioner = partitioner
            self._addresses = [tuple(a) for a in addresses]
            self._replicas = (
                [tuple(tuple(a) for a in r) for r in replicas]
                if replicas else []
            )
        else:
            view = membership.current()
            self._epoch = view.epoch
            self.partitioner = view.partitioner
            self._addresses = [tuple(a) for a in view.addresses]
            self._replicas = [tuple(r) for r in view.replicas]
        if chunk < 1:
            raise ValueError(f"chunk={chunk}: must be >= 1")
        if wire_format not in ("text", "b64", "bf16", "q8"):
            raise ValueError(
                f"wire_format={wire_format!r}: "
                f"'text' | 'b64' | 'bf16' | 'q8'"
            )
        if wire_proto not in ("auto", "line", "shm"):
            raise ValueError(
                f"wire_proto={wire_proto!r}: 'auto' | 'line' | 'shm'"
            )
        self.membership = membership
        self.hedge = hedge
        # write-side hedging is only safe when pushes carry a pid (the
        # (pid,id) dedupe window suppresses the losing leg's apply), so
        # _push_shard gates on pid presence, not just this handle
        self.push_hedge = push_hedge
        self.value_shape = tuple(int(s) for s in value_shape)
        self.chunk = int(chunk)
        # b64 (default): exact fp32 bytes, ~100x cheaper than per-float
        # text (shard.py module docstring); "text" for debuggability.
        # Over the binary framing, "text"/"b64" both become raw fp32
        # (exact); "bf16" halves row bytes (lossy, opt-in — falls back
        # to b64 on a line-proto connection, which has no bf16).
        self.wire_format = wire_format
        # "auto": negotiate binary framing per connection (one hello
        # round trip at dial time; an old server's err bad-request
        # downgrades that connection to the line protocol).  "line":
        # never negotiate — bit-for-bit the pre-binary client, the
        # compat baseline the cross-version tests pin.  "shm": attempt
        # the shared-memory hello against co-located shards (shmem/),
        # falling back per connection to binary TCP (then lines) for
        # non-local peers, old servers, or a proxied path — each
        # fallback counted in shmem_fallbacks_total.
        self._wire_proto = wire_proto
        # spawn grace (cluster/procs.py): a just-spawned shard process
        # may not have bound yet when its first dial arrives — retry
        # REFUSED dials inside this window instead of surfacing a
        # conn-class reject that burns storm retry budget
        self._spawn_grace_s = float(spawn_grace_s)
        self._window = int(window)
        self._timeout = float(timeout)
        self._connect_timeout = float(connect_timeout)
        # replica-chain read routing (replication/, docs/elastic.md):
        # pulls rotate across [primary] + followers; follower rejects
        # and connection errors fall back to the primary.  Writes
        # always go to the primary.
        self._read_replicas = bool(read_replicas)
        self._rr: Dict[int, int] = {}
        self.retry_timeout = float(retry_timeout)
        self.retry_sleep_s = float(retry_sleep_s)
        self.retry_sleep_cap_s = float(retry_sleep_cap_s)
        # overload control (loadgen/overload.py, docs/loadgen.md):
        # retry_budget = token bucket over replay rounds (exhausted →
        # RetryBudgetExhausted fails fast instead of feeding a retry
        # storm); breakers = per-shard circuit BreakerBoard (an open
        # shard's frames become rejects without touching the wire);
        # priority rides frames as pr=<n> so the shard-edge guard can
        # shed serving traffic before training pushes
        self.retry_budget = retry_budget
        self.breakers = breakers
        self._priority = None if priority is None else int(priority)
        # retry backoff state: decorrelated-jitter sleeps need the
        # previous draw, and each client needs its OWN stream — a herd
        # of workers replaying into a recovering shard must disperse,
        # not arrive in lockstep (the retry-storm fix; the jitter shape
        # is resilience/recovery.py's, decorrelated per AWS)
        self._retry_rng = np.random.default_rng(
            (os.getpid() << 16) ^ (id(self) & 0xFFFF_FFFF)
            ^ (hash(worker) & 0xFFFF if worker is not None else 0)
        )
        self._last_retry_sleep: Optional[float] = None
        self._conns: Dict[Tuple[str, int], ShardConnection] = {}
        # persistent per-shard fan-out threads (no per-batch spawns)
        self._pool = _FanoutPool(
            f"fps-fanout-{worker}" if worker is not None else "fps-fanout"
        )
        self.outputs: List[object] = []
        self._pending_pulls: List[int] = []
        self._pending_pushes: List[Tuple[int, np.ndarray]] = []
        self.pulls_coalesced = 0  # duplicate lanes saved from the wire
        self.pushes_coalesced = 0
        self.rows_pushed = 0  # unique delta rows acked (the audit ledger)
        self.frames_retried = 0  # frames replayed after a reject/refresh
        # per-batch idempotence token base: unique per client instance
        self._pid_base = f"{os.getpid():x}.{id(self):x}"
        self._pid_counter = itertools.count()
        # hot-key lease cache (hotcache/, docs/hotcache.md): attached
        # here or later via attach_hotcache; None = no caching at all
        self.hotcache = None
        self.lease_policy = None
        self._lease_ttl = int(lease_ttl)
        self._lease_supported = True
        self._sess: Optional[str] = None
        self.leases_acquired = 0  # lease frames answered ok
        if hotcache is not None:
            self.attach_hotcache(
                hotcache, lease_policy, lease_ttl=lease_ttl
            )
        # distributed tracing (telemetry/distributed.py): with a tracer
        # attached, each pull/push batch becomes one trace, each shard
        # request a child span whose id rides the frame as t=<tr>:<sp>
        self._tracer = tracer
        # stale-epoch storms: retry rounds that keep failing to
        # converge on a servable map trip the flight recorder once
        self._flightrec = flightrec
        if membership is not None:
            from ..telemetry.flightrec import StormDetector

            self._storm = StormDetector(storm_threshold, storm_window_s)
        else:
            self._storm = None
        # unified plane (component=cluster): the pull RTT histogram and
        # the live in-flight window gauge
        if registry is not False:
            from ..telemetry.registry import get_registry

            reg = registry if registry is not None else get_registry()
            labels = {"worker": worker} if worker is not None else {}
            # stash for the on-demand retry counters (_await_retry):
            # client_retries_total{verb,reason} label pairs are only
            # known at retry time
            self._reg = reg
            self._labels = dict(labels)
            self._h_rtt = reg.histogram(
                "cluster_pull_rtt_seconds", component="cluster", **labels
            )
            reg.gauge(
                "inflight_pulls", component="cluster", fn=self.inflight,
                **labels,
            )
            self._c_refresh = (
                reg.counter(
                    "elastic_epoch_refreshes_total", component="elastic",
                    **labels,
                )
                if membership is not None
                else None
            )
            self._c_storms = (
                reg.counter(
                    "elastic_stale_epoch_storms_total",
                    component="elastic", **labels,
                )
                if membership is not None
                else None
            )
            if membership is not None or replicas:
                self._c_replica_reads = reg.counter(
                    "replication_replica_reads_total",
                    component="replication", **labels,
                )
                self._c_fallbacks = reg.counter(
                    "replication_follower_fallbacks_total",
                    component="replication", **labels,
                )
            else:
                self._c_replica_reads = self._c_fallbacks = None
        else:
            self._reg = None
            self._labels = {}
            self._h_rtt = None
            self._c_refresh = None
            self._c_storms = None
            self._c_replica_reads = self._c_fallbacks = None
        # per-SHARD pull RTT (timeline plane, docs/observability.md):
        # the worker-labelled histogram above answers "is this worker
        # slow"; these lazily-registered per-shard twins answer "WHICH
        # shard is making it slow" — the series the SkewTracker and
        # the straggler A/B attribute against.  Lazy because the shard
        # set is a runtime variable under the elastic plane.
        self._h_shard_rtt: Dict[int, Any] = {}
        # latency-budget phases (telemetry/profiler.py): per-frame
        # client serialize / round trip / parse — the client side of
        # the budget.  registry=False implies profiling off too.
        self._profiler = (
            NULL_PROFILER if registry is False and profiler is None
            else resolve_profiler(profiler)
        )
        # quantized delta push path (compression/, docs/compression.md):
        # wire_format "q8"/"bf16" routes every push through an
        # error-feedback DeltaCompressor — the table ALWAYS receives
        # exactly the dequantized rows, over any framing (q8/bf16
        # frames on advertising peers, exact f32 on old ones), so
        # replays, re-routes and mixed fleets stay deterministic and
        # the exactly-once ledger balances.  BSP carve-out is the
        # DRIVER's job (bound-0 worker clients are built with "b64").
        self._compressor = None
        self._c_bytes_saved = None
        if wire_format in ("q8", "bf16"):
            from ..compression.quantizers import DeltaCompressor

            self._compressor = DeltaCompressor(wire_format)
            if self._reg is not None:
                self._c_bytes_saved = self._reg.counter(
                    "compression_bytes_saved_total",
                    component="compression", **self._labels,
                )
                self._reg.gauge(
                    "compression_residual_norm",
                    component="compression",
                    fn=self._compressor.residuals.norm, **self._labels,
                )

    # -- hot-key lease cache (hotcache/, docs/hotcache.md) --------------------
    def attach_hotcache(
        self, cache, policy=None, *, lease_ttl: int = 16
    ) -> "ClusterClient":
        """Attach a :class:`~..hotcache.cache.HotRowCache` (+ lease
        policy deciding which keys are lease-worthy).  The BSP
        carve-out is the CALLER's job: a bound-0 worker client must
        never get a cache (``ClusterDriver`` enforces it — reads must
        see every previous-round write)."""
        self.hotcache = cache
        self.lease_policy = policy
        self._lease_ttl = int(lease_ttl)
        self._lease_supported = True
        # session token: what the shard keys this client's grants and
        # piggybacked invalidations on (unique per client instance)
        self._sess = f"c{self._pid_base}"
        return self

    def _apply_response_options(self, resp):
        """Apply piggybacked response options (``inv=`` invalidations)
        to the cache.  Text lines are stripped of their trailing
        tokens and returned bare; binary frames carry the same payload
        in a ``T_INV`` TLV and are returned as-is."""
        from ..hotcache.leases import parse_inv_token, split_response_options

        if isinstance(resp, binf.Frame):
            inv = resp.tlv_str(binf.T_INV)
            if inv is not None and self.hotcache is not None:
                self.hotcache.invalidate(parse_inv_token(inv))
            return resp
        body, opts = split_response_options(resp)
        inv = opts.get("inv")
        if inv is not None and self.hotcache is not None:
            self.hotcache.invalidate(parse_inv_token(inv))
        return body

    # -- observability ------------------------------------------------------
    def inflight(self) -> int:
        """Outstanding pull/push frames across every shard connection —
        the live pipelining depth (<= window × shards)."""
        return sum(c.inflight for c in list(self._conns.values()))

    # -- connections / membership -------------------------------------------
    def _dial(self, addr: Tuple[str, int]) -> ShardConnection:
        """Dial one shard (negotiating the binary framing when
        ``wire_proto="auto"``).  A REFUSED dial inside the spawn grace
        window is retried with short sleeps: a shard process that was
        just spawned (or respawned by its supervisor) races its own
        ``bind`` against the first dial, and that race is liveness —
        not the conn-class failure signal the retry budget and the
        breaker exist for."""
        deadline = (
            time.monotonic() + self._spawn_grace_s
            if self._spawn_grace_s > 0 else None
        )
        use_shm = False
        if self._wire_proto == "shm":
            # shared memory only reaches co-located peers; a remote
            # address is a not-local fallback before any segment exists
            from ..shmem.channel import shm_usable

            use_shm = shm_usable(addr[0])
            if not use_shm:
                from ..shmem.metrics import count_fallback

                count_fallback(
                    "not-local",
                    registry=self._reg if self._reg is not None else False,
                )
        while True:
            try:
                if use_shm:
                    from ..shmem.channel import ShmShardConnection

                    return ShmShardConnection(
                        addr[0], addr[1], window=self._window,
                        timeout=self._timeout,
                        connect_timeout=self._connect_timeout,
                        registry=(
                            self._reg if self._reg is not None else False
                        ),
                    )
                return ShardConnection(
                    addr[0], addr[1], window=self._window,
                    timeout=self._timeout,
                    connect_timeout=self._connect_timeout,
                    negotiate=self._wire_proto in ("auto", "shm"),
                )
            except ConnectionRefusedError:
                if deadline is None or time.monotonic() >= deadline:
                    raise
                time.sleep(0.02)

    def _conn_for_addr(self, addr: Tuple[str, int]) -> ShardConnection:
        conn = self._conns.get(addr)
        if conn is None:
            conn = self._dial(addr)
            self._conns[addr] = conn
        return conn

    def _conn_for(self, shard: int) -> ShardConnection:
        return self._conn_for_addr(self._addresses[shard])

    def _drop_addr(self, addr: Tuple[str, int]) -> None:
        conn = self._conns.pop(addr, None)
        if conn is not None:
            conn.close()

    def _drop_conn(self, shard: int) -> None:
        self._drop_addr(self._addresses[shard])

    def _refresh_membership(self) -> bool:
        """Re-read the membership view; adopt a newer epoch's map +
        addresses + replica sets (closing connections to addresses
        that left).  Returns True when a new epoch was adopted."""
        if self.membership is None:
            return False
        view = self.membership.current()
        if view.epoch == self._epoch:
            return False
        self._epoch = view.epoch
        self.partitioner = view.partitioner
        new_addrs = [tuple(a) for a in view.addresses]
        new_replicas = [tuple(r) for r in view.replicas]
        keep = set(new_addrs)
        for reps in new_replicas:
            keep.update(reps)
        for addr in list(self._conns):
            if addr not in keep:
                self._conns.pop(addr).close()
        self._addresses = new_addrs
        self._replicas = new_replicas
        if self.hotcache is not None:
            # a resharding may have re-homed any cached key: drop
            # everything (the shards queued inv=* too — this is the
            # client-side half of the same conservatism)
            self.hotcache.clear()
        if self._c_refresh is not None:
            self._c_refresh.inc()
        return True

    # -- replica-chain read routing ------------------------------------------
    def _read_target(self, shard: int) -> Tuple[Tuple[str, int], bool]:
        """Where the next read for ``shard`` goes: round-robin across
        the primary + its followers (``(addr, is_replica)``)."""
        primary = self._addresses[shard]
        reps = (
            self._replicas[shard]
            if self._read_replicas and shard < len(self._replicas)
            else ()
        )
        if not reps:
            return primary, False
        targets = [primary] + list(reps)
        i = self._rr.get(shard, 0)
        self._rr[shard] = i + 1
        addr = targets[i % len(targets)]
        return addr, addr != primary

    def _next_retry_sleep(self, attempt: int) -> float:
        """The next replay-round sleep: capped exponential with
        DECORRELATED jitter — ``uniform(base, min(cap, 3 × previous))``
        with the exponential ceiling as a floor on the range, capped at
        ``retry_sleep_cap_s``.

        The predecessor was ``min(0.05, base × (1 + attempt))``:
        linear, capped at 50 ms, and IDENTICAL across workers — after
        a partition healed or a shard was replaced, every worker woke
        on the same schedule and hammered the recovering shard in
        lockstep (the retry storm the flight recorder kept catching).
        Per-client seeded draws decorrelate the herd; the cap keeps
        the worst case at the old 50 ms."""
        base = max(1e-6, self.retry_sleep_s)
        cap = self.retry_sleep_cap_s
        ceiling = min(cap, base * (2 ** min(attempt, 16)))
        prev = self._last_retry_sleep if self._last_retry_sleep else base
        hi = min(cap, max(prev * 3.0, ceiling))
        sleep = float(self._retry_rng.uniform(base, max(base, hi)))
        sleep = min(cap, sleep)
        self._last_retry_sleep = sleep
        return sleep

    def _await_retry(
        self, deadline: float, attempt: int, what: str,
        reason: str = "reject",
    ) -> None:
        """Between replay rounds: refresh the view; if nothing changed,
        sleep briefly (the flip/replacement is in flight) — bounded by
        ``retry_timeout`` so a wedged cluster still surfaces.  Each
        round is counted (``client_retries_total{verb,reason}`` —
        retry volume was invisible on /metrics before this) and spends
        one retry-budget token when a budget is attached; an exhausted
        budget FAILS FAST instead of feeding the storm."""
        if self.membership is None:
            raise RuntimeError(
                f"{what}: shard rejected the frame and no membership "
                f"view is attached (static client cannot re-route)"
            )
        if self._reg is not None:
            self._reg.counter(
                "client_retries_total", component="cluster",
                verb=what, reason=reason, **self._labels,
            ).inc()
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"{what}: retried past retry_timeout="
                f"{self.retry_timeout}s without converging on a "
                f"servable map"
            )
        # only STORM-CLASS retries spend budget: connection failures
        # and open breakers are the signals that amplify under
        # overload.  Epoch-flip replays (stale-epoch/frozen) are the
        # elastic control plane working as designed — rate-limiting
        # those would turn every resize into artificial sheds.
        if (
            self.retry_budget is not None
            and reason in ("conn", "breaker_open")
            and not self.retry_budget.try_spend()
        ):
            raise RetryBudgetExhausted(
                f"{what}: retry budget exhausted after {attempt} "
                f"replay rounds (reason: {reason}) — failing fast"
            )
        if self._storm is not None and self._storm.note():
            # many reject-driven retries inside the window: the flip is
            # NOT converging — blackbox it before a timeout loses the
            # evidence (one dump per storm, throttled recorder-side)
            if self._c_storms is not None:
                self._c_storms.inc()
            rec = self._flightrec
            if rec is None:
                from ..telemetry.flightrec import get_recorder

                rec = get_recorder()
            if rec is not None:
                rec.note(
                    "stale_epoch_storm", epoch=self._epoch, what=what,
                    retries=self.frames_retried,
                )
                rec.dump("stale_epoch_storm")
        if not self._refresh_membership():
            time.sleep(self._next_retry_sleep(attempt))

    # -- the batch surface --------------------------------------------------
    def _trace_root(self, name: str):
        """``(ctx, span_cm)`` opening one distributed trace per logical
        batch call — ``(None, nullcontext)`` when tracing is off."""
        tr = self._tracer
        if tr is None or not tr.enabled:
            return None, _NULL_CM
        ctx = new_trace()
        return ctx, tr.span(
            name, "cluster", trace_id=ctx.trace_id, span_id=ctx.span_id
        )

    def pull_batch(
        self, ids, mask=None, *, dtype=np.float32
    ) -> np.ndarray:
        """Pull values for ``ids`` (any shape); returns
        ``ids.shape + value_shape`` float32.  Duplicate ids cost one
        wire request; per-shard traffic runs concurrently."""
        ids_arr = np.asarray(ids)
        unique, inverse = coalesce_ids(ids_arr, mask)
        self.pulls_coalesced += int(ids_arr.size - unique.size)
        width = int(np.prod(self.value_shape)) if self.value_shape else 1
        flat = np.empty((unique.size, width), dtype)
        todo = unique
        cache = self.hotcache
        if cache is not None:
            # one pull_batch = one cache tick (a worker round / a
            # serving request); entries within the staleness bound are
            # served with zero wire, the rest fall through below
            cache.tick()
            hits = cache.lookup(unique)
            if hits:
                hit_ids = np.fromiter(hits.keys(), np.int64, len(hits))
                hit_ids.sort()
                flat[np.searchsorted(unique, hit_ids)] = np.stack(
                    [hits[int(g)] for g in hit_ids]
                ).reshape(len(hit_ids), width).astype(dtype)
                todo = np.setdiff1d(unique, hit_ids, assume_unique=True)
        deadline = time.monotonic() + self.retry_timeout
        attempt = 0
        self._last_retry_sleep = None  # fresh backoff ladder per batch
        ctx, root_span = self._trace_root("pull_batch")
        with root_span:
            while todo.size:
                by_shard = self._split(todo)
                rejected: List[np.ndarray] = []
                reasons: List[str] = []
                rej_lock = threading.Lock()

                def do(s, sids):
                    try:
                        rows = self._pull_shard(s, sids, ctx)
                    except _Rejected as r:
                        with rej_lock:
                            rejected.append(r.ids)
                            reasons.append(r.reason)
                        return
                    flat[np.searchsorted(unique, sids)] = rows.reshape(
                        len(sids), width
                    )

                self._for_each_shard(by_shard, do)
                todo = (
                    np.concatenate(rejected) if rejected
                    else np.empty(0, np.int64)
                )
                if todo.size:
                    attempt += 1
                    self.frames_retried += 1
                    self._await_retry(
                        deadline, attempt, "pull", reason=reasons[0]
                    )
        if self.retry_budget is not None:
            self.retry_budget.on_success()
        out = flat.reshape(unique.shape + self.value_shape)
        return out[inverse]

    def push_batch(self, ids, deltas, mask=None) -> int:
        """Aggregate duplicate-id deltas, push each shard's share (in
        parallel, pipelined); returns unique ids pushed.  Under a
        membership view every frame carries this batch's ``pid`` token,
        so replays after a lost ack stay exactly-once shard-side."""
        ids_arr = np.asarray(ids)
        unique, summed = aggregate_deltas(ids_arr, np.asarray(deltas), mask)
        if unique.size == 0:
            return 0
        if self.hotcache is not None:
            # write-through invalidate: the client's own cached copies
            # are stale the moment this push applies (other sessions'
            # copies are the shard lease board's job)
            self.hotcache.invalidate(unique)
        self.pushes_coalesced += int(
            (ids_arr.size if mask is None else int(np.asarray(mask).sum()))
            - unique.size
        )
        # quantize ONCE per logical batch (error feedback applied here,
        # never in a retry path): the delivered rows are the
        # dequantized values, identical over every framing and every
        # replay — the q sections are sliced per shard below
        q_rows = q_scales = None
        if self._compressor is not None:
            summed, q_rows, q_scales = self._compressor.compress(
                unique, summed
            )
            summed = summed.astype(np.float32)
        # one pid per logical batch: (pid, id) identifies each row-push
        # uniquely (unique is deduped), stable across replays/re-routes
        pid = (
            f"{self._pid_base}.{next(self._pid_counter)}"
            if self.membership is not None
            else None
        )
        todo_ids, todo_rows = unique, summed
        deadline = time.monotonic() + self.retry_timeout
        attempt = 0
        self._last_retry_sleep = None  # fresh backoff ladder per batch
        ctx, root_span = self._trace_root("push_batch")
        with root_span:
            while todo_ids.size:
                by_shard = self._split(todo_ids)
                rejected: List[np.ndarray] = []
                reasons: List[str] = []
                rej_lock = threading.Lock()

                def do(s, sids):
                    rows = todo_rows[np.searchsorted(todo_ids, sids)]
                    qr = qs = None
                    if q_rows is not None:
                        # unique is sorted and every retry set is a
                        # subset of it, so the q sections slice by the
                        # same positional lookup on any replay round
                        pos = np.searchsorted(unique, sids)
                        qr, qs = q_rows[pos], q_scales[pos]
                    try:
                        self._push_shard(
                            s, sids, rows, pid, ctx, q_rows=qr,
                            q_scales=qs,
                        )
                    except _Rejected as r:
                        with rej_lock:
                            rejected.append(r.ids)
                            reasons.append(r.reason)

                self._for_each_shard(by_shard, do)
                done = todo_ids.size - sum(len(r) for r in rejected)
                self.rows_pushed += int(done)
                if rejected:
                    retry = np.sort(np.concatenate(rejected))
                    # keep the sorted-ids invariant: the per-shard row
                    # lookup above is a searchsorted against todo_ids
                    todo_rows = todo_rows[np.searchsorted(todo_ids, retry)]
                    todo_ids = retry
                    attempt += 1
                    self.frames_retried += 1
                    self._await_retry(
                        deadline, attempt, "push", reason=reasons[0]
                    )
                else:
                    todo_ids = np.empty(0, np.int64)
        if self.retry_budget is not None:
            self.retry_budget.on_success()
        return int(unique.size)

    def flush(self) -> List[str]:
        """FLUSH every shard (WAL fsync + ack) — the explicit durability
        barrier a bound-0 round ends with when durability matters."""
        return [
            _check_ok(self._conn_for(s).request("flush"), f"flush shard {s}")
            for s in range(self.partitioner.num_shards)
        ]

    def shard_stats(self) -> List[dict]:
        import json

        out = []
        for s in range(self.partitioner.num_shards):
            resp = _check_ok(
                self._conn_for(s).request("stats"), f"stats shard {s}"
            )
            out.append(json.loads(resp[3:]))
        return out

    # -- the event-API surface (ParameterServerClient) ----------------------
    def pull(self, param_id: int) -> None:
        """Buffer a pull; answers arrive at the next :meth:`drain` —
        the asynchronous contract of the ABC, with the microbatch as
        the combination buffer."""
        self._pending_pulls.append(int(param_id))

    def push(self, param_id: int, delta) -> None:
        self._pending_pushes.append((int(param_id), np.asarray(delta)))

    def output(self, w_out) -> None:
        self.outputs.append(w_out)

    def drain(self, on_pull_recv=None) -> int:
        """Flush buffered pushes (aggregated) and answer buffered pulls
        (coalesced); ``on_pull_recv(param_id, value, client)`` is
        invoked once per buffered pull, in buffering order.  Returns
        the number of answers delivered."""
        if self._pending_pushes:
            ids = np.asarray([i for i, _ in self._pending_pushes], np.int64)
            deltas = np.stack([d for _, d in self._pending_pushes])
            self._pending_pushes = []
            self.push_batch(ids, deltas)
        n = 0
        if self._pending_pulls:
            ids = np.asarray(self._pending_pulls, np.int64)
            self._pending_pulls = []
            values = self.pull_batch(ids)
            for i, pid in enumerate(ids):
                if on_pull_recv is not None:
                    on_pull_recv(int(pid), values[i], self)
                n += 1
        return n

    def close(self) -> None:
        if (
            self.hotcache is not None
            and self._sess is not None
            and self._lease_supported
        ):
            # best-effort lease release on live primary connections —
            # the shard board stops tracking this session; failures
            # are fine (the board evicts idle sessions on its own)
            primaries = set(self._addresses)
            for addr, conn in list(self._conns.items()):
                if addr not in primaries:
                    continue
                try:
                    conn.request(f"revoke all sess={self._sess}")
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        for c in list(self._conns.values()):
            c.close()
        self._conns = {}
        self._pool.close()
        if self.hedge is not None:
            self.hedge.close()
        if self.push_hedge is not None:
            self.push_hedge.close()

    # -- internals ----------------------------------------------------------
    def _split(self, unique_ids: np.ndarray) -> Dict[int, np.ndarray]:
        shards = self.partitioner.shard_of(unique_ids)
        return {
            int(s): unique_ids[shards == s] for s in np.unique(shards)
        }

    def _for_each_shard(self, by_shard: Dict[int, np.ndarray], fn) -> None:
        """Run ``fn(shard, ids)`` for every shard concurrently —
        persistent pool workers for all but one, the last inline on
        this thread (errors propagate to the caller; see
        :class:`_FanoutPool` for why nothing is spawned here)."""
        items = list(by_shard.items())
        if len(items) == 1:
            fn(*items[0])
            return
        self._pool.run([
            (lambda s=s, sids=sids: fn(s, sids)) for s, sids in items
        ])

    def _frame_suffix(self, pid: Optional[str] = None) -> str:
        suffix = ""
        if pid is not None:
            suffix += f" pid={pid}"
        if self._epoch is not None:
            suffix += f" e={self._epoch}"
        if self._priority is not None:
            # overload-plane priority tag (loadgen/overload.py): the
            # shard-edge guard sheds pr=2 (serving) traffic first and
            # never sheds pr=0; old servers parse-and-ignore
            suffix += f" pr={self._priority}"
        if self.hotcache is not None and self._sess is not None:
            # declares a lease-capable session: responses may carry
            # piggybacked inv= tokens (old servers parse-and-ignore)
            suffix += f" sess={self._sess}"
        return suffix

    def _frame_trace(self, shard: int, name: str, ctx):
        """Per-shard child span + the BARE trace token its id rides on
        (``<trace>:<span>`` — the line protocol prefixes ``t=``, the
        binary framing carries it as a ``T_TRACE`` TLV):
        ``(token_or_None, span_cm, span_id)`` — empties when
        untraced."""
        if ctx is None or self._tracer is None or not self._tracer.enabled:
            return None, _NULL_CM, None
        span_id = gen_id(4)
        tok = TraceContext(ctx.trace_id, span_id).token()
        cm = self._tracer.span(
            f"{name}.shard{shard}", "cluster",
            trace_id=ctx.trace_id, parent_id=ctx.span_id, span_id=span_id,
        )
        return tok, cm, span_id

    @staticmethod
    def _materialize(lines, conn) -> List:
        """Requests for one connection: a plain list is used as-is; a
        CALLABLE is invoked with the connection (``build(conn)``) so
        the emit paths can render text lines or binary frames per the
        connection's negotiated protocol — which may differ between a
        replica and the primary it falls back to (a mixed-version
        fleet mid-rollout)."""
        return lines(conn) if callable(lines) else lines

    def _request_frames(
        self, shard: int, sids: np.ndarray, lines, *,
        hedgeable: bool, hedger=None, trace=None,
    ) -> List:
        """Send one shard's frames; a connection-level failure in
        elastic mode becomes a :class:`_Rejected` (drop the cached
        connection, let the batch loop refresh + replay) instead of an
        error — the client sees latency while the controller replaces
        the shard.  With a breaker board attached, an OPEN shard's
        frames become rejects WITHOUT touching the wire (fail fast;
        the half-open probe is the only traffic an open shard sees)."""
        board = self.breakers
        if board is not None and not board.allow(shard):
            raise _Rejected(sids, "breaker_open")
        try:
            conn = self._conn_for(shard)
            reqs = self._materialize(lines, conn)
            h = hedger if hedger is not None else self.hedge
            if hedgeable and h is not None:
                addr = self._addresses[shard]

                def on_backup_won(spare_conn):
                    # the still-draining primary must never be reused
                    # (one reader per line-protocol connection): the
                    # clean spare takes its slot
                    old = self._conns.pop(addr, None)
                    if old is not None:
                        old.close()
                    self._conns[addr] = spare_conn

                resps = h.request_many(
                    conn,
                    lambda: self._dial(addr),
                    reqs,
                    on_backup_won,
                    trace=trace,
                )
            else:
                resps = conn.request_many(reqs)
        except OSError:
            # transport failure feeds the breaker (a dead/wedged shard
            # opens its circuit after enough of these in the window)
            if board is not None:
                board.fail(shard)
            if self.membership is None:
                raise
            self._drop_conn(shard)
            raise _Rejected(sids, "conn") from None
        if board is not None:
            board.ok(shard)
        return resps

    def _read_frames(
        self, shard: int, sids: np.ndarray, lines, *, trace=None,
    ) -> List:
        """Route one shard's READ frames: a replica when the rotation
        picks one, the primary otherwise — and always the primary as
        the fallback when the replica declines (lagging/not-primary)
        or cannot be reached.  Pulls are idempotent, so the fallback
        replays the whole frame set."""
        addr, is_replica = self._read_target(shard)
        if not is_replica:
            return self._request_frames(
                shard, sids, lines, hedgeable=True, trace=trace
            )
        resps = None
        try:
            resps = self._replica_request(shard, addr, lines, trace)
        except OSError:
            self._drop_addr(addr)
        if resps is not None and not any(
            _is_follower_reject(r) for r in resps
        ):
            if self._c_replica_reads is not None:
                self._c_replica_reads.inc(len(resps))
            return resps
        if self._c_fallbacks is not None:
            self._c_fallbacks.inc()
        return self._request_frames(
            shard, sids, lines, hedgeable=True, trace=trace
        )

    def _replica_request(
        self, shard: int, addr: Tuple[str, int], lines, trace
    ) -> List:
        """One replica's frames — hedged, when a hedger is attached,
        against the PRIMARY: a straggling replica races the shard's
        write owner and the first answer wins (the budgeted
        elastic/hedging.py race, re-aimed across the chain)."""
        conn = self._conn_for_addr(addr)
        reqs = self._materialize(lines, conn)
        if self.hedge is None:
            return conn.request_many(reqs)
        primary = self._addresses[shard]

        def on_backup_won(spare_conn):
            # the spare dialed the primary; it takes the primary's
            # cache slot (the still-draining replica conn is dropped)
            old = self._conns.pop(primary, None)
            if old is not None:
                old.close()
            self._conns[primary] = spare_conn
            self._drop_addr(addr)

        return self.hedge.request_many(
            conn,
            lambda: self._dial(primary),
            reqs,
            on_backup_won,
            trace=trace,
        )

    def _pull_shard(
        self, shard: int, ids: np.ndarray, ctx=None
    ) -> np.ndarray:
        """One shard's reads, hot/cold split.  Ids the lease policy
        marks HOT (all of which already missed the cache) are read via
        the ``lease`` verb — an atomic read + grant that fills the
        cache — and the rest via plain ``pull``; both frame kinds go
        out in ONE pipelined ``request_many`` on the primary, so the
        hot tier never adds a wire round trip over the plain path.
        Pure-cold batches keep the full hedged/replica-routed read
        path.  A reject in either half replays the whole shard set —
        pulls and leases are both idempotent reads."""
        cache, policy = self.hotcache, self.lease_policy
        if cache is None or policy is None or not self._lease_supported:
            return self._pull_shard_wire(shard, ids, ctx)
        hot = np.asarray(policy.is_hot(ids), bool)
        if not hot.any():
            return self._pull_shard_wire(shard, ids, ctx)
        out = np.empty(
            (len(ids),) + self.value_shape, np.float32
        )
        try:
            try:
                hot_rows, cold_rows = self._lease_pull_shard(
                    shard, ids[hot], ids[~hot], ctx
                )
            except _LeaseUnsupported:
                # pre-hotcache server: downgrade to plain pulls for the
                # rest of this client's life (never re-probed)
                self._lease_supported = False
                return self._pull_shard_wire(shard, ids, ctx)
        except _Rejected as r:
            raise _Rejected(ids, r.reason) from None
        out[hot] = hot_rows
        if cold_rows is not None:
            out[~hot] = cold_rows
        return out

    def _observe_shard_rtt(self, shard: int, per: float,
                           frames: int) -> None:
        """Per-shard twin of the ``cluster_pull_rtt_seconds``
        observation: same value, extra ``shard=`` label, registered on
        first traffic to that shard."""
        if self._reg is None:
            return
        h = self._h_shard_rtt.get(shard)
        if h is None:
            h = self._reg.histogram(
                "cluster_shard_rtt_seconds", component="cluster",
                shard=str(shard), **self._labels,
            )
            self._h_shard_rtt[shard] = h
        for _ in range(frames):
            h.observe(per)

    def _lease_pull_shard(
        self,
        shard: int,
        hot_ids: np.ndarray,
        cold_ids: np.ndarray,
        ctx=None,
    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """``lease`` frames for ``hot_ids`` + ``pull`` frames for
        ``cold_ids``, pipelined in one request batch on the primary
        (one round trip); leased rows are installed in the cache at
        the current tick.  Returns ``(hot_rows, cold_rows-or-None)``;
        rejects surface as :class:`_Rejected` exactly like pulls."""
        prof = self._profiler
        hot_chunks = [
            hot_ids[i: i + self.chunk]
            for i in range(0, len(hot_ids), self.chunk)
        ]
        cold_chunks = [
            cold_ids[i: i + self.chunk]
            for i in range(0, len(cold_ids), self.chunk)
        ]
        tok, span_cm, _span_id = self._frame_trace(shard, "lease", ctx)
        all_ids = np.concatenate([hot_ids, cold_ids])
        hot_rows: List[np.ndarray] = []
        cold_rows: List[np.ndarray] = []
        rejected = False
        reject_reason = "reject"

        def build(conn) -> List:
            if conn.proto != "line":  # bin or shm: same frames
                enc = self._bin_enc()
                tlvs = self._bin_tlvs(tok)
                lease_tlvs = [
                    (binf.T_TTL, str(self._lease_ttl).encode())
                ] + tlvs
                return [
                    binf.encode_request(
                        binf.VERB_IDS["lease"], ids=c, enc=enc,
                        epoch=self._epoch, priority=self._priority,
                        tlvs=lease_tlvs,
                    )
                    for c in hot_chunks
                ] + [
                    binf.encode_request(
                        binf.VERB_IDS["pull"], ids=c, enc=enc,
                        epoch=self._epoch, priority=self._priority,
                        tlvs=tlvs,
                    )
                    for c in cold_chunks
                ]
            suffix = self._frame_suffix() + (
                " t=" + tok if tok is not None else ""
            )
            enc_tok = " text" if self.wire_format == "text" else " b64"
            return [
                "lease " + ",".join(str(int(i)) for i in c)
                + enc_tok + f" ttl={self._lease_ttl}" + suffix
                for c in hot_chunks
            ] + [
                "pull " + ",".join(str(int(i)) for i in c)
                + enc_tok + suffix
                for c in cold_chunks
            ]

        with span_cm:
            t0 = time.perf_counter()
            resps = self._request_frames(
                shard, all_ids, build, hedgeable=False
            )
            per = (time.perf_counter() - t0) / max(1, len(resps))
            for _ in resps:
                if self._h_rtt is not None:
                    self._h_rtt.observe(per)
                prof.observe("pull", "rtt", per)
            self._observe_shard_rtt(shard, per, len(resps))
            n_hot = len(hot_chunks)
            for i, (resp, c) in enumerate(zip(
                resps, hot_chunks + cold_chunks
            )):
                is_lease = i < n_hot
                resp = self._apply_response_options(resp)
                if _is_overloaded(resp):
                    if self.breakers is not None:
                        self.breakers.fail(shard)
                    raise OverloadedError(
                        f"{'lease' if is_lease else 'pull'} shard "
                        f"{shard}: {_describe(resp)}"
                    )
                if _is_reject(resp) and self.membership is not None:
                    rejected = True
                    reject_reason = _reject_reason(resp)
                    continue
                if is_lease and _is_bad_request(resp):
                    raise _LeaseUnsupported(_describe(resp))
                _check_ok(
                    resp,
                    f"{'lease' if is_lease else 'pull'} shard {shard}",
                )
                if isinstance(resp, binf.Frame) or not is_lease:
                    vals = self._parse_rows_any(
                        resp, c, shard,
                        "lease" if is_lease else "pull",
                    )
                else:
                    # text lease answer: ok n=<k> seq=<q> ttl=<r> <body>
                    parts = resp.split(" ", 4)
                    if len(parts) < 5:
                        raise RuntimeError(
                            f"shard {shard} lease answer malformed: "
                            f"{resp!r}"
                        )
                    with prof.timer("pull", "client_parse"):
                        vals = parse_rows(parts[4], self.value_shape)
                    if len(vals) != len(c):
                        raise RuntimeError(
                            f"shard {shard} answered {len(vals)} rows "
                            f"for {len(c)} ids"
                        )
                if is_lease:
                    self.hotcache.fill(c, vals)
                    self.leases_acquired += len(c)
                    hot_rows.append(vals)
                else:
                    cold_rows.append(vals)
        if rejected:
            raise _Rejected(all_ids, reject_reason)
        hot_out = np.concatenate(hot_rows) if hot_rows else np.empty(
            (0,) + self.value_shape, np.float32
        )
        cold_out = (
            np.concatenate(cold_rows) if cold_rows else None
        )
        return hot_out, cold_out

    def _bin_enc(self) -> int:
        """Row encoding for binary READ frames (pull/lease answers):
        exact fp32 unless the client opted into bf16 (half the row
        bytes, lossy).  ``q8`` is a PUSH-delta codec only — absolute
        values carry no residual to re-inject, so quantizing reads
        would be silent corruption (docs/compression.md)."""
        return (
            binf.ENC_BF16 if self.wire_format == "bf16"
            else binf.ENC_F32
        )

    def _bin_tlvs(self, tok: Optional[str], pid: Optional[str] = None):
        """The frame TLVs mirroring :meth:`_frame_suffix`'s trailing
        tokens (epoch and priority live in the fixed header)."""
        tlvs = []
        if tok is not None:
            tlvs.append((binf.T_TRACE, tok.encode()))
        if pid is not None:
            tlvs.append((binf.T_PID, pid.encode()))
        if self.hotcache is not None and self._sess is not None:
            tlvs.append((binf.T_SESS, self._sess.encode()))
        return tlvs

    def _parse_rows_any(self, resp, chunk, shard: int, what: str):
        """One response's rows, either framing, length-checked."""
        prof = self._profiler
        if isinstance(resp, binf.Frame):
            with prof.timer("pull", "client_parse"):
                vals = binf.rows_from_payload(
                    resp.payload, self.value_shape, resp.enc
                )
        else:
            _, _, body = resp.partition(" ")
            _, _, body = body.partition(" ")  # strip "n=<k>"
            with prof.timer("pull", "client_parse"):
                vals = parse_rows(body, self.value_shape)
        if len(vals) != len(chunk):
            raise RuntimeError(
                f"shard {shard} answered {len(vals)} rows for "
                f"{len(chunk)} ids ({what})"
            )
        return vals

    def _pull_shard_wire(
        self, shard: int, ids: np.ndarray, ctx=None
    ) -> np.ndarray:
        chunks = [
            ids[i: i + self.chunk] for i in range(0, len(ids), self.chunk)
        ]
        prof = self._profiler
        tok, span_cm, span_id = self._frame_trace(shard, "pull", ctx)
        trace = (
            (self._tracer, ctx.trace_id, span_id)
            if span_id is not None else None
        )
        rows = []
        rejected: List[np.ndarray] = []
        reject_reason = "reject"
        ser_cell = [0.0]

        def build(conn) -> List:
            """Requests for this connection's protocol — binary frames
            (raw i8 ids + fp32/bf16 rows, options as TLVs) on a
            negotiated connection, text lines otherwise."""
            t_ser = time.perf_counter()
            if conn.proto != "line":  # bin or shm: same frames
                enc = self._bin_enc()
                tlvs = self._bin_tlvs(tok)
                reqs = [
                    binf.encode_request(
                        binf.VERB_IDS["pull"], ids=c, enc=enc,
                        epoch=self._epoch, priority=self._priority,
                        tlvs=tlvs,
                    )
                    for c in chunks
                ]
            else:
                suffix = self._frame_suffix() + (
                    " t=" + tok if tok is not None else ""
                )
                reqs = [
                    "pull " + ",".join(str(int(i)) for i in c)
                    + (" text" if self.wire_format == "text" else " b64")
                    + suffix
                    for c in chunks
                ]
            ser_cell[0] = (
                (time.perf_counter() - t_ser) / max(1, len(reqs))
            )
            return reqs

        # the pull.shard<k> span covers the WHOLE per-shard round —
        # serialize, wire round trip, response parse — which makes it
        # the independent oracle the latency-budget phases (observed
        # separately below) must sum to (tests/test_profiler.py)
        with span_cm:
            t0 = time.perf_counter()
            resps = self._read_frames(shard, ids, build, trace=trace)
            # one observation per chunk frame: the pipelined per-frame
            # turnaround, amortised (total wall / frames); serialize
            # time was measured inside the builder, net of the dial
            per = (
                (time.perf_counter() - t0) / max(1, len(resps))
                - ser_cell[0]
            )
            for _ in resps:
                if self._h_rtt is not None:
                    self._h_rtt.observe(per)
                prof.observe("pull", "rtt", per)
                prof.observe("pull", "client_serialize", ser_cell[0])
            self._observe_shard_rtt(shard, per, len(resps))
            for resp, c in zip(resps, chunks):
                if self.hotcache is not None:
                    # piggybacked inv= tokens ride any response to a
                    # lease-capable session — strip and apply first
                    resp = self._apply_response_options(resp)
                if _is_overloaded(resp):
                    # typed shed: fail fast (count badput, never
                    # retry the storm); the breaker sees it as a
                    # failure signal on this shard
                    if self.breakers is not None:
                        self.breakers.fail(shard)
                    raise OverloadedError(
                        f"pull shard {shard}: {_describe(resp)}"
                    )
                if _is_reject(resp) and self.membership is not None:
                    rejected.append(c)
                    reject_reason = _reject_reason(resp)
                    continue
                _check_ok(resp, f"pull shard {shard}")
                rows.append(
                    self._parse_rows_any(resp, c, shard, "pull")
                )
        if rejected:
            # partial answers cannot scatter into the output without
            # per-chunk bookkeeping; pulls are idempotent, so replay
            # the shard's whole id set under the refreshed map
            raise _Rejected(ids, reject_reason)
        return np.concatenate(rows) if rows else np.empty(
            (0,) + self.value_shape, np.float32
        )

    def _push_shard(
        self,
        shard: int,
        ids: np.ndarray,
        deltas: np.ndarray,
        pid: Optional[str] = None,
        ctx=None,
        q_rows: Optional[np.ndarray] = None,
        q_scales: Optional[np.ndarray] = None,
    ) -> None:
        prof = self._profiler
        tok, span_cm, _span_id = self._frame_trace(shard, "push", ctx)
        chunks = [
            ids[i: i + self.chunk]
            for i in range(0, len(ids), self.chunk)
        ]
        ser_cell = [0.0]

        def build(conn) -> List:
            t_ser = time.perf_counter()
            if conn.proto != "line":  # bin or shm: same frames
                tlvs = self._bin_tlvs(tok, pid)
                if q_rows is not None and "q8" in conn.encs:
                    # the quantized push path: int8 rows + a T_SCALE
                    # TLV of the per-row f32 scales, per chunk.  The
                    # rows the shard will apply are bitwise the
                    # `deltas` (dq) rows — only the bytes differ.
                    reqs = []
                    saved = 0
                    for i in range(0, len(ids), self.chunk):
                        qc = np.ascontiguousarray(
                            q_rows[i: i + self.chunk]
                        )
                        sc = np.ascontiguousarray(
                            q_scales[i: i + self.chunk], "<f4"
                        )
                        reqs.append(binf.encode_request(
                            binf.VERB_IDS["push"],
                            ids=ids[i: i + self.chunk],
                            payload=qc.tobytes(),
                            enc=binf.ENC_Q8, epoch=self._epoch,
                            priority=self._priority,
                            tlvs=[(binf.T_SCALE, sc.tobytes())] + tlvs,
                        ))
                        saved += 3 * qc.size - sc.nbytes
                    if self._c_bytes_saved is not None and saved > 0:
                        self._c_bytes_saved.inc(saved)
                    ser_cell[0] = (
                        (time.perf_counter() - t_ser)
                        / max(1, len(reqs))
                    )
                    return reqs
                enc = (
                    binf.ENC_BF16 if self.wire_format == "bf16"
                    else binf.ENC_F32
                )
                reqs = [
                    binf.encode_request(
                        binf.VERB_IDS["push"],
                        ids=ids[i: i + self.chunk],
                        payload=binf.rows_to_payload(
                            deltas[i: i + self.chunk], enc
                        ),
                        enc=enc, epoch=self._epoch,
                        priority=self._priority, tlvs=tlvs,
                    )
                    for i in range(0, len(ids), self.chunk)
                ]
                if (
                    enc == binf.ENC_BF16
                    and self._c_bytes_saved is not None
                ):
                    # bf16 halves the row bytes vs f32
                    self._c_bytes_saved.inc(
                        2 * int(np.asarray(deltas).size)
                    )
            else:
                suffix = self._frame_suffix(pid) + (
                    " t=" + tok if tok is not None else ""
                )
                fmt = (
                    "text" if self.wire_format == "text" else "b64"
                )
                reqs = [
                    "push "
                    + ",".join(
                        str(int(x)) for x in ids[i: i + self.chunk]
                    )
                    + " "
                    + format_rows(deltas[i: i + self.chunk], fmt)
                    + suffix
                    for i in range(0, len(ids), self.chunk)
                ]
            ser_cell[0] = (
                (time.perf_counter() - t_ser) / max(1, len(reqs))
            )
            return reqs

        # like pull: the push.shard<k> span covers serialize + round
        # trip, the same window the push phases decompose
        with span_cm:
            t0 = time.perf_counter()
            # hedged only when the batch carries a pid: the shard's
            # (pid,id) dedupe window then absorbs the losing leg's
            # duplicate apply, the same way it absorbs ambiguous
            # retries — without a pid a raced push would double-apply
            resps = self._request_frames(
                shard, ids, build,
                hedgeable=(pid is not None and self.push_hedge is not None),
                hedger=self.push_hedge,
            )
            per = (
                (time.perf_counter() - t0) / max(1, len(resps))
                - ser_cell[0]
            )
            for _ in resps:
                prof.observe("push", "rtt", per)
                prof.observe("push", "client_serialize", ser_cell[0])
        rejected: List[np.ndarray] = []
        reject_reason = "reject"
        for resp, c_ids in zip(resps, chunks):
            if self.hotcache is not None:
                resp = self._apply_response_options(resp)
            if _is_overloaded(resp):
                if self.breakers is not None:
                    self.breakers.fail(shard)
                raise OverloadedError(
                    f"push shard {shard}: {_describe(resp)}"
                )
            if _is_reject(resp) and self.membership is not None:
                rejected.append(c_ids)
                reject_reason = _reject_reason(resp)
                continue
            _check_ok(resp, f"push shard {shard}")
        if rejected:
            raise _Rejected(np.concatenate(rejected), reject_reason)


__all__ = ["ClusterClient", "ShardConnection"]
