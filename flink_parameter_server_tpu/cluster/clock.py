"""Bounded-staleness (SSP) vector clock for the cluster workers.

The PS literature's consistency dial (MXNET-MPI, arXiv:1801.03855;
straggler study, arXiv:2308.15482) is one integer: how many iterations
may the fastest worker run AHEAD of the slowest before it must wait.

  ==========  =================================================
  ``bound``   semantics
  ==========  =================================================
  0           BSP — lockstep rounds; every worker's reads see
              every worker's previous-round writes
  k > 0       SSP — reads may miss at most ``k`` rounds of the
              stragglers' writes; fast workers block exactly at
              ``fastest − slowest > k``
  ``None``    fully asynchronous — never block (the reference's
              native hogwild mode)
  ==========  =================================================

Mechanics: each worker owns one monotonically increasing round counter
(``ticks completed``).  :meth:`wait_for_turn` blocks while advancing
would put the caller more than ``bound`` rounds ahead of the slowest
ACTIVE worker; :meth:`tick` completes a round and wakes the waiters; a
finished worker calls :meth:`deactivate` so its frozen counter stops
counting as "the slowest" (otherwise every stream end would deadlock
the survivors).  One condition variable covers the vector — rounds are
milliseconds-long (a network pull + a jitted step), so contention on
the clock is noise.

The live staleness (``fastest − slowest``) is the gauge the telemetry
plane scrapes (``cluster_staleness_steps{component=cluster}``) — the
mid-run observable that says whether a run is actually BSP-tight or
drifting to its bound.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional


class StalenessClock:
    """SSP vector clock over ``num_workers`` round counters."""

    def __init__(self, num_workers: int, bound: Optional[int] = 0):
        if num_workers < 1:
            raise ValueError(f"num_workers={num_workers}: must be >= 1")
        if bound is not None and bound < 0:
            raise ValueError(f"bound={bound}: must be >= 0 or None (async)")
        self.num_workers = int(num_workers)
        self.bound = None if bound is None else int(bound)
        self._clocks = [0] * self.num_workers
        self._active = [True] * self.num_workers
        self._cond = threading.Condition()
        # how many times each worker actually blocked at the bound —
        # the test/bench surface for "SSP is being enforced"
        self.block_counts = [0] * self.num_workers

    # -- the protocol ------------------------------------------------------
    def wait_for_turn(self, worker: int, timeout: Optional[float] = None) -> bool:
        """Block until worker may START its next round without exceeding
        the bound, i.e. while ``clock[worker] − min(active clocks) >
        bound``.  Returns False on timeout (deadlock guard for tests),
        True when clear.  ``bound=None`` never blocks.

        The gate bounds the lead at round START: a worker that was
        allowed to start still completes that round, so the momentary
        completed-round lead (and the staleness gauge) tops out at
        ``bound + 1`` right before the next wait blocks."""
        if self.bound is None:
            return True
        with self._cond:
            blocked = False

            def clear() -> bool:
                return self._clear_locked(worker)

            if not clear():
                blocked = True
                self.block_counts[worker] += 1
            ok = self._cond.wait_for(clear, timeout=timeout)
            return ok or not blocked

    def _clear_locked(self, worker: int) -> bool:
        """Gate predicate, evaluated under ``self._cond``.  Subclasses
        (``adaptive.bounds.AdaptiveClock``) override this to apply
        per-worker allowances instead of the single global bound."""
        return self._clocks[worker] - self._min_active_locked() <= self.bound

    def tick(self, worker: int) -> int:
        """Worker completed a round (its pushes are durable at the
        shards); returns its new round count and wakes any waiter."""
        with self._cond:
            self._clocks[worker] += 1
            self._cond.notify_all()
            return self._clocks[worker]

    def deactivate(self, worker: int) -> None:
        """Worker finished its stream: exclude its (frozen) counter from
        the slowest-active computation so survivors can proceed."""
        with self._cond:
            self._active[worker] = False
            self._cond.notify_all()

    # -- reads -------------------------------------------------------------
    def _min_active_locked(self) -> int:
        act = [c for c, a in zip(self._clocks, self._active) if a]
        return min(act) if act else max(self._clocks, default=0)

    def clocks(self) -> List[int]:
        with self._cond:
            return list(self._clocks)

    def staleness(self) -> int:
        """``fastest − slowest`` over ACTIVE workers — the live gauge."""
        with self._cond:
            act = [c for c, a in zip(self._clocks, self._active) if a]
            if not act:
                return 0
            return max(act) - min(act)

    def snapshot(self) -> Dict[str, object]:
        with self._cond:
            act = [c for c, a in zip(self._clocks, self._active) if a]
            return {
                "clocks": list(self._clocks),
                "active": list(self._active),
                "bound": self.bound,
                "staleness": (max(act) - min(act)) if act else 0,
                "block_counts": list(self.block_counts),
            }


__all__ = ["StalenessClock"]
