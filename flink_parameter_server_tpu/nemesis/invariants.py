"""Cluster invariants — what must hold no matter what the network did.

Each checker returns a :class:`Verdict` (name, ok, detail) so the
runner can report ALL violations, not just the first: a Jepsen-style
post-mortem starts from the full verdict table.  Checkers are split
into live probes (sampled while the scenario runs — staleness, serving
errors) and post-hoc audits (run after teardown — ledger, parity,
thread leaks, lock order).

The invariants, and why each is the right oracle:

  * **exactly-once ledger** — every unique delta row a worker client
    counted as acked (``ClusterClient.rows_pushed``) was applied on
    exactly one shard (``ParamShard.rows_applied``, summed over every
    shard EVER live, replacements included).  Retries after torn
    frames/lost acks are deduplicated by the ``(pid, id)`` window, so
    a fault can add latency but never a lost or double-counted update.
  * **final-table parity** — the faulted run's assembled table is
    allclose-equal (fp32) to a fault-free oracle trained on the SAME
    stream.  This is the end-to-end consistency oracle: anything that
    silently mis-routed, re-ordered (under BSP), dropped or corrupted
    an update shows up here even when every counter balances.
  * **SSP staleness bound** — the live ``fastest − slowest`` spread
    never exceeds ``bound + 1`` (the clock gates round STARTS, so the
    momentary completed-round lead legally tops out one past the
    bound — cluster/clock.py).  For BSP (bound 0) this plus parity is
    the read-your-last-round guarantee: the barrier admitted no round
    whose reads missed the previous round's writes.
  * **serving error budget** — a reader thread issuing pulls through
    its own membership client across the whole scenario sees at most
    ``budget`` errors (default 0: faults are latency, never failures).
  * **tier residency** — on tiered scenarios (tierstore/), every live
    sample of every tiered store shows ``resident ≤ hot capacity``:
    demotion pressure, spills and recovery replays may move rows
    between tiers but never grow the bounded hot set.
  * **no leaked threads** — after teardown every thread the PS stack
    spawned (shards, pumps, workers, shippers, controllers) is gone;
    a fault that orphans a handler fails here, not three suites later.
  * **no lock inversions** — the scenario runs under the
    :mod:`~..telemetry.lockwitness` capture and the witnessed
    acquisition order stays cycle-free (the runtime half of fpsanalyze
    L001).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

# thread-name prefixes owned by this package (utils/net.py names
# handlers "<server>-conn-*", the drivers name their workers, the
# proxy names its pumps): the leak check is scoped to OUR threads so a
# persistent jax/orbax pool never false-positives it
_OWNED_THREAD_PREFIXES = (
    "shard-", "nemesis-", "cluster-", "elastic-", "repl-", "serving",
    "chaos", "line-server", "wal-", "hb-", "ship-", "telemetry",
    "hotcache-", "loadgen-", "adaptive", "timeline-",
)


@dataclasses.dataclass
class Verdict:
    """One invariant's outcome; ``detail`` carries the evidence either
    way (a passing verdict still says what it measured)."""

    name: str
    ok: bool
    detail: str

    def as_dict(self) -> dict:
        return {"name": self.name, "ok": self.ok, "detail": self.detail}


def check_no_errors(errors: Sequence[str]) -> Verdict:
    return Verdict(
        "no_errors",
        not errors,
        "clean run" if not errors else "; ".join(errors[:4]),
    )


def check_exactly_once(acked_rows: int, applied_rows: int) -> Verdict:
    """The ledger audit: client-acked unique delta rows == shard-applied
    delta rows, summed over every client and every shard ever live."""
    ok = acked_rows == applied_rows and acked_rows > 0
    return Verdict(
        "exactly_once_ledger", ok,
        f"acked={acked_rows} applied={applied_rows}"
        + ("" if ok else " — lost or duplicated updates"),
    )


def check_parity(
    values: np.ndarray,
    oracle: np.ndarray,
    *,
    rtol: float = 1e-4,
    atol: float = 1e-6,
) -> Verdict:
    """Final table vs the fault-free oracle on the same stream (the
    repo-wide BSP parity tolerance, tests/test_cluster.py)."""
    if values.shape != oracle.shape:
        return Verdict(
            "final_table_parity", False,
            f"shape {values.shape} vs oracle {oracle.shape}",
        )
    err = np.abs(values - oracle)
    tol = atol + rtol * np.abs(oracle)
    bad = int((err > tol).sum())
    return Verdict(
        "final_table_parity", bad == 0,
        f"max_abs_err={float(err.max()):.3e} mismatched_elems={bad}",
    )


def check_parity_bitwise(
    values: np.ndarray, oracle: np.ndarray
) -> Verdict:
    """Final table vs the oracle, BIT FOR BIT — the parity mode for
    workloads whose update combine is structurally deterministic
    (workloads/pa.py: the on-device dense combine leaves exactly one
    fp32 row per id per round on both arms).  Same verdict name as the
    allclose mode so corpus expectations stay uniform; the detail says
    which bar was applied."""
    if values.shape != oracle.shape:
        return Verdict(
            "final_table_parity", False,
            f"shape {values.shape} vs oracle {oracle.shape}",
        )
    a = np.asarray(values, np.float32)
    b = np.asarray(oracle, np.float32)
    mismatched = int((a.view(np.uint32) != b.view(np.uint32)).sum())
    return Verdict(
        "final_table_parity", mismatched == 0,
        f"bitwise: mismatched_words={mismatched} of {a.size}"
        + ("" if mismatched == 0 else
           f" max_abs_err={float(np.abs(a - b).max()):.3e}"),
    )


def check_count_parity(
    values: np.ndarray, oracle: np.ndarray
) -> Verdict:
    """Integer-exact parity for increment workloads (sketches): every
    delivered counter must be an integer and EQUAL the ground-truth
    count — no float tolerance.  Exactness is legitimate because
    integer increments are exact in fp32 below 2^24 and integer adds
    commute, so no schedule (retries, promotion replay, resharding,
    multi-worker interleaving) may change a single count."""
    if values.shape != oracle.shape:
        return Verdict(
            "final_table_parity", False,
            f"shape {values.shape} vs oracle {oracle.shape}",
        )
    v = np.asarray(values, np.float64)
    nonint = int((v != np.round(v)).sum())
    diff = int((v != np.asarray(oracle, np.float64)).sum())
    total = int(v.sum())
    ok = nonint == 0 and diff == 0
    return Verdict(
        "final_table_parity", ok,
        f"integer-exact: total_count={total} "
        f"mismatched_cells={diff} non_integer_cells={nonint}",
    )


def check_staleness(
    samples: Sequence[int], bound: Optional[int]
) -> Verdict:
    """Sampled live spread ≤ bound + 1 (see module docstring); async
    (bound None) always passes — there is no bound to exceed."""
    worst = max(samples) if samples else 0
    if bound is None:
        return Verdict(
            "ssp_staleness_bound", True,
            f"async clock, worst observed spread {worst}",
        )
    ok = worst <= bound + 1
    return Verdict(
        "ssp_staleness_bound", ok,
        f"worst spread {worst} vs bound {bound} (+1 round in flight)",
    )


def check_adaptive_bound(
    samples: Sequence[Sequence[int]],
    bound: Optional[int],
    ceiling: Optional[int],
) -> Verdict:
    """The adaptive-bounds safety envelope (adaptive/bounds.py): every
    live-sampled per-worker EFFECTIVE bound stays within
    ``[bound, ceiling]`` — widening never exceeds the declared ceiling
    and narrowing never undercuts the correctness bound.  Vacuous
    passes are rejected the way lease_staleness rejects them: at least
    one sample must have been taken from a live adaptive clock,
    otherwise the scenario never exercised the invariant it claims to
    prove.  Async (bound None) has no allowances to audit and passes
    on the sampler having seen the clock."""
    n = len(samples)
    if bound is None:
        return Verdict(
            "adaptive_bound_envelope", n > 0,
            f"async clock, {n} sample(s)"
            + ("" if n else " — never sampled (vacuous)"),
        )
    low = min(
        (min(row) for row in samples if len(row)), default=bound
    )
    high = max(
        (max(row) for row in samples if len(row)), default=bound
    )
    ok = n > 0 and low >= bound and high <= ceiling
    return Verdict(
        "adaptive_bound_envelope", ok,
        f"samples={n} effective bounds in [{low}, {high}] vs "
        f"declared [{bound}, {ceiling}]"
        + ("" if high <= ceiling else " — CEILING VIOLATED")
        + ("" if low >= bound else " — CORRECTNESS BOUND VIOLATED")
        + ("" if n else " — never sampled (vacuous)"),
    )


def check_serving_budget(
    served: int, errors: int, *, budget: int = 0
) -> Verdict:
    ok = errors <= budget and served > 0
    return Verdict(
        "serving_error_budget", ok,
        f"served={served} errors={errors} budget={budget}",
    )


def check_lease_staleness(
    cache_stats: dict, bound: int
) -> Verdict:
    """The hot-key cache's staleness contract under fault
    (docs/hotcache.md): every row the client-edge cache SERVED was at
    most ``bound`` ticks old — through partitions, lost invalidations
    and shard restarts, because the bound is enforced client-locally.
    Vacuous passes are rejected: the cache must actually have served
    (``hits > 0``), otherwise the scenario never exercised the tier it
    claims to prove."""
    hits = int(cache_stats.get("hits", 0))
    worst = int(cache_stats.get("max_served_age", 0))
    revoked = int(cache_stats.get("revocations", 0))
    stale = int(cache_stats.get("stale_rejects", 0))
    ok = hits > 0 and worst <= bound
    return Verdict(
        "lease_staleness", ok,
        f"cache_hits={hits} worst_served_age={worst} bound={bound} "
        f"revocations={revoked} stale_rejects={stale}"
        + ("" if worst <= bound else " — BOUND VIOLATED")
        + ("" if hits else " — cache never served (vacuous)"),
    )


def check_tier_residency(samples: Sequence[dict]) -> Verdict:
    """The two-tier store's bounded-residency contract (tierstore/,
    docs/tierstore.md): at EVERY live sample, every tiered shard's
    resident (hot) row count stays within its configured hot capacity
    — through demotion storms, kills, promotions and WAL replays,
    because oversized admissions spill write-through to the cold slab
    instead of growing the hot tier.  Each sample is
    ``{label: (resident_rows, hot_capacity_rows)}`` as collected by
    :class:`TierResidencySampler`.  Vacuous passes are rejected: at
    least one sample from at least one live tiered store must have
    been taken, otherwise the scenario never exercised the tier it
    claims to prove."""
    n = 0
    worst_over = 0
    worst_label = ""
    peak = 0
    cap_seen = 0
    for sample in samples:
        for label, (resident, cap) in sample.items():
            n += 1
            peak = max(peak, int(resident))
            cap_seen = max(cap_seen, int(cap))
            over = int(resident) - int(cap)
            if over > worst_over:
                worst_over = over
                worst_label = str(label)
    ok = n > 0 and worst_over <= 0
    return Verdict(
        "tier_residency", ok,
        f"samples={n} peak_resident={peak} hot_capacity={cap_seen}"
        + ("" if worst_over <= 0 else
           f" — CAPACITY EXCEEDED by {worst_over} rows on {worst_label}")
        + ("" if n else " — never sampled (vacuous)"),
    )


def check_lock_inversions(inversions) -> Verdict:
    n = len(inversions)
    return Verdict(
        "no_lock_inversions", n == 0,
        "witnessed order is cycle-free" if n == 0
        else f"{n} inversion(s): {inversions[0]}",
    )


class ThreadLedger:
    """Before/after thread accounting for the leak invariant.

    Snapshot before the topology is built; after teardown,
    :meth:`check` polls (teardown joins run with timeouts) until every
    package-owned thread born since the snapshot is gone, or the grace
    window expires — the survivors are the leak."""

    def __init__(self):
        self._before = {t.ident for t in threading.enumerate()}

    def _leaked(self) -> List[str]:
        return sorted(
            t.name for t in threading.enumerate()
            if t.ident not in self._before and t.is_alive()
            and t is not threading.current_thread()
            and t.name.startswith(_OWNED_THREAD_PREFIXES)
        )

    def check(self, *, grace_s: float = 5.0) -> Verdict:
        deadline = time.monotonic() + grace_s
        leaked = self._leaked()
        while leaked and time.monotonic() < deadline:
            time.sleep(0.05)
            leaked = self._leaked()
        return Verdict(
            "no_leaked_threads", not leaked,
            "all package threads joined" if not leaked
            else f"leaked: {leaked[:6]}",
        )


class AdaptiveBoundSampler:
    """Polls the driver clock's per-worker effective bounds while a
    scenario runs (same re-read-every-tick discipline as
    :class:`StalenessSampler` — the driver swaps in a fresh clock at
    run start).  Only adaptive clocks yield samples; a stock clock
    leaves ``samples`` empty and :func:`check_adaptive_bound` then
    rejects the run as vacuous."""

    def __init__(self, driver, interval_s: float = 0.002):
        self._driver = driver
        self._interval = float(interval_s)
        self.samples: List[List[int]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def __enter__(self) -> "AdaptiveBoundSampler":
        self._thread = threading.Thread(
            target=self._loop, name="nemesis-adaptive-sampler",
            daemon=True,
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            clock = self._driver.clock
            bounds = getattr(clock, "effective_bounds", None)
            if bounds is not None:
                try:
                    self.samples.append(list(bounds()))
                except Exception:  # clock mid-swap: skip the tick
                    pass

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class TierResidencySampler:
    """Polls every live tiered store's ``(resident, capacity)`` pair
    while a scenario runs, through the process-wide tiers snapshot
    registry (tierstore/metrics.py) — which is what covers chain
    FOLLOWERS too, not just the shards the driver lists.  A store
    mid-crash/restart yields no entry for that tick (its stats
    callable answers ``None``); a non-tiered scenario leaves
    ``samples`` empty and :func:`check_tier_residency` then rejects
    the run as vacuous."""

    def __init__(self, interval_s: float = 0.005):
        self._interval = float(interval_s)
        self.samples: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def __enter__(self) -> "TierResidencySampler":
        self._thread = threading.Thread(
            target=self._loop, name="nemesis-tier-sampler", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        from ..tierstore.metrics import tiers_snapshot

        while not self._stop.wait(self._interval):
            snap = tiers_snapshot()
            if not snap:
                continue
            tick = {}
            for label, st in snap.items():
                try:
                    tick[label] = (
                        int(st["resident_rows"]),
                        int(st["hot_capacity_rows"]),
                    )
                except (KeyError, TypeError, ValueError):
                    continue
            if tick:
                self.samples.append(tick)

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class StalenessSampler:
    """Polls ``driver.clock.staleness()`` on its own thread while a
    scenario runs (the driver swaps in a fresh clock at run start, so
    the sampler re-reads the attribute every tick)."""

    def __init__(self, driver, interval_s: float = 0.002):
        self._driver = driver
        self._interval = float(interval_s)
        self.samples: List[int] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def __enter__(self) -> "StalenessSampler":
        self._thread = threading.Thread(
            target=self._loop, name="nemesis-staleness-sampler", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            clock = self._driver.clock
            if clock is not None:
                try:
                    self.samples.append(int(clock.staleness()))
                except Exception:  # clock mid-swap: skip the tick
                    pass

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


__all__ = [
    "AdaptiveBoundSampler",
    "StalenessSampler",
    "ThreadLedger",
    "TierResidencySampler",
    "Verdict",
    "check_adaptive_bound",
    "check_count_parity",
    "check_exactly_once",
    "check_lease_staleness",
    "check_lock_inversions",
    "check_no_errors",
    "check_parity",
    "check_parity_bitwise",
    "check_serving_budget",
    "check_staleness",
    "check_tier_residency",
]
