"""nemesis/ — network-level fault injection + cluster invariant checking.

A Jepsen-lite for the parameter-server cluster (docs/resilience.md
"Fault-model matrix"): every robustness claim the stack makes —
exactly-once updates across retries, parity with a fault-free run,
SSP staleness bounds, sub-second failover — becomes a *checked
invariant under composed network faults* instead of an anecdote.

  * :mod:`.proxy` — :class:`ChaosProxy`, a seeded byte-level TCP chaos
    proxy fronting any ``LineServer`` (shard, serving, repl leg):
    partitions (one-way and two-way), delay/jitter, bandwidth drip,
    frame duplication/reorder, mid-frame truncation + RST, half-open
    accepts;
  * :mod:`.scenarios` — the scenario DSL: network faults composed with
    cluster operations (kill-primary-under-partition,
    scale-out-during-drip, promote-while-client-partitioned, straggler
    storms), serializable to a canonical JSON schedule;
  * :mod:`.invariants` — the checkers: exactly-once ledger audit,
    final-table parity vs a fault-free oracle, SSP staleness bound,
    serving error budget, zero leaked threads, zero lock inversions;
  * :mod:`.runner` — proxied cluster drivers (every client↔shard byte
    crosses the mesh), the scenario executor, a randomized scenario
    search whose failures are reproducible from ``(seed, schedule)``,
    a shrinker that minimizes failing schedules, and the committed
    regression corpus (``nemesis/corpus/``) replayed in tier-1.
"""
from .invariants import Verdict
from .proxy import ChaosProxy, ProxiedServer
from .runner import (
    NemesisElasticDriver,
    NemesisReplicatedDriver,
    ScenarioReport,
    load_corpus,
    replay_corpus,
    run_scenario,
    search_scenarios,
    shrink,
)
from .scenarios import BUILTIN_SCENARIOS, NemesisOp, Scenario

__all__ = [
    "BUILTIN_SCENARIOS",
    "ChaosProxy",
    "NemesisElasticDriver",
    "NemesisOp",
    "NemesisReplicatedDriver",
    "ProxiedServer",
    "Scenario",
    "ScenarioReport",
    "Verdict",
    "load_corpus",
    "replay_corpus",
    "run_scenario",
    "search_scenarios",
    "shrink",
]
