"""ChaosProxy — a seeded byte-level TCP fault-injection proxy.

Every front end in this repo speaks the newline-framed line protocol
over TCP (``utils/net.LineServer``), and every in-process fault we
could inject before this module lived ABOVE the socket: chaos hooks on
the training thread, a flaky producer, replication-stream drops.  The
network between a client and a shard — the layer the PS literature
says dominates production failures (stragglers and partial partitions,
arXiv:2308.15482) — was never exercised.  This proxy is that layer
made hostile on demand.

It fronts any backend ``(host, port)``: clients dial the proxy, the
proxy dials the backend, and two pump threads relay bytes per
connection, reassembling newline frames so faults can be injected at
frame *and* byte granularity.  Fault classes (docs/resilience.md
fault-model matrix):

  =============  ========================================================
  fault          wire effect
  =============  ========================================================
  partition      bytes in the affected direction(s) are HELD (the pump
                 stops reading, TCP backpressure builds) until healed —
                 one-way (``c2s`` requests blackholed, ``s2c`` responses
                 blackholed — the asymmetric split) or ``both``;
                 optionally self-healing after ``duration_s``
  delay          per-frame sleep of ``ms`` + seeded uniform jitter —
                 the slow-shard straggler
  drip           bandwidth cap: frames trickle out in small slices at
                 ``bytes_per_sec``
  dup            the next complete frame is forwarded TWICE (a broken
                 middlebox; TCP itself never delivers this)
  reorder        the next complete frame is held and forwarded AFTER
                 its successor (ditto — violates TCP ordering)
  truncate_rst   the next complete frame is cut mid-frame (``keep_frac``
                 of its bytes, never the whole frame) and BOTH legs are
                 aborted with RST — the peer-died-mid-payload case
  half_open      the next ``count`` accepted connections are never
                 bridged to the backend: the dial succeeds, every read
                 hangs until the client's own deadline
  =============  ========================================================

Determinism: jitter draws come from one seeded generator, one-shot
faults key on frame arrival order, and partitions/windows are armed by
scenario ops at training-round boundaries (``nemesis/scenarios.py``) —
a scenario's faults replay from its ``(seed, schedule)`` pair.

Injected faults are counted per class into
``nemesis_faults_injected_total{kind=}`` (``component=nemesis``) and
mirrored in :attr:`ChaosProxy.faults` for the artifact roll-up.

:class:`ProxiedServer` is the mesh's splice point: it wraps a running
``ShardServer`` so ``.host``/``.port`` advertise the PROXY while
lifecycle calls reach the real server — the elastic drivers publish
whatever ``(srv.host, srv.port)`` says, so a driver built from proxied
servers routes every client, migration, and heartbeat byte through the
mesh without any cluster-side changes.
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..utils import frames as binframes
from ..utils.net import LineServer

# struct linger {onoff=1, linger=0}: close() becomes RST, not FIN —
# the abrupt peer death (same idiom as resilience/chaos.py)
_LINGER_RST = b"\x01\x00\x00\x00\x00\x00\x00\x00"

DIRECTIONS = ("c2s", "s2c")
_ONE_SHOT_KINDS = ("dup", "reorder", "truncate_rst")


class _Aborted(Exception):
    """Internal: a truncate_rst fault tore this connection down."""


class _FaultEngine:
    """Per-proxy fault state shared by every connection's pumps.

    Partitions are direction gates (``threading.Event`` cleared =
    held); delay/drip are windowed per direction; one-shot faults queue
    per direction and fire on the next complete frame anywhere on the
    link (frame ordinals are link-wide, which is what makes a schedule
    deterministic across reconnects).
    """

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._rng = np.random.default_rng(seed)
        self._clear = {d: threading.Event() for d in DIRECTIONS}
        for ev in self._clear.values():
            ev.set()
        self._delay: Dict[str, Optional[tuple]] = {d: None for d in DIRECTIONS}
        self._drip: Dict[str, Optional[float]] = {d: None for d in DIRECTIONS}
        self._one_shot: Dict[str, List[dict]] = {d: [] for d in DIRECTIONS}
        self._half_open = 0
        self.frames = {d: 0 for d in DIRECTIONS}

    def _dirs(self, mode: str) -> tuple:
        if mode == "both":
            return DIRECTIONS
        if mode not in DIRECTIONS:
            raise ValueError(f"direction {mode!r}: 'c2s' | 's2c' | 'both'")
        return (mode,)

    # -- windowed faults ---------------------------------------------------
    def hold(self, mode: str) -> None:
        for d in self._dirs(mode):
            self._clear[d].clear()

    def release_all(self) -> None:
        for ev in self._clear.values():
            ev.set()

    def partitioned(self) -> bool:
        return any(not ev.is_set() for ev in self._clear.values())

    def wait_clear(self, direction: str, stop: threading.Event) -> None:
        ev = self._clear[direction]
        while not ev.is_set() and not stop.is_set():
            ev.wait(0.02)

    def set_delay(self, ms: float, jitter_ms: float, mode: str) -> None:
        for d in self._dirs(mode):
            self._delay[d] = (float(ms), float(jitter_ms))

    def clear_delay(self) -> None:
        for d in DIRECTIONS:
            self._delay[d] = None

    def set_drip(self, bytes_per_sec: float, mode: str) -> None:
        if bytes_per_sec <= 0:
            raise ValueError(f"bytes_per_sec={bytes_per_sec}: must be > 0")
        for d in self._dirs(mode):
            self._drip[d] = float(bytes_per_sec)

    def clear_drip(self) -> None:
        for d in DIRECTIONS:
            self._drip[d] = None

    def drip_rate(self, direction: str) -> Optional[float]:
        return self._drip[direction]

    def delay_s(self, direction: str) -> float:
        """The (seeded) sleep for one frame in ``direction`` — 0.0 when
        no delay window is active."""
        d = self._delay[direction]
        if d is None:
            return 0.0
        ms, jitter = d
        with self._lock:
            j = float(self._rng.uniform(0.0, jitter)) if jitter > 0 else 0.0
        return (ms + j) / 1e3

    # -- one-shot faults ---------------------------------------------------
    def inject_once(
        self, kind: str, direction: str, *, keep_frac: float = 0.35,
        count: int = 1, cut: str = "frame",
    ) -> None:
        """``cut`` aims a ``truncate_rst`` inside a specific region of
        a BINARY frame: ``"frame"`` (anywhere, ``keep_frac`` of the
        bytes — the line-protocol behaviour too), ``"header"``
        (strictly inside the 24-byte fixed header — the peer dies
        before the length prefix completes), ``"payload"`` (past the
        header, inside the TLV/id/row bytes — a torn payload under an
        intact header).  Line frames fall back to the frac cut."""
        if kind not in _ONE_SHOT_KINDS:
            raise ValueError(f"kind {kind!r}: one of {_ONE_SHOT_KINDS}")
        if direction not in DIRECTIONS:
            raise ValueError(f"direction {direction!r}: 'c2s' | 's2c'")
        if not 0.0 < keep_frac < 1.0:
            raise ValueError(f"keep_frac={keep_frac}: must be in (0, 1)")
        if cut not in ("frame", "header", "payload"):
            raise ValueError(
                f"cut={cut!r}: 'frame' | 'header' | 'payload'"
            )
        with self._lock:
            for _ in range(int(count)):
                self._one_shot[direction].append(
                    {"kind": kind, "keep_frac": float(keep_frac),
                     "cut": cut}
                )

    def take_one_shot(self, direction: str) -> Optional[dict]:
        with self._lock:
            self.frames[direction] += 1
            if self._one_shot[direction]:
                return self._one_shot[direction].pop(0)
        return None

    def arm_half_open(self, count: int) -> None:
        with self._lock:
            self._half_open += int(count)

    def take_half_open(self) -> bool:
        with self._lock:
            if self._half_open > 0:
                self._half_open -= 1
                return True
        return False


class ChaosProxy(LineServer):
    """The fault-injecting TCP relay in front of one backend.

    ``LineServer`` provides the accept loop, connection tracking and
    the shutdown-first stop discipline; :meth:`handle_connection` is
    overridden to bridge instead of respond.  One proxy = one link
    (one shard's front door); a mesh is a dict of them
    (``nemesis/runner.py``).
    """

    def __init__(
        self,
        backend_host: str,
        backend_port: int,
        *,
        host: str = "127.0.0.1",
        name: str = "nemesis-proxy",
        seed: int = 0,
        connect_timeout: float = 5.0,
        registry=None,
    ):
        # registry=False on the base: the relay must not double-count
        # the link's bytes into the server-role wire ledger (the real
        # backend already counts them)
        super().__init__(host, 0, name=name, registry=False)
        self.backend_host = backend_host
        self.backend_port = int(backend_port)
        self.seed = int(seed)
        self.connect_timeout = float(connect_timeout)
        self.engine = _FaultEngine(seed)
        self.faults: Dict[str, int] = {}
        self._faults_lock = threading.Lock()
        self._upstreams: List[socket.socket] = []
        self._up_lock = threading.Lock()
        self._heal_timers: List[threading.Timer] = []
        self._registry = registry
        self._fault_counters: Dict[str, object] = {}
        # shm hellos refused at the splice point (_relay_frame): each
        # one is a client downgraded to binary TCP through this link
        self.shm_downgrades = 0

    # -- fault accounting --------------------------------------------------
    def _count_fault(self, kind: str, n: int = 1) -> None:
        with self._faults_lock:
            self.faults[kind] = self.faults.get(kind, 0) + n
        if self._registry is False:
            return
        try:
            c = self._fault_counters.get(kind)
            if c is None:
                from ..telemetry.registry import get_registry

                reg = (
                    self._registry if self._registry is not None
                    else get_registry()
                )
                c = reg.counter(
                    "nemesis_faults_injected_total", component="nemesis",
                    kind=kind,
                )
                self._fault_counters[kind] = c
            c.inc(n)
        except Exception:  # accounting must never fail the relay
            self._registry = False

    # -- the imperative fault surface (scenario ops call these) ------------
    def partition(
        self, mode: str = "both", *, duration_s: Optional[float] = None
    ) -> None:
        """Hold bytes in the given direction(s) until :meth:`heal` (or
        after ``duration_s``, self-healing — the op thread is free to
        run cluster operations INSIDE the partition window)."""
        self.engine.hold(mode)
        self._count_fault(f"partition_{mode}")
        if duration_s is not None:
            t = threading.Timer(float(duration_s), self.heal)
            t.daemon = True
            self._heal_timers.append(t)
            t.start()

    def heal(self) -> None:
        self.engine.release_all()

    def set_delay(
        self, ms: float, jitter_ms: float = 0.0, mode: str = "both"
    ) -> None:
        self.engine.set_delay(ms, jitter_ms, mode)
        self._count_fault("delay")

    def clear_delay(self) -> None:
        self.engine.clear_delay()

    def set_drip(self, bytes_per_sec: float, mode: str = "both") -> None:
        self.engine.set_drip(bytes_per_sec, mode)
        self._count_fault("drip")

    def clear_drip(self) -> None:
        self.engine.clear_drip()

    def inject_once(
        self, kind: str, direction: str = "s2c", *,
        keep_frac: float = 0.35, count: int = 1, cut: str = "frame",
    ) -> None:
        self.engine.inject_once(
            kind, direction, keep_frac=keep_frac, count=count, cut=cut
        )

    def half_open(self, count: int = 1) -> None:
        self.engine.arm_half_open(count)

    # -- lifecycle ---------------------------------------------------------
    def stop(self) -> None:
        for t in self._heal_timers:
            t.cancel()
        self._heal_timers = []
        self.engine.release_all()  # unblock pumps held at a partition
        with self._up_lock:
            ups = list(self._upstreams)
            self._upstreams = []
        for s in ups:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        super().stop()

    # -- the relay ---------------------------------------------------------
    def handle_connection(self, conn: socket.socket) -> None:
        if self.engine.take_half_open():
            self._count_fault("half_open")
            # accepted but never bridged: swallow requests, answer
            # nothing — the client's read deadline is its only way out
            conn.settimeout(0.1)
            while not self._stop.is_set():
                try:
                    if not conn.recv(1 << 12):
                        return
                except socket.timeout:
                    continue
                except OSError:
                    return
            return
        try:
            up = socket.create_connection(
                (self.backend_host, self.backend_port),
                timeout=self.connect_timeout,
            )
        except OSError:
            return  # backend down: client sees the dead link
        try:
            up.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        with self._up_lock:
            self._upstreams.append(up)
        t = threading.Thread(
            target=self._pump_safe, args=(up, conn, "s2c"),
            name=f"{self.name}-s2c", daemon=True,
        )
        with self._conns_lock:
            self._handlers.append(t)  # joined by LineServer.stop()
        t.start()
        try:
            self._pump(conn, up, "c2s")
        finally:
            for s in (up, conn):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            try:
                up.close()
            except OSError:
                pass
            with self._up_lock:
                if up in self._upstreams:
                    self._upstreams.remove(up)
            t.join(timeout=5)

    def _pump_safe(self, src, dst, direction: str) -> None:
        try:
            self._pump(src, dst, direction)
        except OSError:
            pass

    @staticmethod
    def _split_frames(buf: bytes):
        """``(complete_frames, tail)`` — the link-level frame grammar
        both protocols share: a chunk opening with the binary magic is
        a length-prefixed frame (utils/frames.py; held until all its
        bytes arrive — binary frames have no newline to wait for, and
        may legitimately CONTAIN 0x0A bytes), anything else is a
        newline line.  Byte-for-byte preserving in order, so every
        fault class composes over either framing."""
        frames: List[bytes] = []
        while True:
            if binframes.peek_is_binary(buf):
                total = binframes.frame_length(buf)
                if total is None or len(buf) < total:
                    return frames, buf
                frames.append(buf[:total])
                buf = buf[total:]
            else:
                i = buf.find(b"\n")
                if i < 0:
                    return frames, buf
                frames.append(buf[: i + 1])
                buf = buf[i + 1:]

    def _pump(self, src, dst, direction: str) -> None:
        """Relay ``src → dst``, one complete frame at a time — newline
        lines or length-prefixed binary frames (partial tails are held
        until complete, so frame faults see whole frames; the tail is
        flushed raw on EOF)."""
        eng = self.engine
        buf = b""
        ctx: dict = {}
        try:
            while not self._stop.is_set():
                eng.wait_clear(direction, self._stop)
                if self._stop.is_set():
                    return
                try:
                    data = src.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not data:
                    # peer half-closed: flush any partial tail, then
                    # propagate the FIN so the other side sees EOF too
                    if buf:
                        self._send(dst, buf, direction)
                    try:
                        dst.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass
                    return
                buf += data
                frames, buf = self._split_frames(buf)
                for f in frames:
                    self._relay_frame(f, direction, ctx, src, dst)
        except _Aborted:
            return
        finally:
            stash = ctx.pop("stash", None)
            if stash is not None:
                # a reorder armed on the link's last frame: never drop
                # bytes on a clean path — flush the held frame
                try:
                    self._send(dst, stash, direction)
                except OSError:
                    pass

    def _relay_frame(
        self, frame: bytes, direction: str, ctx: dict, src, dst
    ) -> None:
        if (
            direction == "c2s"
            and not binframes.peek_is_binary(frame)
            and frame[:9].lower() == b"hello shm"
        ):
            # the shm splice point (docs/resilience.md): shared-memory
            # segments cannot be routed through a TCP relay, so a
            # proxied link REFUSES the shm hello here — the client's
            # standard downgrade path renegotiates binary on this same
            # connection and every fault class below then applies to
            # all of its traffic.  Letting the hello through would
            # negotiate a side channel the proxy never sees.
            self.shm_downgrades += 1
            try:
                src.sendall(
                    b"err bad-request: shm not routable through a "
                    b"proxied link\n"
                )
            except OSError:
                pass
            return
        eng = self.engine
        shot = eng.take_one_shot(direction)
        if shot is not None:
            kind = shot["kind"]
            if kind == "dup":
                self._count_fault("dup")
                self._send(dst, frame, direction)
                self._send(dst, frame, direction)
                return
            if kind == "reorder":
                self._count_fault("reorder")
                ctx["stash"] = frame
                return
            if kind == "truncate_rst":
                # cut strictly mid-frame (never 0, never the full
                # frame incl. newline), then abort both legs: the
                # peer sees a torn payload and a reset, exactly the
                # mid-b64 death the dedupe ledger must survive.  For
                # BINARY frames, cut="header"/"payload" aims the tear
                # inside the 24-byte fixed header or past it — the two
                # torn-read shapes a length-prefixed reader must
                # survive (mid-header: the length never arrives;
                # mid-payload: the length promised more than EOF
                # delivered).
                cut = shot.get("cut", "frame")
                is_bin = binframes.peek_is_binary(frame)
                hdr = binframes.HEADER_SIZE
                if is_bin and cut == "header" and len(frame) > 2:
                    hi = min(hdr, len(frame)) - 1
                    keep = max(1, min(hi, int(hdr * shot["keep_frac"])))
                elif is_bin and cut == "payload" and len(frame) > hdr + 1:
                    body = len(frame) - hdr
                    keep = hdr + max(
                        1, min(body - 1, int(body * shot["keep_frac"]))
                    )
                else:
                    keep = max(
                        1, int((len(frame) - 1) * shot["keep_frac"])
                    )
                self._count_fault("truncate_rst")
                try:
                    dst.sendall(frame[:keep])
                except OSError:
                    pass
                self._abort(src, dst)
                raise _Aborted()
        d = eng.delay_s(direction)
        if d > 0:
            self._count_fault("delay_frame")
            time.sleep(d)
        stash = ctx.pop("stash", None)
        self._send(dst, frame, direction)
        if stash is not None:
            self._send(dst, stash, direction)

    def _send(self, dst, payload: bytes, direction: str) -> None:
        eng = self.engine
        eng.wait_clear(direction, self._stop)
        if self._stop.is_set():
            raise _Aborted()
        rate = eng.drip_rate(direction)
        if rate is None:
            dst.sendall(payload)
            return
        self._count_fault("drip_frame")
        slice_bytes = 1 << 10
        for i in range(0, len(payload), slice_bytes):
            chunk = payload[i: i + slice_bytes]
            dst.sendall(chunk)
            time.sleep(len(chunk) / rate)
            if self._stop.is_set():
                raise _Aborted()

    @staticmethod
    def _abort(*socks) -> None:
        for s in socks:
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER, _LINGER_RST)
            except OSError:
                pass
            try:
                # SHUT_RD first: a sibling pump blocked in recv() on
                # this fd holds a kernel reference, and close() alone
                # would DEFER the linger-0 RST until that recv returns
                # — i.e. forever (the peer would see a silent stall,
                # not a reset).  SHUT_RD wakes the reader without
                # sending a FIN, so the close below really aborts.
                s.shutdown(socket.SHUT_RD)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class ProxiedServer:
    """A ShardServer façade advertising its proxy's address.

    The elastic drivers publish shard addresses by reading
    ``(srv.host, srv.port)`` off whatever ``_build_shard`` returned —
    wrapping the server here is therefore the ONE splice that routes
    every consumer (worker clients, migration data plane, replication
    heartbeats, psctl) through the mesh.  Lifecycle calls fan out to
    both halves: ``stop()`` takes the proxy down WITH the server, so
    ``kill_shard`` kills the whole front door.  Everything else
    delegates to the real server.
    """

    def __init__(self, server, proxy: ChaosProxy):
        self._server = server
        self.proxy = proxy

    @property
    def host(self) -> str:
        return self.proxy.host

    @property
    def port(self) -> int:
        return self.proxy.port

    @property
    def running(self) -> bool:
        return self._server.running

    def stop(self) -> None:
        self.proxy.stop()
        self._server.stop()

    def __getattr__(self, name):
        return getattr(self._server, name)


__all__ = ["ChaosProxy", "ProxiedServer", "DIRECTIONS"]
